#!/bin/bash
cd /root/repo
LOG=/root/repo/studies_r05d.log
echo "--- stage: /opt/venv/bin/python examples/deceptive_valley_novelty.py 400 512 2 0.55" >> "$LOG"
flock /root/repo/.evidence.lock /opt/venv/bin/python examples/deceptive_valley_novelty.py 400 512 2 0.55 >> "$LOG" 2>&1
echo "exit $? $(date -u +%FT%TZ)" >> "$LOG"
