#!/bin/bash
# Round-5 session-3 serialized CPU study queue.  Every stage holds the
# evidence flock so the TPU watcher defers its on-chip sequence instead
# of contending for the single host core (and vice versa).
set -u
cd /root/repo
LOCK=/root/repo/.evidence.lock
LOG=/root/repo/studies_r05d.log
stage() {
  echo "--- stage: $*" >> "$LOG"
  flock "$LOCK" "$@" >> "$LOG" 2>&1
  echo "exit $? $(date -u +%FT%TZ)" >> "$LOG"
}
stage /opt/venv/bin/python examples/deceptive_valley_novelty.py 120 512 2
stage /opt/venv/bin/python examples/halfcheetah_pop1k.py 40 1024 3
stage /opt/venv/bin/python examples/halfcheetah_pop1k.py 40 1024 4
echo "queue done $(date -u +%FT%TZ)" >> "$LOG"
