"""obs_norm probe-count A/B: 1 vs 4 center episodes per generation.

Round-4 verdict weak #5: the device path's running obs stats come solely
from center-policy probe episodes (default 1/generation,
`EngineConfig.obs_probe_episodes`) — the one obs_norm default with no
A/B behind it.  Fixed generation budget on Humanoid2D; more probe
episodes converge the stats faster (and track the population's
distribution better through the center's neighborhood) at the cost of
extra probe FLOPs.  Compared at end-of-budget final/last-10 mean (the
round-4 lesson: obs_norm comparisons at end-of-budget, not AUC).

Run:  python examples/obsnorm_probe_ab.py [gens] [pop] [seeds]
"""

import json
import sys
import time

import numpy as np


def main():
    gens = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    pop = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    n_seeds = int(sys.argv[3]) if len(sys.argv) > 3 else 2

    from estorch_tpu import configs
    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(8)
    enable_compilation_cache()

    for seed in range(n_seeds):
        for probes in (1, 4):
            t0 = time.perf_counter()
            es = configs.humanoid2d_device(
                population_size=pop, seed=seed,
                obs_probe_episodes=probes,
            )
            es.train(gens, verbose=False)
            means = [r["reward_mean"] for r in es.history]
            ev = es.evaluate_policy(n_episodes=16, seed=55)
            print(json.dumps({
                "arm": f"probe{probes}", "seed": seed, "gens": gens,
                "pop": pop,
                "final_mean": round(means[-1], 1),
                "last10_mean": round(float(np.mean(means[-10:])), 1),
                "auc_mean": round(float(np.mean(means)), 1),
                "heldout_mean_16ep": round(ev["mean"], 1),
                "obs_count": float(es.state.obs_stats[0]),
                "wall_s": round(time.perf_counter() - t0, 1),
            }), flush=True)


if __name__ == "__main__":
    main()
