"""Recurrent ES: solve a memory task no feedforward policy can.

RecallEnv shows a ±1 signal ONLY before the first step; every step's
reward is action·signal.  A memoryless policy earns ~1 per episode in
expectation (the one step where it can see the signal); a recurrent
policy that latches the signal into its GRU carry earns ~horizon.

The hidden carry is threaded through the compiled rollout scan by the
framework (envs/rollout.py) — the reference's user-owned rollout loop
(SURVEY.md §3.3) has no equivalent machinery, torch users thread hidden
state by hand.

Run:  python examples/recurrent_memory.py
"""

import optax

from estorch_tpu import ES, JaxAgent, RecurrentPolicy
from estorch_tpu.envs import RecallEnv


def main():
    es = ES(
        policy=RecurrentPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=256,
        sigma=0.1,
        policy_kwargs={"action_dim": 1, "hidden": (8,), "gru_size": 8,
                       "discrete": False},
        agent_kwargs={"env": RecallEnv(), "horizon": 16},
        optimizer_kwargs={"learning_rate": 5e-2},
        seed=0,
    )
    es.train(80, verbose=True)
    print("center policy:", es.evaluate_policy(n_episodes=64))
    print("ceiling = horizon = 16; memoryless cap ≈ 1")


if __name__ == "__main__":
    main()
