"""Partially observable locomotion: does memory pay when the velocity
channels go dark?

`PositionOnly(Walker2D())` zeros every rate channel (torso velocity,
spin, joint rates).  Standing still is statically achievable blind (the
alive bonus rewards it), so the discriminating metric is forward
DISPLACEMENT — walking needs the rate feedback a memoryless policy
cannot see and a recurrent one can estimate from consecutive positions.

Run:  python examples/pomdp_locomotion.py [gens] [pop]
"""

import sys

import numpy as np


def run(recurrent: bool, seed: int, gens: int, pop: int):
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy, RecurrentPolicy
    from estorch_tpu.envs import PositionOnly, Walker2D
    from estorch_tpu.utils import force_cpu_backend

    force_cpu_backend(8)
    if recurrent:
        policy, pk = RecurrentPolicy, {
            "action_dim": 6, "hidden": (64,), "gru_size": 32,
            "discrete": False,
        }
    else:
        policy, pk = MLPPolicy, {
            "action_dim": 6, "hidden": (64, 64), "discrete": False,
        }
    es = ES(
        policy=policy, agent=JaxAgent, optimizer=optax.adam,
        population_size=pop, sigma=0.05, policy_kwargs=pk,
        agent_kwargs={"env": PositionOnly(Walker2D()), "horizon": 200},
        optimizer_kwargs={"learning_rate": 2e-2}, seed=seed,
    )
    es.train(gens, verbose=False)
    # displacement of the center policy: mean final BC x over held-out
    # episodes (the locomotion BC is the torso's final (x, y))
    ev = es.evaluate_policy(n_episodes=16, seed=99, return_details=True)
    return {
        "final_mean": es.history[-1]["reward_mean"],
        "best": es.best_reward,
        "center_disp_x": float(ev["bc"][:, 0].mean()),
    }


def main():
    gens = int(sys.argv[1]) if len(sys.argv) > 1 else 60
    pop = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    for seed in (0, 1):
        for rec in (True, False):
            r = run(rec, seed, gens, pop)
            name = "recurrent" if rec else "memoryless"
            print(f"seed {seed} {name:10s} final_mean {r['final_mean']:7.1f}"
                  f"  best {r['best']:7.1f}"
                  f"  center displacement {r['center_disp_x']:6.2f} m",
                  flush=True)


if __name__ == "__main__":
    main()
