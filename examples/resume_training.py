"""Checkpoint, crash, resume — the full persistence recipe.

The reference has no checkpointing; users torch.save the policy and lose
optimizer moments, RNG position, and the novelty archive.  estorch_tpu
resumes bit-exactly: this script trains with periodic checkpoints, then
rebuilds the object from scratch (as a new process would) and continues —
the resumed trajectory is identical to an uninterrupted run.

Run: python examples/resume_training.py
"""

import numpy as np
import optax

from estorch_tpu import NSRA_ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole
from estorch_tpu.utils import (
    JsonlWriter,
    MultiWriter,
    PeriodicCheckpointer,
    restore_checkpoint,
)


def build():
    return NSRA_ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=32,
        sigma=0.1,
        seed=11,
        meta_population_size=2,
        k=5,
        weight=0.8,
        policy_kwargs={"action_dim": 2, "hidden": (16,)},
        agent_kwargs={"env": CartPole(), "horizon": 100},
        optimizer_kwargs={"learning_rate": 2e-2},
    )


def main(workdir: str = "/tmp/estorch_tpu_resume_demo"):
    # phase 1: train with checkpoints every 2 generations
    es = build()
    ck = PeriodicCheckpointer(es, f"{workdir}/ckpts", every=2, max_to_keep=2)
    log = MultiWriter([JsonlWriter(f"{workdir}/log.jsonl")], echo=True)
    es.train(6, log_fn=lambda r: (log(r), ck.on_record(r)))

    # phase 2: simulate a crash — rebuild from nothing and restore
    es2 = build()
    restore_checkpoint(es2, ck.latest())
    print(f"\nrestored at generation {es2.generation} "
          f"(archive {len(es2.archive)}, w {es2.weight:.2f})")
    es2.train(4, log_fn=log)

    print(f"\nfinal best: {es2.best_reward:.1f}; "
          f"history persisted to {workdir}/log.jsonl")
    return es2


if __name__ == "__main__":
    main()
