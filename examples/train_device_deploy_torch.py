"""Train on the device path, deploy the policy in torch.

The migration story for a reference user: train with the compiled TPU
engine, then carry the learned weights back into a ``torch.nn.Module`` (the
deployment format the reference ecosystem expects) and validate it on a
gym-style rollout of the same env via the adapter — all weights, no
retraining.

Run: python examples/train_device_deploy_torch.py
"""

import numpy as np
import optax
import torch

from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole
from estorch_tpu.envs.gym_adapter import GymFromJax
from estorch_tpu.models.torch_adapter import flax_mlp_to_torch


def main():
    # 1) train TPU-native
    es = ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=64,
        sigma=0.1,
        policy_kwargs={"action_dim": 2, "hidden": (32, 32)},
        agent_kwargs={"env": CartPole()},
        optimizer_kwargs={"learning_rate": 3e-2},
    )
    es.train(n_steps=15)

    # 2) deploy to torch
    torch_policy = torch.nn.Sequential(
        torch.nn.Linear(4, 32), torch.nn.Tanh(),
        torch.nn.Linear(32, 32), torch.nn.Tanh(),
        torch.nn.Linear(32, 2),
    )
    flax_mlp_to_torch(es.best_policy, torch_policy)

    # 3) validate: the torch policy on a gym-style rollout of the same env
    env = GymFromJax(CartPole(), seed=123)
    obs, _ = env.reset(seed=7)
    total, done = 0.0, False
    with torch.no_grad():
        while not done:
            action = int(torch_policy(torch.from_numpy(obs)).argmax())
            obs, r, term, trunc, _ = env.step(action)
            total += r
            done = term or trunc
    print(f"\ndevice-trained policy, torch deployment: episode reward {total:.0f}")
    return total


if __name__ == "__main__":
    main()
