"""The Atari workflow end-to-end without ALE: NatureCNN + preprocessing.

BASELINE config 5's machinery on the bundled C++ pixel pong: 84×84 frames
through the full ALE-standard preprocessing stack (4-frame stacking →
NatureCNN's designed 84×84×4 input, action repeat, sticky actions —
envs/atari_wrappers.py), population envs stepped by native threads while
the device runs one batched conv forward per env step, first-to-21
matches.  Swap ``env_name`` for a real ALE id the moment ``ale_py`` is
installable — nothing else changes.

Sized for an accelerator (population conv forwards are the whole cost);
on CPU pass smaller overrides, e.g. main(population_size=16, horizon=60).

Run: python examples/atari_style_pong.py
"""

import optax

from estorch_tpu import ES, NatureCNN, PooledAgent


def main(population_size=64, horizon=400, n_steps=3):
    es = ES(
        policy=NatureCNN,
        agent=PooledAgent,
        optimizer=optax.adam,
        population_size=population_size,
        sigma=0.02,
        policy_kwargs={"action_dim": 3, "use_vbn": True},
        agent_kwargs={"env_name": "pong84", "horizon": horizon,
                      "frame_stack": 4, "action_repeat": 2,
                      "sticky_prob": 0.25},
        optimizer_kwargs={"learning_rate": 1e-2},
        table_size=1 << 22,
    )
    print(f"policy input {es.engine.pool.obs_shape}, "
          f"params {es._spec.dim:,}")
    es.train(n_steps=n_steps)
    print(f"\nbest reward: {es.best_reward:.1f}")
    return es


if __name__ == "__main__":
    main()
