"""humanoid2d_pop10k at its STATED population — a real training run.

Round-4 verdict next #3: the shipped north-star config had only ever run
2-3-generation bench rows at population 10240; its training evidence was
pop-2048.  This trains the exact shipped recipe (pop 10240, 256×256
policy, low_rank=1, obs_norm, eval_chunk 1024, horizon 400) for a
bounded number of generations on the 8-virtual-device CPU mesh and
records the learning curve, per-generation wall time, and peak RSS —
retiring the memory/throughput risk (the eval_chunk sizing was a bet,
bench.py:107-109) before chip day.  CPU-relative numbers only; the MXU
turns the per-generation minutes into seconds.

Run:  python examples/pop10k_training.py [gens] [seed]
"""

import json
import resource
import sys
import time


def main():
    gens = int(sys.argv[1]) if len(sys.argv) > 1 else 12
    seed = int(sys.argv[2]) if len(sys.argv) > 2 else 0

    from estorch_tpu import configs
    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(8)
    enable_compilation_cache()

    es = configs.humanoid2d_pop10k(seed=seed)

    t0 = time.perf_counter()
    last = [t0]
    total_steps = 0

    def log(rec):
        nonlocal total_steps
        now = time.perf_counter()
        total_steps += rec["env_steps"]
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
        print(json.dumps({
            "gen": rec["generation"],
            "reward_mean": round(rec["reward_mean"], 1),
            "reward_max": round(rec["reward_max"], 1),
            "env_steps": rec["env_steps"],
            "gen_wall_s": round(now - last[0], 1),
            "elapsed_s": round(now - t0, 1),
            "peak_rss_gb": round(rss, 2),
        }), flush=True)
        last[0] = now

    es.train(gens, log_fn=log, verbose=False)

    ev = es.evaluate_policy(n_episodes=32, seed=1, return_details=True)
    g = ev.get("gait", {})
    print(json.dumps({
        "summary": "humanoid2d_pop10k STATED SCALE (pop 10240, low_rank=1, "
                   "obs_norm, 256x256, h400)",
        "gens": gens, "seed": seed,
        "first_reward_mean": round(es.history[0]["reward_mean"], 1),
        "final_reward_mean": round(es.history[-1]["reward_mean"], 1),
        "best": round(es.best_reward, 1),
        "heldout_mean_32ep": round(ev["mean"], 1),
        "heldout_std": round(ev["std"], 1),
        "fwd_vel_mps": round(float(g["forward_velocity_mps"].mean()), 3)
        if g else None,
        "upright_frac": round(float(g["upright_fraction"].mean()), 3)
        if g else None,
        "total_env_steps": total_steps,
        "wall_s": round(time.perf_counter() - t0, 1),
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2),
    }), flush=True)


if __name__ == "__main__":
    main()
