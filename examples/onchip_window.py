"""Bounded on-chip training run for a TPU recovery window.

Round-4 verdict, next-step 1: when the axon tunnel finally serves compute,
a short window must yield TRAINING evidence, not just microbenchmarks.
This script trains the shipped north-star config (`humanoid2d_pop10k`)
under a hard wall-clock budget, checkpointing every few generations and
logging one JSONL record per generation, so even a window that closes
mid-run leaves a resumable checkpoint and a learning curve.

Use:  python examples/onchip_window.py [--budget-s 2700] [--config NAME]
          [--workdir DIR] [--resume]

Safe to re-fire: --resume restores the latest checkpoint in the workdir
(if any) and continues, so the tunnel watcher can launch it on every
recovery without clobbering earlier progress.
"""

from __future__ import annotations

import argparse
import json
import os
import time

from estorch_tpu import configs
from estorch_tpu.utils import (JsonlWriter, MultiWriter, PeriodicCheckpointer,
                               enable_compilation_cache, restore_checkpoint)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--budget-s", type=float, default=2700.0,
                   help="wall-clock budget; stops after the first generation "
                        "that crosses it (default 45 min)")
    p.add_argument("--config", default="humanoid2d_pop10k",
                   choices=sorted(configs.CONFIGS))
    p.add_argument("--workdir", default="runs/onchip_window")
    p.add_argument("--max-gens", type=int, default=10_000)
    p.add_argument("--ckpt-every", type=int, default=5)
    p.add_argument("--resume", action="store_true")
    args = p.parse_args(argv)

    enable_compilation_cache()
    os.makedirs(args.workdir, exist_ok=True)
    # heartbeat in the workdir (unless a supervisor already set a path):
    # the watcher / doctor --run-dir read it when this run stops answering
    os.environ.setdefault(
        "ESTORCH_OBS_HEARTBEAT",
        os.path.join(args.workdir, "heartbeat.json"))
    es = configs.CONFIGS[args.config]()
    # run manifest beside the curve: which config/jax/devices/sha this was
    es.write_manifest(os.path.join(args.workdir, "manifest.json"),
                      extra={"example_config": args.config})
    ck = PeriodicCheckpointer(es, os.path.join(args.workdir, "ckpts"),
                              every=args.ckpt_every, max_to_keep=3)
    if args.resume and ck.latest():
        restore_checkpoint(es, ck.latest())
        print(f"resumed at generation {es.generation}")
    log = MultiWriter(
        [JsonlWriter(os.path.join(args.workdir, "curve.jsonl"))], echo=True)

    platform = es.mesh.devices.flat[0].platform
    t0 = time.perf_counter()
    gens = 0
    while (time.perf_counter() - t0 < args.budget_s
           and gens < args.max_gens):
        es.train(1, verbose=False,
                 log_fn=lambda r: (log(r), ck.on_record(r)))
        gens += 1
    ck.save(es.generation)
    ck.close()
    dt = time.perf_counter() - t0
    summary = {
        "config": args.config, "platform": platform, "generations": gens,
        "final_generation": es.generation, "wall_s": round(dt, 1),
        "best_reward": float(es.best_reward),
        "env_steps": int(sum(r.get("env_steps", 0) for r in es.history)),
    }
    with open(os.path.join(args.workdir, "summary.json"), "w") as f:
        json.dump(summary, f)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
