"""Device-native locomotion: planar swimmer, physics inside the program.

The MuJoCo-class path without MuJoCo: `envs/locomotion.py` is a pure-JAX
articulated-chain simulator (spring-damper joints, anisotropic fluid drag,
semi-implicit Euler), so env stepping happens INSIDE the compiled
generation program — no host round-trips at all, the execution model the
reference's Gym-loop architecture can't reach (SURVEY.md §3.3).

The swimmer learns a ~1 m/s undulating gait in ~30 generations.

Run: python examples/locomotion_swimmer.py
"""

import optax

from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import Swimmer2D


def main():
    env = Swimmer2D()
    es = ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=512,
        sigma=0.08,
        policy_kwargs={"action_dim": env.action_dim, "hidden": (32, 32),
                       "discrete": False, "action_scale": 1.0},
        agent_kwargs={"env": env, "horizon": 300},
        optimizer_kwargs={"learning_rate": 3e-2},
    )
    es.train(n_steps=30)
    print(f"\nbest reward: {es.best_reward:.1f}")
    return es


if __name__ == "__main__":
    main()
