"""Serialized on-chip A/B driver for a recovered-tunnel session.

bench.py --stage-ab gives each row a 600 s subprocess timeout — calibrated
for the documented 20-40 s XLA compile.  The round-5 recovered axon tunnel
compiles the fused generation program in ~4-6 MINUTES (measured 03:43-03:52
UTC: two ~500 MB executables for the SMALL config), so a cold --stage-ab
would time out row after row and record nulls.  This driver runs the same
AB_MATRIX rows (same labels, same alias logic is unnecessary on-chip since
nothing coerces) one subprocess at a time with a compile-sized timeout,
appending each labeled JSON line to the output file as it lands.  Every
completed row also leaves its executables in the persistent compile cache,
so the driver's end-of-round `bench.py` run hits a warm cache and its
600 s timeouts are comfortable.

Use:  python examples/ab_onchip_driver.py [--out bench_ab_tpu.jsonl]
          [--timeout-s 1500] [--skip-done] [--abort-after 2]

--skip-done makes the driver resumable across tunnel wedges: rows whose
label already has a non-null "rate" in the output file are not re-run.
--abort-after N exits after N CONSECUTIVE failed rows: when the tunnel
wedges mid-matrix every remaining row would burn its full timeout to
record a null, so the driver hands control back to the cheap probing
loop (examples/tpu_watch.py) instead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import bench  # noqa: E402  (repo-root bench.py: AB_MATRIX + stage protocol)

# extras configs the headline run needs warm, measured with the same
# protocol so they double as evidence rows.  Only configs the AB_MATRIX
# does NOT already cover: standard-mode pop10k is absent there, and the
# headline's big_policy point runs gens=3 (the matrix BIG rows use the
# default 5 — a different program count only in wall-clock, but a
# distinct cfg dict, hence a distinct row).  The headline's locomotion
# point (LOCO bf16 gens=3) is exactly AB_MATRIX's "loco/standard/bf16" —
# not duplicated here.
EXTRA_ROWS = [
    ("extras/big/standard/bf16", bench.BIG, {"dtype": "bfloat16", "gens": 3}),
    ("extras/pop10k/standard/bf16", bench.POP10K,
     {"dtype": "bfloat16", "gens": 3}),
]


def done_labels(path):
    done = set()
    if os.path.exists(path):
        with open(path) as f:
            for ln in f:
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if rec.get("rate") is not None:
                    done.add(rec.get("label"))
    return done


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="bench_ab_tpu.jsonl")
    p.add_argument("--timeout-s", type=float, default=1500.0)
    p.add_argument("--skip-done", action="store_true")
    p.add_argument("--abort-after", type=int, default=2,
                   help="exit after this many consecutive failed rows "
                        "(0 = never abort)")
    args = p.parse_args(argv)

    # single-core mutual exclusion: a manual invocation must respect the
    # same evidence flock the watcher/study queue serialize through
    # (bench.acquire_evidence_lock no-ops when the watcher spawned us
    # holding it, via EVIDENCE_LOCK_HELD)
    print("waiting for evidence lock…", file=sys.stderr)
    _lock_fd = bench.acquire_evidence_lock()  # held until process exit

    skip = done_labels(args.out) if args.skip_done else set()
    rows = list(bench.AB_MATRIX) + EXTRA_ROWS
    consec_fail = 0
    for label, base, over in rows:
        if label in skip:
            print(f"skip (done): {label}", file=sys.stderr)
            continue
        cfg = {**base, **over}
        t0 = time.monotonic()  # elapsed measure: wall clock steps (R09)
        out = bench.run_stage_detailed(cfg, timeout_s=args.timeout_s)
        line = {"label": label, **out,
                "wall_s": round(time.monotonic() - t0, 1)}
        with open(args.out, "a") as f:
            f.write(json.dumps(line) + "\n")
        print(json.dumps({k: line[k] for k in ("label", "rate", "wall_s")
                          if k in line}), file=sys.stderr, flush=True)
        consec_fail = consec_fail + 1 if out.get("rate") is None else 0
        if args.abort_after and consec_fail >= args.abort_after:
            print(f"abort: {consec_fail} consecutive failed rows — tunnel "
                  f"presumed wedged; re-run with --skip-done on recovery",
                  file=sys.stderr)
            sys.exit(3)


if __name__ == "__main__":
    main()
