"""Long-budget capstone driver for any shipped recipe (CPU mesh).

Trains `configs.<name>` for a bounded generation budget with the full
evidence protocol the round-4/5 capstones used: a JSONL learning curve,
held-out evaluations (32 episodes, gait metrics included) every
`eval_every` generations, and periodic checkpoints so a killed run
keeps its endgame (the round-5 Humanoid-v5 lesson).

Run:  python examples/capstone_run.py [config] [gens] [eval_every] [seed]
      defaults: humanoid2d_device 1000 100 0
"""

import json
import resource
import sys
import time


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "humanoid2d_device"
    gens = int(sys.argv[2]) if len(sys.argv) > 2 else 1000
    eval_every = int(sys.argv[3]) if len(sys.argv) > 3 else 100
    seed = int(sys.argv[4]) if len(sys.argv) > 4 else 0

    from estorch_tpu import configs
    from estorch_tpu.utils import (PeriodicCheckpointer,
                                   enable_compilation_cache,
                                   force_cpu_backend)

    force_cpu_backend(8)
    enable_compilation_cache()

    es = configs.CONFIGS[name](seed=seed)
    ck = PeriodicCheckpointer(
        es, f"runs/capstone_{name}_s{seed}/ckpts", every=eval_every,
        max_to_keep=2)

    t0 = time.perf_counter()

    def log(rec):
        if rec["generation"] % 10:
            return
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
        print(json.dumps({
            "gen": rec["generation"],
            "reward_mean": round(rec["reward_mean"], 1),
            "reward_max": round(rec["reward_max"], 1),
            "elapsed_s": round(time.perf_counter() - t0, 1),
            "peak_rss_gb": round(rss, 2),
        }), flush=True)

    done = 0
    while done < gens:
        step = min(eval_every, gens - done)
        es.train(step, log_fn=lambda r: (log(r), ck.on_record(r)),
                 verbose=False)
        done += step
        ev = es.evaluate_policy(n_episodes=32, seed=1, return_details=True)
        g = ev.get("gait", {})  # per-episode arrays → report episode means
        print(json.dumps({
            "heldout_at_gen": es.generation,
            "mean": round(float(ev["mean"]), 1),
            "std": round(float(ev["std"]), 1),
            **{k: round(float(v.mean()), 3) for k, v in g.items()},
        }), flush=True)
    ck.save(es.generation)
    ck.close()
    print(json.dumps({
        "summary": f"capstone {name} seed {seed}",
        "gens": gens,
        "best_reward": round(float(es.best_reward), 1),
        "wall_s": round(time.perf_counter() - t0, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
