"""Reference-parity usage: torch policy + Gym agent + torch optimizer.

This is the reference's README example shape (SURVEY.md Appendix A) running
UNCHANGED on estorch_tpu's host backend: a ``torch.nn.Module`` policy, a
duck-typed Agent whose ``rollout(policy)`` steps a gymnasium env in Python,
``torch.optim.Adam``, and ``train(n_steps, n_proc)`` fanning rollouts over
worker threads (the reference used MPI processes).

Run: python examples/torch_host_es.py
"""

import gymnasium as gym
import numpy as np
import torch

from estorch_tpu import ES


class Policy(torch.nn.Module):
    def __init__(self, n_input=4, n_hidden=32, n_output=2):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(n_input, n_hidden),
            torch.nn.Tanh(),
            torch.nn.Linear(n_hidden, n_output),
        )

    def forward(self, x):
        return self.net(x)


class Agent:
    """The reference's rollout contract: episode return from a Gym env."""

    def __init__(self):
        self.env = gym.make("CartPole-v1")

    def rollout(self, policy, render=False):
        obs, _ = self.env.reset()
        total, steps, done = 0.0, 0, False
        with torch.no_grad():
            while not done:
                action = int(
                    policy(torch.from_numpy(np.asarray(obs, np.float32))).argmax()
                )
                obs, reward, term, trunc, _ = self.env.step(action)
                total += float(reward)
                steps += 1
                done = term or trunc
        self.last_episode_steps = steps
        return total


def main():
    es = ES(
        policy=Policy,
        agent=Agent,
        optimizer=torch.optim.Adam,
        population_size=64,
        sigma=0.1,
        optimizer_kwargs={"lr": 3e-2},
    )
    es.train(n_steps=10, n_proc=8)
    print(f"\nbest reward: {es.best_reward}")
    return es


if __name__ == "__main__":
    main()
