"""Does observation normalization move the Humanoid2D plateau?

Round 3 left the capstone at a standing-plus-drift population (600 gens,
mean 158.6, best 422 by gen 175) and obs_norm untried on it.  Walker2D's
obs_norm null came with an explanation — no variance spread to fix — so
step 0 here MEASURES Humanoid2D's per-dimension observation spread to
predict the outcome, then runs the A/B at a fixed budget: same recipe,
same seeds, only ``obs_norm`` differs.

Run:  python examples/obsnorm_humanoid.py [gens] [pop] [--spread-only]
"""

import json
import sys
import time

import numpy as np


def measure_spread(n_episodes=4, horizon=400):
    """Per-dim obs variance of a random policy on Humanoid2D: the scale
    spread obs_norm exists to fix (Walker2D measured ~flat → null)."""
    import jax
    import jax.numpy as jnp

    from estorch_tpu.envs import Humanoid2D

    env = Humanoid2D()

    def episode(key):
        def step(carry, _):
            state, k = carry
            k, ka = jax.random.split(k)
            act = jax.random.uniform(
                ka, (env.action_dim,), minval=-1.0, maxval=1.0
            )
            state, obs, _, _ = env.step(state, act)
            return (state, k), obs

        k0, k1 = jax.random.split(key)
        state, obs0 = env.reset(k0)
        _, obs = jax.lax.scan(step, (state, k1), None, length=horizon)
        return jnp.concatenate([obs0[None], obs], axis=0)

    keys = jax.random.split(jax.random.PRNGKey(0), n_episodes)
    obs = np.asarray(jax.vmap(episode)(keys)).reshape(-1, int(env.obs_dim))
    var = obs.var(axis=0)
    mean = obs.mean(axis=0)
    return {
        "obs_dim": int(env.obs_dim),
        "var_min": float(var.min()),
        "var_max": float(var.max()),
        "var_spread": float(var.max() / max(var.min(), 1e-12)),
        "n_dims_var_gt_1": int((var > 1.0).sum()),
        "n_dims_var_lt_0.1": int((var < 0.1).sum()),
        "max_abs_mean_over_std": float(
            (np.abs(mean) / np.sqrt(np.maximum(var, 1e-12))).max()
        ),
    }


def run(obs_norm: bool, seed: int, gens: int, pop: int):
    from estorch_tpu import configs

    es = configs.humanoid2d_device(
        population_size=pop, seed=seed, obs_norm=obs_norm,
    )
    t0 = time.perf_counter()
    es.train(gens, verbose=False)
    means = [r["reward_mean"] for r in es.history]
    return {
        "final_mean": round(means[-1], 1),
        "best": round(es.best_reward, 1),
        "auc": round(float(np.mean(means)), 1),
        "last10_mean": round(float(np.mean(means[-10:])), 1),
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def main():
    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(8)
    enable_compilation_cache()

    print(json.dumps({"spread": measure_spread()}), flush=True)
    if "--spread-only" in sys.argv:
        return
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    gens = int(args[0]) if args else 80
    pop = int(args[1]) if len(args) > 1 else 512
    for seed in (0, 1):
        for flag in (True, False):
            r = run(flag, seed, gens, pop)
            print(json.dumps({"seed": seed, "obs_norm": flag, "gens": gens,
                              "pop": pop, **r}), flush=True)


if __name__ == "__main__":
    main()
