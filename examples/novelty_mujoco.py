"""BASELINE config 4 on real physics: NSR-ES on MuJoCo HalfCheetah.

The novelty family's end-to-end evidence so far is the deceptive
MountainCarContinuous; this runs NSR-ES — reward AND novelty, BC =
final x-position (Conti et al.'s locomotion characterization) — on real
MuJoCo through the pooled path, against a reward-only ES control at the
same budget, and checkpoints the archive mid-run to prove resume covers
the novelty state on this config.

Both arms share ONE hyperparameter dict (defined here, matching
configs.halfcheetah_nsres) so the A/B stays internally matched by
construction.

Run:  python examples/novelty_mujoco.py [gens] [pop] [seed]
"""

import json
import sys
import tempfile
import time


def shared_kw(pop, seed):
    """The config-4 recipe both arms share (mirrors halfcheetah_nsres)."""
    import optax

    from estorch_tpu import MLPPolicy, PooledAgent
    from estorch_tpu.parallel.mesh import single_device_mesh

    return dict(
        policy=MLPPolicy,
        agent=PooledAgent,
        optimizer=optax.adam,
        population_size=pop,
        sigma=0.02,
        seed=seed,
        policy_kwargs={"action_dim": 6, "hidden": (64, 64),
                       "discrete": False},
        agent_kwargs={
            "env_name": "gym:HalfCheetah-v5",
            "horizon": 1000,
            "env_kwargs": {
                "exclude_current_positions_from_observation": False},
            "bc_indices": (0,),
        },
        optimizer_kwargs={"learning_rate": 1e-2},
        weight_decay=0.005,
        mesh=single_device_mesh(),
    )


def close_pools(es):
    es.engine.pool.close()
    es.engine.center_pool.close()


def run_nsres(gens, pop, seed):
    from estorch_tpu import NSR_ES
    from estorch_tpu.utils import restore_checkpoint, save_checkpoint

    es = NSR_ES(k=10, meta_population_size=3, **shared_kw(pop, seed))
    t0 = time.perf_counter()

    def log(rec):
        print(json.dumps({
            "algo": "NSR_ES", "gen": rec["generation"],
            "reward_mean": round(rec["reward_mean"], 1),
            "reward_max": round(rec["reward_max"], 1),
            "novelty_mean": round(rec.get("novelty_mean", float("nan")), 3),
            "archive": len(es.archive),
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }), flush=True)

    half = max(1, gens // 2)
    es.train(half, log_fn=log, verbose=False)

    # archive checkpoint/resume on THIS config (BASELINE config 4 asks for
    # a checkpointed archive): round-trip mid-run, then continue
    from estorch_tpu import NSR_ES as _NSR

    with tempfile.TemporaryDirectory() as d:
        save_checkpoint(es, d + "/ck")
        es2 = _NSR(k=10, meta_population_size=3, **shared_kw(pop, seed))
        try:
            restore_checkpoint(es2, d + "/ck")
            assert len(es2.archive) == len(es.archive), "archive must resume"
            print(json.dumps(
                {"archive_checkpoint_roundtrip": len(es2.archive)}),
                flush=True)
        finally:
            close_pools(es2)

    es.train(gens - half, log_fn=log, verbose=False)

    # final-x spread across the meta-population: what novelty bought
    xs = []
    for m in range(len(es.meta_states)):
        det = es.evaluate_policy(n_episodes=4, meta_index=m,
                                 return_details=True)
        xs.append(float(det["bc"][:, 0].mean()))
    out = {
        "summary": f"NSR_ES halfcheetah pop-{pop}", "gens": gens,
        "seed": seed,
        "final_reward_mean": round(es.history[-1]["reward_mean"], 1),
        "best": round(es.best_reward, 1),
        "archive_size": len(es.archive),
        "meta_final_x": [round(x, 2) for x in xs],
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    close_pools(es)
    return out


def run_es_control(gens, pop, seed):
    """Reward-only control: the SAME shared_kw, novelty machinery removed."""
    from estorch_tpu import ES

    es = ES(**shared_kw(pop, seed))
    t0 = time.perf_counter()

    def log(rec):
        print(json.dumps({
            "algo": "ES", "gen": rec["generation"],
            "reward_mean": round(rec["reward_mean"], 1),
            "reward_max": round(rec["reward_max"], 1),
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }), flush=True)

    es.train(gens, log_fn=log, verbose=False)
    det = es.evaluate_policy(n_episodes=4, return_details=True)
    out = {
        "summary": f"ES control halfcheetah pop-{pop}", "gens": gens,
        "seed": seed,
        "final_reward_mean": round(es.history[-1]["reward_mean"], 1),
        "best": round(es.best_reward, 1),
        "final_x": round(float(det["bc"][:, 0].mean()), 2),
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    close_pools(es)
    return out


def main():
    gens = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    pop = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 0

    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(1)
    enable_compilation_cache()

    print(json.dumps(run_nsres(gens, pop, seed)), flush=True)
    print(json.dumps(run_es_control(gens, pop, seed)), flush=True)


if __name__ == "__main__":
    main()
