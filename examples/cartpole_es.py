"""CartPole ES — the reference's README example, TPU-native.

Reference equivalent (estorch README, upstream — SURVEY.md §2 item 9):
a 2-layer MLP policy + Gym CartPole agent, ``ES(...).train(n_steps)``.
Here the env itself runs on the accelerator inside the rollout scan, so a
whole generation is one XLA program.  BASELINE config 1.

Run: python examples/cartpole_es.py
"""

import optax

from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole


def main():
    es = ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=64,
        sigma=0.1,
        policy_kwargs={"action_dim": 2, "hidden": (32, 32)},
        agent_kwargs={"env": CartPole()},
        optimizer_kwargs={"learning_rate": 3e-2},
    )
    es.train(n_steps=20)
    print(f"\nbest reward: {es.best_reward}")
    return es


if __name__ == "__main__":
    main()
