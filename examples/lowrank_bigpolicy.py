"""Low-rank ES on a Humanoid-sized policy: hyperscale noise on one chip.

`low_rank=1` replaces every layer's dense Gaussian perturbation with a
rank-1 factor pair E = a·bᵀ (ops/lowrank.py — PAPERS.md "Evolution
Strategies at the Hyperscale"): for this 166k-param MLP the per-member
noise state drops from 166,673 to 1,946 floats (86×), which is what makes
population 10k+ with big policies fit a single chip's HBM — and measures
~5× faster per generation than full-rank even on CPU.

Run: python examples/lowrank_bigpolicy.py
"""

import optax

from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import SyntheticEnv


def main():
    env = SyntheticEnv()  # obs 376 / act 17 — Humanoid's interface shape
    es = ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=2048,
        sigma=0.05,
        policy_kwargs={"action_dim": env.action_dim, "hidden": (256, 256),
                       "discrete": False, "action_scale": 1.0},
        agent_kwargs={"env": env, "horizon": 100},
        optimizer_kwargs={"learning_rate": 1e-2},
        low_rank=1,
        eval_chunk=256,
    )
    print(f"param dim {es._spec.dim:,} -> member noise state "
          f"{es.engine.noise_dim:,} floats")
    es.train(n_steps=5)
    print(f"\nbest reward: {es.best_reward:.3f}")
    return es


if __name__ == "__main__":
    main()
