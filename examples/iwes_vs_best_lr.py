"""The IW-ES claim, completed: vanilla ES at its OWN best lr vs IW-ES.

The round-2 result (−25% env-steps to threshold) compared both at
lr 3e-3 — the small-step regime the reuse math requires (lr ≲ σ/√dim,
algo/iwes.py).  The open question an expert asks: does vanilla ES at
its own best lr beat IW-ES at its constrained lr on env-steps AND on
wall-clock?  This sweeps vanilla over a lr grid, picks the best by
median env-steps to the bar, and compares both currencies.

Run: python examples/iwes_vs_best_lr.py [--quick]
"""

import json
import sys
import time

import numpy as np
import optax

from estorch_tpu import ES, IW_ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole

SIGMA, GENS, WINDOW, POP = 0.1, 150, 2, 128
REUSE_LR = 3e-3  # the lr the reuse math constrains IW-ES to (σ/√dim)
VANILLA_GRID = (3e-3, 1e-2, 3e-2)
BAR = 450


def run(algo, lr, seed, gens):
    kw = dict(
        policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
        population_size=POP, sigma=SIGMA,
        policy_kwargs={"action_dim": 2, "hidden": (16, 16)},
        agent_kwargs={"env": CartPole()},
        optimizer_kwargs={"learning_rate": lr}, seed=seed,
    )
    es = (IW_ES(reuse_window=WINDOW, ess_min=0.3, **kw)
          if algo == "iwes" else ES(**kw))
    t0 = time.perf_counter()
    es.train(gens, verbose=False)
    wall = time.perf_counter() - t0
    steps, steps_at, wall_at = 0, None, None
    for r in es.history:
        steps += r["env_steps"]
        if steps_at is None and r["reward_mean"] >= BAR:
            steps_at = steps
            # wall-clock attribution: fraction of generations used
            wall_at = wall * (r["generation"] + 1 - es.history[0]["generation"]) / len(es.history)
    return {
        "steps_to_bar": steps_at,
        "wall_to_bar_s": round(wall_at, 1) if wall_at else None,
        "final_mean": round(es.history[-1]["reward_mean"], 1),
        "wall_s": round(wall, 1),
    }


def median_or_inf(vals):
    """Median with never-reached seeds counted as INFINITY, not dropped —
    dropping them would crown an lr that fails most seeds on the strength
    of its one lucky run."""
    return float(np.median([float("inf") if v is None else v for v in vals]))


def main():
    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(8)
    enable_compilation_cache()

    quick = "--quick" in sys.argv
    gens = 40 if quick else GENS
    seeds = (0,) if quick else (0, 1, 2)

    results = {}
    for lr in VANILLA_GRID:
        rows = [run("es", lr, s, gens) for s in seeds]
        results[lr] = rows
        print(json.dumps({"algo": "es", "lr": lr,
                          "rows": rows}), flush=True)
    best_lr = min(
        results,
        key=lambda lr: (
            median_or_inf([r["steps_to_bar"] for r in results[lr]]),
            -np.median([r["final_mean"] for r in results[lr]]),
        ),
    )

    iwes_rows = [run("iwes", REUSE_LR, s, gens) for s in seeds]
    print(json.dumps({"algo": "iwes", "lr": REUSE_LR,
                      "rows": iwes_rows}), flush=True)

    verdict = {
        "vanilla_best_lr": best_lr,
        "vanilla_median_steps_to_bar": median_or_inf(
            [r["steps_to_bar"] for r in results[best_lr]]),
        "vanilla_median_wall_to_bar_s": median_or_inf(
            [r["wall_to_bar_s"] for r in results[best_lr]]),
        "iwes_lr": REUSE_LR,
        "iwes_median_steps_to_bar": median_or_inf(
            [r["steps_to_bar"] for r in iwes_rows]),
        "iwes_median_wall_to_bar_s": median_or_inf(
            [r["wall_to_bar_s"] for r in iwes_rows]),
    }
    def winner(iwes_med, vanilla_med):
        # neither arm reached the bar → no evidence, no winner
        if np.isinf(iwes_med) and np.isinf(vanilla_med):
            return "none"
        return "iwes" if iwes_med < vanilla_med else "vanilla"

    verdict["env_steps_winner"] = winner(
        verdict["iwes_median_steps_to_bar"],
        verdict["vanilla_median_steps_to_bar"],
    )
    verdict["wall_clock_winner"] = winner(
        verdict["iwes_median_wall_to_bar_s"],
        verdict["vanilla_median_wall_to_bar_s"],
    )
    print(json.dumps({"verdict": verdict}), flush=True)


if __name__ == "__main__":
    main()
