"""IW-ES sample-efficiency study: same lr, fewer env-steps to the bar.

Runs vanilla ES and IW_ES (reuse_window=2) on CartPole in the small-step
regime and reports env-steps to reach mean-return thresholds.  Reuse
survives the ESS guard only when the per-generation center move is small
relative to the search distribution — the log-ratio spread is
d·ε ~ N(0, ‖Δθ/σ‖²), so with a coordinate-wise optimizer that means
lr ≲ σ/√dim (here: σ=0.1, dim=386 → lr ≈ 3e-3).  Outside that regime
IW_ES warns once and runs as vanilla ES (see algo/iwes.py).

Measured on the 8-virtual-device CPU mesh, 3 seeds (BENCHMARKS.md round 2):
IW-ES reaches mean return 450 in ~25% fewer env-steps (2.11M vs 2.80M)
and ends higher on every seed (489-494 vs 466-479), reusing in 99% of
generations.  The win is in ENV-STEPS — exactly what matters when the env
is the expensive side (robotics, simulators); the ratio/update overhead
stays on-device.

Run: python examples/iwes_sample_efficiency.py [--quick]
"""

import json
import sys
import time

import optax

from estorch_tpu import ES, IW_ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole

LR, SIGMA, GENS, WINDOW, POP = 3e-3, 0.1, 150, 2, 128
THRESHOLDS = (100, 300, 450)


def run(algo, seed, gens):
    kw = dict(
        policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
        population_size=POP, sigma=SIGMA,
        policy_kwargs={"action_dim": 2, "hidden": (16, 16)},
        agent_kwargs={"env": CartPole()},
        optimizer_kwargs={"learning_rate": LR}, seed=seed,
    )
    es = (IW_ES(reuse_window=WINDOW, ess_min=0.3, **kw)
          if algo == "iwes" else ES(**kw))
    es.train(gens, verbose=False)
    steps, curve = 0, []
    for r in es.history:
        steps += r["env_steps"]
        curve.append((steps, r["reward_mean"]))
    reuse = sum(r.get("reused_prev", False) for r in es.history)
    return curve, reuse / len(es.history)


def steps_to(curve, thresh):
    return next((s for s, m in curve if m >= thresh), None)


def main():
    gens = 30 if "--quick" in sys.argv else GENS
    seeds = (0,) if "--quick" in sys.argv else (0, 1, 2)
    for algo in ("es", "iwes"):
        for seed in seeds:
            t0 = time.perf_counter()
            curve, reuse_frac = run(algo, seed, gens)
            print(json.dumps({
                "algo": algo, "seed": seed, "lr": LR,
                "final_mean": round(curve[-1][1], 1),
                **{f"steps_to_{t}": steps_to(curve, t) for t in THRESHOLDS},
                "reuse_frac": round(reuse_frac, 2),
                "wall_s": round(time.perf_counter() - t0, 1),
            }), flush=True)


if __name__ == "__main__":
    main()
