"""TPU tunnel watcher: probe until the chip serves, then land evidence.

Five rounds of this build have been gated on an axon tunnel that wedges
for hours and serves in unpredictable windows (BENCHMARKS.md "TPU status"
sections; the 2026-07-31 03:43 UTC window lasted ~15 minutes).  This
watcher makes every window count without a human in the loop:

  probe loop (subprocess `jax.devices()` under a hard timeout, one line
  per attempt appended to the log)
    └─ on recovery, run the evidence sequence, each step resumable so a
       window that closes mid-step loses nothing:
       1. examples/ab_onchip_driver.py --skip-done   (A/B matrix rows,
          recorded incrementally, aborts fast when the tunnel drops)
       2. bench.py > bench_headline_live.json        (headline + extras
          against the by-then-warm compile cache)
       3. examples/onchip_window.py --resume         (bounded training
          run of the north-star config, checkpointed)
       then back to probing — a later window adds rows/generations
       instead of restarting.

Use:  nohup python examples/tpu_watch.py [--log tpu_watch_r05.log]
          [--interval-s 240] [--once] &
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import bench  # noqa: E402  (acquire_evidence_lock — one lock protocol;
# bench also loads the heartbeat helpers WITHOUT importing jax into this
# process — the watcher must stay accelerator-free to survive wedges)
HEARTBEAT_ENV = bench.HEARTBEAT_ENV
describe_heartbeat = bench.describe_heartbeat

PROBE = ("import jax; d = jax.devices(); "
         "print(d[0].platform, len(d), flush=True)")


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _log(path, msg):
    with open(path, "a") as f:
        f.write(msg + "\n")


def probe(timeout_s: float) -> bool:
    try:
        r = subprocess.run([sys.executable, "-c", PROBE],
                           timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0 and r.stdout.strip().startswith("tpu")
    except subprocess.TimeoutExpired:
        return False


def run_step(label, argv, log_path, timeout_s, stdout=None):
    """Run one sequence step in its OWN process group so a timeout kills
    the whole tree — subprocess.run's timeout alone would orphan the
    step's grandchildren (bench --stage-one stages), which would then
    burn the single host core unbounded and contaminate the next
    window's serialized measurements (the round-4 lesson)."""
    _log(log_path, f"{_now()} step={label} start")
    # children must not re-take the evidence flock we already hold.
    # Heartbeat: any ES the step constructs beats into this per-step file
    # (bench stages override with their own per-stage path), so a timeout
    # below reports the last-known phase/generation, not just "TIMEOUT"
    hb_path = f"{log_path}.{label}.heartbeat.json"
    env = {**os.environ, "EVIDENCE_LOCK_HELD": "1", HEARTBEAT_ENV: hb_path}
    proc = subprocess.Popen(argv, cwd=REPO, start_new_session=True,
                            stdout=stdout, stderr=None, env=env)
    try:
        rc = proc.wait(timeout=timeout_s)
        _log(log_path, f"{_now()} step={label} exit={rc}")
        return rc == 0
    except subprocess.TimeoutExpired:
        import signal

        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            # bounded reap even after SIGKILL: a child wedged in
            # uninterruptible sleep (tunnel I/O) ignores the kill and an
            # unbounded wait would wedge the WATCHER too
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            _log(log_path, f"{_now()} step={label} unreapable after "
                           "SIGKILL (uninterruptible child?) — abandoning")
        _log(log_path, f"{_now()} step={label} TIMEOUT after {timeout_s}s "
                       f"(process group killed; {describe_heartbeat(hb_path)})")
        return False


def recovery_sequence(log_path, probe_timeout_s):
    py = sys.executable
    # 1. A/B matrix — incremental, aborts itself when the tunnel drops
    run_step("ab_matrix",
             [py, os.path.join(REPO, "examples", "ab_onchip_driver.py"),
              "--skip-done", "--out", os.path.join(REPO, "bench_ab_tpu.jsonl")],
             log_path, timeout_s=6 * 3600)
    # 2. headline (warm cache) — written to a temp path and renamed only
    # on success, so a mid-run wedge can't destroy a previous window's
    # good artifact
    if probe(probe_timeout_s):
        out = os.path.join(REPO, "bench_headline_live.json")
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            ok = run_step("headline", [py, os.path.join(REPO, "bench.py")],
                          log_path, timeout_s=3600, stdout=f)
        if ok:
            os.replace(tmp, out)
        else:
            try:
                os.remove(tmp)  # don't leave a partial artifact beside
            except OSError:      # the real one
                pass
    # 3. bounded, resumable training run of the north-star config
    if probe(probe_timeout_s):
        run_step("onchip_window",
                 [py, os.path.join(REPO, "examples", "onchip_window.py"),
                  "--resume", "--budget-s", "2700",
                  "--workdir", os.path.join(REPO, "runs", "onchip_window")],
                 log_path, timeout_s=2 * 3600)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--log", default=os.path.join(REPO, "tpu_watch_r05.log"))
    p.add_argument("--interval-s", type=float, default=240.0)
    p.add_argument("--probe-timeout-s", type=float, default=90.0)
    p.add_argument("--once", action="store_true",
                   help="single probe (+ sequence if up), then exit")
    args = p.parse_args(argv)

    import itertools
    import time
    for attempt in itertools.count(1):
        up = probe(args.probe_timeout_s)
        _log(args.log, f"{_now()} watcher attempt={attempt} up={up}")
        if up:
            # single-core host: on-chip measurements and CPU-mesh studies
            # must never overlap (round-4 load-contamination lesson).  CPU
            # study stages hold this flock (`flock .evidence.lock <stage>`);
            # if one is mid-stage, defer to the next probe cycle instead of
            # contaminating both sides' rates.
            try:
                lock_fd = bench.acquire_evidence_lock(max_wait_s=0,
                                                      respect_env=False)
            except bench.EvidenceLockBusy:
                _log(args.log, f"{_now()} up but evidence lock busy "
                               f"(CPU study mid-stage) — deferring")
                if args.once:
                    break
                time.sleep(args.interval_s)
                continue
            try:
                _log(args.log,
                     f"{_now()} RECOVERY — launching evidence sequence")
                recovery_sequence(args.log, args.probe_timeout_s)
                _log(args.log, f"{_now()} sequence done; resuming probe loop")
            finally:
                os.close(lock_fd)  # releases the flock
        if args.once:
            break
        time.sleep(args.interval_s)


if __name__ == "__main__":
    main()
