"""Process-topology scaling: the same ES program on 1×8, 2×4, 4×2
(processes × local CPU devices), collectives crossing process boundaries
via jax.distributed/Gloo — the DCN-analog layering of a TPU pod.

Measures steady-state generation time per topology so the cross-process
collective overhead is a number, not prose.  Run on an idle machine:

    python examples/multiprocess_scaling.py

Each topology runs in fresh child processes (the JAX distributed runtime
is once-per-process).  Expect the multi-process topologies to pay a
per-generation constant (Gloo TCP allreduce + fitness all_gather) on top
of the single-process time; on one physical core the device counts are
virtual, so the interesting number is that constant, not parallel
speedup.
"""

import json
import pathlib
import socket
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent

WORKER = r"""
import sys, time, json
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", int(sys.argv[4]))
pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
import estorch_tpu.parallel.multihost as mh
if nprocs > 1:
    ok = mh.initialize(f"localhost:{port}", num_processes=nprocs,
                       process_id=pid)
    if not ok:
        raise RuntimeError("jax.distributed init did not happen")
import optax
from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import Pendulum

es = ES(policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
        population_size=256, sigma=0.05,
        policy_kwargs={"action_dim": 1, "hidden": (64, 64),
                       "discrete": False, "action_scale": 2.0},
        agent_kwargs={"env": Pendulum(), "horizon": 100},
        optimizer_kwargs={"learning_rate": 1e-2}, seed=7,
        mesh=mh.global_population_mesh())
es.train(1, verbose=False)   # compile outside timing
t0 = time.perf_counter()
GENS = 5
es.train(GENS, verbose=False)
dt = (time.perf_counter() - t0) / GENS
if pid == 0:
    print(json.dumps({"s_per_gen": dt,
                      "steps_per_gen": es.history[-1]["env_steps"]}))
"""


def free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def run_topology(nprocs: int, local_devices: int) -> dict:
    port = free_port()
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(WORKER)
        path = f.name
    procs = []
    try:
        import os

        env = {**os.environ, "PYTHONPATH": str(REPO)}
        procs = [
            subprocess.Popen(
                [sys.executable, path, str(pid), str(nprocs), str(port),
                 str(local_devices)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env,
            )
            for pid in range(nprocs)
        ]
        outs, errs = [], []
        for p in procs:
            try:
                out, err = p.communicate(timeout=900)
                outs.append(out)
                errs.append(err)
            except subprocess.TimeoutExpired:
                raise RuntimeError(
                    f"{nprocs}x{local_devices}: worker hung (>900s) — "
                    "likely a Gloo rendezvous deadlock"
                )
        for p, err in zip(procs, errs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"{nprocs}x{local_devices}: a worker failed; stderr "
                    f"tail:\n{err[-2000:]}"
                )
        line = [ln for ln in outs[0].splitlines() if ln.startswith("{")][-1]
        return json.loads(line)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        pathlib.Path(path).unlink(missing_ok=True)


def main():
    results = {}
    for nprocs, local in ((1, 8), (2, 4), (4, 2)):
        r = run_topology(nprocs, local)
        results[f"{nprocs}x{local}"] = r
        print(f"{nprocs} proc x {local} dev: {r['s_per_gen']*1e3:.0f} ms/gen "
              f"({r['steps_per_gen']} steps)", flush=True)
    base = results["1x8"]["s_per_gen"]
    for k, r in results.items():
        print(f"{k}: overhead vs single-process "
              f"{(r['s_per_gen'] - base)*1e3:+.0f} ms/gen")


if __name__ == "__main__":
    main()
