"""Real MuJoCo Humanoid training — BASELINE config 3's first evidence.

Round-4 verdict next #2: the Humanoid env (gymnasium Humanoid-v5, the
v3 lineage's current id) had never trained in this repo — the capstone
evidence is the in-tree planar Humanoid2D.  This runs the pooled recipe
(`configs.humanoid_pooled`: real physics in gym.vector workers,
device-batched 256×256 MLP forwards, obs_norm, mirrored sampling) at a
CPU-feasible population and records the learning curve, throughput, and
peak RSS — config 3's evidence trail starts here; the 10k population is
the chip's job.

Run:  python examples/humanoid_v3_pooled.py [gens] [pop] [seed]
"""

import json
import resource
import sys
import time


def main():
    # flags and positionals may come in any order: `... 40 512 0 --resume`
    # and `... --resume` both work
    pos = [a for a in sys.argv[1:] if not a.startswith("--")]
    resume = "--resume" in sys.argv
    gens = int(pos[0]) if len(pos) > 0 else 40
    pop = int(pos[1]) if len(pos) > 1 else 512
    seed = int(pos[2]) if len(pos) > 2 else 0

    from estorch_tpu import configs
    from estorch_tpu.parallel.mesh import single_device_mesh
    from estorch_tpu.utils import (PeriodicCheckpointer,
                                   enable_compilation_cache,
                                   force_cpu_backend, restore_checkpoint)

    force_cpu_backend(1)
    enable_compilation_cache()

    es = configs.humanoid_pooled(
        population_size=pop, seed=seed, mesh=single_device_mesh(),
    )
    # checkpoint + periodic held-out evals: a wall-clock kill (the round-5
    # stage-2 run died 2 generations before its final eval) must not cost
    # the evidence — the latest checkpoint restores and every 10th
    # generation already carries a held-out row
    ck = PeriodicCheckpointer(es, f"runs/humanoid_v3_s{seed}/ckpts",
                              every=5, max_to_keep=2)
    resumed_at = 0
    if resume and ck.latest():
        restore_checkpoint(es, ck.latest())
        resumed_at = es.generation
        print(json.dumps({"resumed_at": resumed_at}), flush=True)

    t0 = time.perf_counter()
    total_steps = 0

    def log(rec):
        nonlocal total_steps
        total_steps += rec["env_steps"]
        el = time.perf_counter() - t0
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
        print(json.dumps({
            "gen": rec["generation"],
            "reward_mean": round(rec["reward_mean"], 1),
            "reward_max": round(rec["reward_max"], 1),
            "env_steps": rec["env_steps"],
            "steps_per_s": round(total_steps / el, 1),
            "elapsed_s": round(el, 1),
            "peak_rss_gb": round(rss, 2),
        }), flush=True)
        ck.on_record(rec)
        if rec["generation"] % 10 == 0:
            ev10 = es.evaluate_policy(n_episodes=8, seed=1)
            print(json.dumps({
                "gen": rec["generation"],
                "heldout_mean_8ep": round(ev10["mean"], 1),
                "heldout_std": round(ev10["std"], 1),
            }), flush=True)

    remaining = gens - es.generation
    if remaining > 0:
        es.train(remaining, log_fn=log, verbose=False)
    ck.save(es.generation)
    ck.close()

    ev = es.evaluate_policy(n_episodes=32, seed=1)
    print(json.dumps({
        "summary": "humanoid_pooled pop-%d obs_norm (Humanoid-v5)" % pop,
        # history-derived totals so a resumed run reports the WHOLE run,
        # not just the post-resume session (the log rows' steps_per_s and
        # wall_s stay session-relative by design)
        "gens": es.generation, "seed": seed,
        "resumed_at": resumed_at or None,
        "final_reward_mean": round(es.history[-1]["reward_mean"], 1),
        "best": round(es.best_reward, 1),
        "heldout_mean_32ep": round(ev["mean"], 1),
        "heldout_std": round(ev["std"], 1),
        "total_env_steps": int(sum(r["env_steps"] for r in es.history)),
        "session_env_steps": total_steps,
        "session_wall_s": round(time.perf_counter() - t0, 1),
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2),
    }), flush=True)
    es.engine.pool.close()
    es.engine.center_pool.close()


if __name__ == "__main__":
    main()
