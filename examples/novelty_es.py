"""Novelty-search ES on MountainCarContinuous — the deceptive-reward demo.

Reference equivalent: the NS-ES example script (SURVEY.md §2 item 9) whose
Agent.rollout returns ``(reward, bc)``.  Here the behavior characterization
(final car position) is produced on-device by the env's ``behavior`` method;
the archive and k-NN stay host-side (BASELINE.json north star).

Run: python examples/novelty_es.py [ns|nsr|nsra]
"""

import sys

import optax

from estorch_tpu import NS_ES, NSR_ES, NSRA_ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import MountainCarContinuous

ALGOS = {"ns": NS_ES, "nsr": NSR_ES, "nsra": NSRA_ES}


def main(algo: str = "nsra"):
    cls = ALGOS[algo]
    extra = {"weight": 1.0} if cls is NSRA_ES else {}
    es = cls(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=128,
        sigma=0.05,
        k=10,
        meta_population_size=3,
        policy_kwargs={"action_dim": 1, "hidden": (32, 32), "discrete": False},
        agent_kwargs={"env": MountainCarContinuous(), "horizon": 500},
        optimizer_kwargs={"learning_rate": 1e-2},
        **extra,
    )
    es.train(n_steps=15)
    print(f"\nbest reward: {es.best_reward:.2f}  archive size: {len(es.archive)}")
    return es


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "nsra")
