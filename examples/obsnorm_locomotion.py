"""Does observation normalization help locomotion?  A/B on Walker2D.

Walker2D observations mix bounded joint angles with unbounded velocity
channels whose variance dominates — the classic case running obs stats
exist for (OpenAI-ES normalizes MuJoCo observations for exactly this
reason; the reference has no such machinery).  Same recipe, same seeds,
only ``obs_norm`` differs.

Run:  python examples/obsnorm_locomotion.py [gens] [pop]
"""

import sys

import numpy as np


def run(obs_norm: bool, seed: int, gens: int, pop: int):
    from estorch_tpu import configs
    from estorch_tpu.utils import force_cpu_backend

    # A/B study: run on the virtual CPU mesh regardless of accelerator
    # health — relative ordering is the result, not absolute throughput
    force_cpu_backend(8)

    es = configs.walker2d_device(
        population_size=pop, seed=seed, obs_norm=obs_norm,
    )
    es.train(gens, verbose=False)
    means = [r["reward_mean"] for r in es.history]
    return {
        "final_mean": means[-1],
        "best": es.best_reward,
        "auc": float(np.mean(means)),  # area under the learning curve
    }


def main():
    gens = int(sys.argv[1]) if len(sys.argv) > 1 else 40
    pop = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    for seed in (0, 1):
        for flag in (True, False):
            r = run(flag, seed, gens, pop)
            print(f"seed {seed} obs_norm={str(flag):5s} "
                  f"final_mean {r['final_mean']:8.1f}  best {r['best']:8.1f}"
                  f"  auc {r['auc']:8.1f}", flush=True)


if __name__ == "__main__":
    main()
