"""Device-native locomotion capstone: the planar humanoid stays upright.

Humanoid2D (envs/locomotion.py) is the hardest in-tree task — an 11-body
jointed pelvis–torso–head column on two walker legs with free-swinging arm
counterweights, terminating when the column falls.  Physics runs INSIDE
the compiled generation program, the device-native stand-in for the
reference users' MuJoCo-Humanoid configs (those stay on the host/pooled
paths; BASELINE config 3).

Within ~30 generations the population mean roughly triples as policies
learn to balance; a 300-generation run reaches mean 160 / best 407 — best
members hold the full 400-step horizon while moving (BENCHMARKS.md).

Run: python examples/locomotion_humanoid.py
"""

from estorch_tpu.configs import humanoid2d_device


def main():
    es = humanoid2d_device(population_size=512)
    es.train(n_steps=30)
    ev = es.evaluate_policy(n_episodes=10)
    print(f"\nbest member reward: {es.best_reward:.1f}")
    print(f"center policy held-out mean: {ev['mean']:.1f}")
    return es


if __name__ == "__main__":
    main()
