"""Novelty vs reward-only ES on a DECEPTIVE locomotion task.

Round-4 verdict next #5: the novelty family's only outright win was
MountainCarContinuous; its real-physics showing (HalfCheetah NSR-ES) was
a predicted loss because plain locomotion is not deceptive.  This study
runs the A/B on a task BUILT to be deceptive — `DeceptiveValley`
(envs/locomotion.py): a reward valley along the progress axis of a
planar runner, the 1-D equivalent of Conti et al.'s U-maze (PAPERS.md).
Reward-following ES should stall at the bait (a true local optimum
whose basin covers the greedy path); novelty search over the
final-position BC has no such barrier.

Protocol:
  phase 0  calibrate reachable displacement: plain ES on the BASE env,
           median final x of the trained policy → X_reach; the valley is
           placed INSIDE demonstrated reach (bait 0.3·X, valley 0.7·X),
           so "ES stalls" can never be an artifact of the prize being
           physically unreachable.
  phase 1  same budget per arm on the deceptive env:
           ES (reward-only control) vs NSRA-ES (adaptive novelty).
           Escape = median held-out final x past the valley.

Run:  python examples/deceptive_valley_novelty.py [gens] [pop] [seeds]
"""

import json
import sys
import time

import numpy as np


def _median_final_x(es, n_episodes=16, meta_index=None):
    ev = es.evaluate_policy(n_episodes=n_episodes, seed=101,
                            meta_index=meta_index, return_details=True)
    return float(np.median(ev["bc"][:, 0])), float(ev["mean"])


def main():
    gens = int(sys.argv[1]) if len(sys.argv) > 1 else 120
    pop = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    n_seeds = int(sys.argv[3]) if len(sys.argv) > 3 else 2

    import optax

    from estorch_tpu import ES, NSRA_ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import DeceptiveValley, Walker2D
    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(8)
    enable_compilation_cache()

    base = Walker2D()
    common = dict(
        policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
        population_size=pop, sigma=0.08,
        policy_kwargs={"action_dim": base.action_dim, "hidden": (64, 64),
                       "discrete": False, "action_scale": 1.0},
        optimizer_kwargs={"learning_rate": 2e-2},
    )

    # phase 0: how far can this recipe actually walk?
    cal = ES(agent_kwargs={"env": base, "horizon": 400}, seed=0, **common)
    cal.train(max(gens // 2, 30), verbose=False)
    x_reach, _ = _median_final_x(cal)
    print(json.dumps({"phase": "calibrate", "x_reach": round(x_reach, 2),
                      "gens": max(gens // 2, 30)}), flush=True)
    if x_reach < 1.0:
        print(json.dumps({"error": "calibration walked < 1.0 units; "
                          "valley geometry would be degenerate"}), flush=True)
        return

    x_bait = round(0.3 * x_reach, 2)
    x_valley = round(0.7 * x_reach, 2)
    env = DeceptiveValley(base, x_bait=x_bait, x_valley=x_valley,
                          valley_slope=1.5, rise_slope=4.0)
    print(json.dumps({"phase": "geometry", "x_bait": x_bait,
                      "x_valley": x_valley}), flush=True)

    results = []
    for seed in range(n_seeds):
        for arm in ("es", "nsra"):
            t0 = time.perf_counter()
            if arm == "es":
                algo = ES(agent_kwargs={"env": env, "horizon": 400},
                          seed=seed, **common)
            else:
                algo = NSRA_ES(agent_kwargs={"env": env, "horizon": 400},
                               seed=seed, k=10, meta_population_size=3,
                               **common)
            algo.train(gens, verbose=False)
            if arm == "es":
                x_med, r_mean = _median_final_x(algo)
                per_center = [round(x_med, 2)]
            else:
                centers = [
                    _median_final_x(algo, meta_index=i)
                    for i in range(len(algo.meta_states))
                ]
                per_center = [round(x, 2) for x, _ in centers]
                x_med, r_mean = max(centers, key=lambda c: c[0])
            row = {
                "phase": "ab", "arm": arm, "seed": seed,
                "median_final_x": round(x_med, 2),
                "per_center_x": per_center,
                "escaped_valley": bool(x_med > x_valley),
                "reached_bait": bool(x_med > 0.8 * x_bait),
                "heldout_reward_mean": round(r_mean, 1),
                "wall_s": round(time.perf_counter() - t0, 1),
            }
            results.append(row)
            print(json.dumps(row), flush=True)

    es_esc = [r["escaped_valley"] for r in results if r["arm"] == "es"]
    ns_esc = [r["escaped_valley"] for r in results if r["arm"] == "nsra"]
    print(json.dumps({
        "verdict": {
            "es_escapes": f"{sum(es_esc)}/{len(es_esc)}",
            "nsra_escapes": f"{sum(ns_esc)}/{len(ns_esc)}",
            "deception_held_for_es": not any(es_esc),
            "novelty_won": sum(ns_esc) > sum(es_esc),
        }
    }), flush=True)


if __name__ == "__main__":
    main()
