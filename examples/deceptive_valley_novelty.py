"""Novelty vs reward-only ES on a DECEPTIVE locomotion task.

Round-4 verdict next #5: the novelty family's only outright win was
MountainCarContinuous; its real-physics showing (HalfCheetah NSR-ES) was
a predicted loss because plain locomotion is not deceptive.  This study
runs the A/B on a task BUILT to be deceptive — `DeceptiveValley`
(envs/locomotion.py): a reward valley along the progress axis of a
planar runner, the 1-D equivalent of Conti et al.'s U-maze (PAPERS.md).
Reward-following ES should stall at the bait (a true local optimum
whose basin covers the greedy path); novelty search over the
final-position BC has no such barrier.

Substrate: Swimmer2D — no alive bonus and no termination, so the shaped
fitness telescopes EXACTLY to reward_scale·(φ(x_T) − φ(x_0)) − control
cost (no survival confound), and — decisive (round-5 calibration) —
displacement is entirely EARNED: a passive/random swimmer stays at
x ≈ 0.00 while trained undulation reaches ~8 units (the walker/cheetah
alternatives drift ~0.5-0.8 units passively, so a valley inside their
envelope gets crossed by accident, not locomotion).

Geometry is SCALE-RELATIVE to the measured [passive, trained] span:
phase 0 measures the untrained median final x (x_rand), the trained
reach X, and the episode noise of final x; the bait sits at
x_rand + 0.35·(X − x_rand) and the valley ends at x_rand + 0.75·(X −
x_rand) — inside demonstrated reach, above passive drift — and the
study aborts honestly unless the valley width clears 3 noise widths
AND the bait clears the passive envelope by 5 (otherwise "escape"
could be luck, not search).

Run:  python examples/deceptive_valley_novelty.py [gens] [pop] [seeds]
          [valley_end_frac]

`valley_end_frac` (default 0.75) is the task-difficulty knob: where the
valley's far wall sits as a fraction of the calibrated [passive,
trained] span.  The round-5 120-gen run at 0.75 measured NSRA's valley
penetration at ~0.36 units per 120 gens — enough to show the mechanism
(ES pinned AT the bait both seeds; novelty past it both seeds) but a
3.3-unit-wide valley needs a budget no CPU-mesh session has.  A
narrower valley (e.g. 0.55) is the same trap — a true local optimum
whose width still clears the 3-noise-width guard by two orders of
magnitude — sized so a full escape fits the generation budget.
"""

import json
import sys
import time

import numpy as np


def _final_x_stats(es, n_episodes=16, meta_index=None):
    ev = es.evaluate_policy(n_episodes=n_episodes, seed=101,
                            meta_index=meta_index, return_details=True)
    xs = ev["bc"][:, 0]
    return (float(np.median(xs)), float(np.std(xs)), float(ev["mean"]))


def main():
    gens = int(sys.argv[1]) if len(sys.argv) > 1 else 120
    pop = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    n_seeds = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    valley_end_frac = float(sys.argv[4]) if len(sys.argv) > 4 else 0.75
    seed_start = int(sys.argv[5]) if len(sys.argv) > 5 else 0

    import optax

    from estorch_tpu import ES, NSRA_ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import DeceptiveValley, Swimmer2D
    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(8)
    enable_compilation_cache()

    base = Swimmer2D()
    common = dict(
        policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
        population_size=pop, sigma=0.08,
        policy_kwargs={"action_dim": base.action_dim, "hidden": (32, 32),
                       "discrete": False, "action_scale": 1.0},
        # the proven Swimmer2D recipe (locomotion_swimmer.py: full gait in
        # ~30 gens at pop 512 / lr 3e-2) — calibration AND both A/B arms
        # share it, so an ES stall at the bait is deception, not an
        # under-powered optimizer (the round-5 0.77-unit calibration abort
        # was pop 256 / lr 2e-2 under-training, not geometry)
        optimizer_kwargs={"learning_rate": 3e-2},
    )

    # phase 0: passive envelope (median AND spread), reachable
    # displacement, trained episode noise
    cal = ES(agent_kwargs={"env": base, "horizon": 400}, seed=0, **common)
    x_rand, x_rand_noise, _ = _final_x_stats(cal)
    cal.train(max(gens // 2, 30), verbose=False)
    x_reach, x_noise, _ = _final_x_stats(cal)
    print(json.dumps({"phase": "calibrate", "x_rand": round(x_rand, 3),
                      "x_rand_noise": round(x_rand_noise, 3),
                      "x_reach": round(x_reach, 3),
                      "final_x_noise": round(x_noise, 3),
                      "gens": max(gens // 2, 30)}), flush=True)

    span = x_reach - x_rand
    x_bait = x_rand + 0.35 * span
    x_valley = x_rand + valley_end_frac * span
    width = x_valley - x_bait
    # two distinct noise scales: the TRAINED policy's episode spread sizes
    # the valley width; the PASSIVE policy's spread sizes the bait's
    # clearance above where un-trained episodes land by luck
    noise = max(x_noise, 1e-3)
    p_noise = max(x_rand_noise, 1e-3)
    if span <= 0 or width < 3.0 * noise or x_bait < x_rand + 5.0 * p_noise:
        print(json.dumps({"error": "geometry not luck-proof: span %.3f, "
                          "width %.3f vs 3*trained-noise %.3f, bait margin "
                          "%.3f vs 5*passive-noise %.3f"
                          % (span, width, 3 * noise,
                             x_bait - x_rand, 5 * p_noise)}),
              flush=True)
        return
    env = DeceptiveValley(base, x_bait=x_bait, x_valley=x_valley,
                          valley_slope=1.5, rise_slope=4.0,
                          reward_scale=10.0)
    print(json.dumps({"phase": "geometry", "x_bait": round(x_bait, 3),
                      "x_valley": round(x_valley, 3),
                      "reward_scale": 10.0}), flush=True)

    from estorch_tpu import NS_ES

    results = []
    for seed in range(seed_start, seed_start + n_seeds):
        for arm in ("es", "nses", "nsra"):
            t0 = time.perf_counter()
            if arm == "es":
                algo = ES(agent_kwargs={"env": env, "horizon": 400},
                          seed=seed, **common)
            else:
                # nses = pure novelty (Conti's strongest escaper on
                # deceptive tasks); nsra = adaptive reward/novelty blend
                cls = NS_ES if arm == "nses" else NSRA_ES
                algo = cls(agent_kwargs={"env": env, "horizon": 400},
                           seed=seed, k=10, meta_population_size=3,
                           **common)
            algo.train(gens, verbose=False)
            if arm == "es":
                x_med, _, r_mean = _final_x_stats(algo)
                per_center = [round(x_med, 3)]
            else:
                centers = [
                    _final_x_stats(algo, meta_index=i)
                    for i in range(len(algo.meta_states))
                ]
                per_center = [round(x, 3) for x, _, _ in centers]
                best = max(centers, key=lambda c: c[0])
                x_med, r_mean = best[0], best[2]
            row = {
                "phase": "ab", "arm": arm, "seed": seed,
                "median_final_x": round(x_med, 3),
                "per_center_x": per_center,
                "escaped_valley": bool(x_med > x_valley),
                "past_bait": bool(x_med > x_bait + 3 * max(noise, p_noise)),
                "heldout_reward_mean": round(r_mean, 1),
                "wall_s": round(time.perf_counter() - t0, 1),
            }
            results.append(row)
            print(json.dumps(row), flush=True)

    def esc(a):
        return [r["escaped_valley"] for r in results if r["arm"] == a]

    es_esc = esc("es")
    nov_esc = esc("nses") + esc("nsra")
    print(json.dumps({
        "verdict": {
            "es_escapes": f"{sum(es_esc)}/{len(es_esc)}",
            "nses_escapes": f"{sum(esc('nses'))}/{len(esc('nses'))}",
            "nsra_escapes": f"{sum(esc('nsra'))}/{len(esc('nsra'))}",
            "deception_held_for_es": not any(es_esc),
            "novelty_won": sum(nov_esc) > 0 and not any(es_esc),
        }
    }), flush=True)


if __name__ == "__main__":
    main()
