"""Does a LEARNED episode-start carry pay on POMDP locomotion?

A/B for the round-5 `learned_carry=True` extension (models/policies.py):
the episode-start carry becomes ordinary ``carry0_*`` params — perturbed
by ES noise, moved by the update — instead of zeros.  The hypothesis:
on a partially observable task the recurrent core spends its first
steps rebuilding rate estimates from positions; a learned start state
can encode that warm-up (a gait-phase prior), which a zeros start must
re-derive every episode.

Protocol mirrors examples/pomdp_locomotion.py: `PositionOnly(Walker2D())`
(all rate channels zeroed — walking requires memory), identical budget
and hypers for both arms, displacement as the discriminating metric.
Also reports the trained ‖carry0‖ so "the learned start moved away from
zeros" is itself a measurement, and an honest null stays publishable.

Run:  python examples/learned_carry_ab.py [gens] [pop] [seeds]
"""

import json
import sys
import time

import numpy as np


def run(learned: bool, seed: int, gens: int, pop: int):
    import optax

    from estorch_tpu import ES, JaxAgent, RecurrentPolicy

    from estorch_tpu.envs import PositionOnly, Walker2D

    pk = {"action_dim": 6, "hidden": (64,), "gru_size": 32,
          "discrete": False, "learned_carry": learned}
    es = ES(
        policy=RecurrentPolicy, agent=JaxAgent, optimizer=optax.adam,
        population_size=pop, sigma=0.05, policy_kwargs=pk,
        agent_kwargs={"env": PositionOnly(Walker2D()), "horizon": 200},
        optimizer_kwargs={"learning_rate": 2e-2}, seed=seed,
    )
    t0 = time.perf_counter()
    es.train(gens, verbose=False)
    ev = es.evaluate_policy(n_episodes=16, seed=99, return_details=True)
    out = {
        "arm": "learned" if learned else "zeros",
        "seed": seed,
        "final_mean": round(float(es.history[-1]["reward_mean"]), 1),
        "heldout_mean": round(float(ev["mean"]), 1),
        "center_disp_x": round(float(ev["bc"][:, 0].mean()), 2),
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    if learned:
        c0 = es._spec.unravel(es.state.params_flat)["carry0_0"]
        out["carry0_norm"] = round(float(np.linalg.norm(np.asarray(c0))), 3)
    return out


def main():
    gens = int(sys.argv[1]) if len(sys.argv) > 1 else 120
    pop = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    n_seeds = int(sys.argv[3]) if len(sys.argv) > 3 else 2

    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(8)
    enable_compilation_cache()

    rows = []
    for seed in range(n_seeds):
        for learned in (False, True):
            r = run(learned, seed, gens, pop)
            rows.append(r)
            print(json.dumps(r), flush=True)

    def med(arm, k):
        return float(np.median([r[k] for r in rows if r["arm"] == arm]))

    print(json.dumps({"verdict": {
        "zeros_heldout_median": med("zeros", "heldout_mean"),
        "learned_heldout_median": med("learned", "heldout_mean"),
        "zeros_disp_median": med("zeros", "center_disp_x"),
        "learned_disp_median": med("learned", "center_disp_x"),
    }}), flush=True)


if __name__ == "__main__":
    main()
