#!/bin/bash
cd /root/repo
LOG=/root/repo/studies_r05e.log
echo "--- stage: /opt/venv/bin/python examples/pop10k_training.py 60 0  (probe-4 recipe)" >> "$LOG"
flock /root/repo/.evidence.lock /opt/venv/bin/python examples/pop10k_training.py 60 0 >> "$LOG" 2>&1
echo "exit $? $(date -u +%FT%TZ)" >> "$LOG"
