#!/usr/bin/env bash
# Fast pre-test gate: esguard static analysis + bytecode compile check.
# Pure AST + compileall — runs on CPU in seconds, touches no device
# (JAX_PLATFORMS=cpu guards against the image's axon default even though
# the analyzer imports neither jax nor the analyzed modules).
set -euo pipefail
cd "$(dirname "$0")"

export JAX_PLATFORMS=cpu

echo "== esguard =="
python -m estorch_tpu.analysis estorch_tpu/

echo "== compileall =="
python -m compileall -q estorch_tpu/ tests/ examples/

echo "lint gate: OK"
