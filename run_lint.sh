#!/usr/bin/env bash
# Fast pre-test gate: esguard static analysis + bytecode compile check.
# Pure AST + compileall — runs on CPU in seconds, touches no device
# (JAX_PLATFORMS=cpu guards against the image's axon default even though
# the analyzer imports neither jax nor the analyzed modules).
set -euo pipefail
cd "$(dirname "$0")"

export JAX_PLATFORMS=cpu

echo "== esguard =="
# Two-speed gate.  When a base ref is available (CI PRs export
# ESGUARD_CHANGED_RANGE, or origin/main exists locally) the changed-file
# pass runs FIRST so a racy edit fails in well under a second; the full
# whole-program pass (lockset rules R18-R22 need every module linked,
# and the esguard_ratchet.json shrink-only counts are checked here)
# always follows, archiving the machine-readable findings report for CI.
CHANGED_RANGE="${ESGUARD_CHANGED_RANGE:-}"
if [ -z "$CHANGED_RANGE" ] && git rev-parse --verify -q origin/main >/dev/null 2>&1; then
    CHANGED_RANGE="origin/main...HEAD"
fi
if [ -n "$CHANGED_RANGE" ]; then
    echo "-- changed files ($CHANGED_RANGE) --"
    python -m estorch_tpu.analysis --changed "$CHANGED_RANGE"
fi
echo "-- full tree --"
ARTIFACT_DIR="${ESGUARD_ARTIFACT_DIR:-/tmp/esguard}"
mkdir -p "$ARTIFACT_DIR"
python -m estorch_tpu.analysis --format=json estorch_tpu/ \
    > "$ARTIFACT_DIR/findings.json" \
    || { cat "$ARTIFACT_DIR/findings.json"; exit 1; }
python -m estorch_tpu.analysis estorch_tpu/
echo "findings artifact: $ARTIFACT_DIR/findings.json"

echo "== obs selfcheck =="
# record-schema validation of the golden generation record + summarize
# pipeline (estorch_tpu/obs/summarize.py) — schema drift fails fast here,
# before a JSONL consumer parses mismatched records
python -m estorch_tpu.obs summarize --selfcheck

echo "== obs profile selfcheck =="
# performance-attribution gate (estorch_tpu/obs/profile/): a synthetic
# run with known per-step FLOPs must produce exactly the expected MFU,
# compile-ledger entries must round-trip the Prometheus exposition
# parser, degenerate inputs must degrade to a note (never a crash), and
# an injected 30% eval-phase slowdown must be flagged NAMING the eval
# phase.  Stdlib+numpy, sub-second.
python -m estorch_tpu.obs profile --selfcheck

echo "== obs regress selfcheck =="
# perf-gate gate (estorch_tpu/obs/export/regress.py): the statistical
# regression detector must flag a synthetic 30% slowdown injected into a
# copied baseline AND pass an identical-run comparison — a gate that can
# do neither would either cry wolf on every loaded-host run or wave real
# regressions through.  Pure stdlib, milliseconds.
python -m estorch_tpu.obs regress --selfcheck

echo "== obs hist selfcheck =="
# streaming-histogram gate (estorch_tpu/obs/hist.py): exact small-N
# quantiles, a known-distribution sample inside the documented bucket
# error bound, merge associativity, and the cross-restart composition +
# Prometheus exposition round trips.  Stdlib, milliseconds.
python -m estorch_tpu.obs hist --selfcheck

echo "== obs collect selfcheck =="
# fleet-collector gate (estorch_tpu/obs/agg/): synthetic healthy /
# garbage / dead-port targets under one collector — every tick must
# tolerate the dead pair, absence + burn-rate rules must fire NAMING the
# target, stored quantiles must sit inside the histogram ladder's
# documented bound, and the collector's own /metrics + /alerts must
# parse.  Stdlib, ~seconds.
python -m estorch_tpu.obs collect --selfcheck

echo "== collector file-run probe =="
# the wedged-host contract, proven the same way the sidecar/loadgen
# prove it: the collector runs AS A FILE (no package import, no jax)
# and still passes the full selfcheck
python estorch_tpu/obs/agg/collector.py --selfcheck

echo "== obs trace selfcheck =="
# distributed-trace assembly gate (estorch_tpu/obs/agg/traces.py): a
# synthetic three-process fleet run dir (router + two replicas) with a
# hedged trace, a torn tail, and a foreign trace — assembly must join
# the hedge across all three processes with the loser marked cancelled,
# isolate the foreign trace, skip the torn line, and the Perfetto
# export must validate.  Stdlib, milliseconds.
python -m estorch_tpu.obs trace --fleet --selfcheck

echo "== obs regress tail selfcheck =="
# tail-gate gate (estorch_tpu/obs/export/regress.py compare_tail): a
# median-clean pair with ~2% of requests slowed 5x (the chaos-shed
# signature) must PASS the median gate but be FLAGGED at p99, naming
# the quantile and the endpoint/phase.  Pure stdlib, milliseconds.
python -m estorch_tpu.obs regress --tail --selfcheck

echo "== chaos selfcheck =="
# recovery-path gate (estorch_tpu/resilience, docs/resilience.md): a tiny
# host-backend run under a worker-kill chaos plan must keep FULL
# population participation (respawn + same-generation retry) — measured
# against a clean twin; fails when recovery regressed.  Host path only,
# no device touch.
python bench.py --chaos --selfcheck

echo "== async-ab selfcheck =="
# barrier-free-scheduler gate (estorch_tpu/algo/scheduler.py,
# docs/async.md): the same tiny host run under an identical
# deterministic straggler plan must run >=1.25x faster through the
# event-driven fold scheduler than through the synchronous barrier
# loop (medians + learned noise band), with every late result folded
# or counted — zero silent drops.  Host path only, no device touch.
python bench.py --async-ab --selfcheck

echo "== elastic-ab selfcheck =="
# elastic multi-host gate (estorch_tpu/parallel/elastic.py +
# algo/scheduler.py ElasticScheduler, docs/multihost.md): under an
# IDENTICAL declared straggle_host plan, the elastic host-granular fold
# must beat the synchronous 2-process SPMD multihost loop >=1.25x
# beyond the learned noise band (a slow host costs throughput, the
# barrier costs the fleet), stale host contributions must actually
# FOLD with clipped importance weights, and the accounting invariant
# dispatched == consumed + discarded + lost must hold.  CPU processes
# over loopback (jax.distributed/Gloo for the sync leg, stdlib TCP for
# the elastic fleet), ~2 min.
python bench.py --elastic-ab --selfcheck

echo "== shard-ab selfcheck =="
# param-sharded gate (estorch_tpu/parallel/sharded.py, docs/sharding.md):
# a same-seed sharded run must match the replicated fused path allclose
# at f32, the program-noise sharded program must fit in LESS per-device
# memory than the replicated one (compile-ledger memory_analysis), and
# the sharded row must report a non-null MFU from the shard-aware cost
# model.  Virtual CPU mesh in a child process, tiny config.
python bench.py --shard-ab --selfcheck

echo "== scenario-ab selfcheck =="
# scenario-suite gate (estorch_tpu/scenarios, docs/scenarios.md): one
# 10-variant domain-randomized run must beat 10 sequential
# single-scenario runs >=3x wall-clock, the compile ledger must show
# the program count independent of variant count (traced-operand
# contract — the recompile-per-variant smell esguard R16 hunts), and
# per-variant fitness must surface with full variant coverage.  CPU
# child, ~40s.
python bench.py --scenario-ab --selfcheck

echo "== loadgen smoke =="
# the load generator validated against an in-process stdlib echo server
# (closed+open loop, latency percentiles, response indexing).  Run as a
# FILE, not a module: loadgen is deliberately stdlib-only, so this works
# even where the jax import chain is broken/wedged.
python estorch_tpu/serve/loadgen.py --selfcheck

echo "== serve selfcheck =="
# serving-vertical gate (estorch_tpu/serve, docs/serving.md): export a
# trained pendulum bundle, serve it through the dynamic batcher, drive
# concurrent load — gates bit-exact responses (vs the exporting run's
# es.predict), bucket/recompile accounting, zero shed, and a clean
# SIGTERM drain.  CPU only; the >=3x batching-throughput gate lives in
# the full `bench.py --serve` form and the tier-1 serving demo.
python bench.py --serve --selfcheck

echo "== fleet selfcheck =="
# serving-fleet gate (serve/router.py + serve/fleet.py, docs/serving.md
# "Fleet"): a 2-replica fleet under concurrent load with a DECLARED
# kill_replica chaos event must lose zero client answers (failover
# retries within the budget), open and re-close the breaker, respawn
# the corpse WARM (compiles_at_load == 0), and report a sane
# capacity-sweep ladder (max RPS at a p99 SLO).  CPU only, ~60s.
python bench.py --fleet --selfcheck

echo "== autoscale policy selfcheck =="
# autoscaler policy/log/refusal gate (obs/agg/autoscale.py,
# docs/serving.md "Autoscaling") against a synthetic store: demand
# scale-up, cooldown suppression, burn-rate step, sustained
# low-watermark scale-down, bit-exact decision-log replay + tamper
# detection, and the mismatched-capacity refusal naming both sides.
# Run as a FILE (the wedged-host contract): stdlib only, no jax,
# milliseconds.
python estorch_tpu/obs/agg/autoscale.py --selfcheck

echo "== autoscale selfcheck =="
# closed-control-loop E2E gate (obs/agg/autoscale.py + serve/fleet.py,
# docs/serving.md "Autoscaling"): a 2-replica fleet + in-process
# collector + real capacity sweep + autoscaler actuating over HTTP
# POST /scale — offered load TRIPLES mid-run and the replica count
# must track it (up past the floor, back down after the trickle), p99
# stays inside the SLO, zero client errors/shed including through a
# declared kill_replica during the scale-up, every scale-up replica
# loads warm (compiles_at_load == 0), the retirement drains cleanly,
# and the decision log replays bit-exactly.  CPU only, ~90s.
python bench.py --autoscale --selfcheck

echo "== coldstart selfcheck =="
# warm-bundle + quantized-serving gate (serve/warm.py, docs/serving.md
# "Cold start & quantized serving"): a warm bundle must load with ZERO
# fresh XLA builds (all persistent-cache hits) while the cold control
# leg provably pays the JIT storm, warm time-to-first-response must beat
# cold beyond the learned noise band, and every bf16 bucket's divergence
# must be measured inside the documented bound.  The >=1.5x bf16
# throughput gate applies on native-bf16 hardware (TPU); off-chip the
# ratio is recorded honestly (XLA:CPU bf16 is an upconvert path).
python bench.py --coldstart --selfcheck

echo "== compileall =="
python -m compileall -q estorch_tpu/ tests/ examples/

echo "lint gate: OK"
