"""Resilience subsystem (estorch_tpu/resilience, docs/resilience.md).

The headline claim under test: recovery is not merely "doesn't crash" —
it is *bit-exact*.  Because the noise stream is keyed on
``(key, generation)`` and every recovery path either restores full
population participation (worker respawn + same-generation slice retry)
or re-runs the generation from the pre-fault state (rejection, skip,
checkpoint resume), a run that survived worker SIGKILLs, NaN bursts, a
checkpoint-write crash, and a SIGKILL of the whole process must end with
``params_flat`` IDENTICAL to an uninterrupted run of the same seed.

Chaos events are scheduled (resilience/chaos.py), never raced, so every
test here is deterministic.
"""

import json
import os
import signal
import time

import numpy as np
import pytest
import torch

from estorch_tpu import ES
from estorch_tpu.resilience import CHAOS_ENV, ChaosPlan, Supervisor, run_resilient
from estorch_tpu.resilience import chaos as chaos_mod


class TinyMLP(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.Tanh(), torch.nn.Linear(8, 2)
        )

    def forward(self, x):
        return self.net(x)


class QuadAgent:
    """Deterministic fitness — recovery bit-exactness needs an oracle."""

    target = 0.1

    def rollout(self, policy):
        with torch.no_grad():
            vec = torch.nn.utils.parameters_to_vector(policy.parameters())
            reward = -float(((vec - self.target) ** 2).sum())
        self.last_episode_steps = 1
        return reward


class AlwaysDeadAgent:
    def rollout(self, policy):
        raise RuntimeError("env permanently dead")


def _make_es(worker_mode="process", agent=QuadAgent):
    return ES(TinyMLP, agent, torch.optim.Adam, population_size=8,
              sigma=0.05, seed=3, optimizer_kwargs={"lr": 0.05},
              table_size=1 << 12, worker_mode=worker_mode)


def _child_factory():
    """Supervisor child factory (spawned: a FRESH interpreter whose jax
    backend is not yet initialized — pin it to CPU before anything can
    touch this image's axon default)."""
    from estorch_tpu.utils import force_cpu_backend

    force_cpu_backend(1)
    return _make_es("process")


# ---------------------------------------------------------------------
# ChaosPlan mechanics
# ---------------------------------------------------------------------

class TestChaosPlan:
    def test_parse_roundtrip_and_indexing(self):
        plan = ChaosPlan.parse(json.dumps({"events": [
            {"kind": "kill_worker", "gen": 5, "worker": 1},
            {"kind": "nan_fitness", "gen": 9, "member": "all"},
        ]}))
        assert [e["kind"] for e in plan.events_at(5)] == ["kill_worker"]
        assert plan.events_at(9, "nan_fitness")
        assert plan.events_at(9, "kill_worker") == []
        again = ChaosPlan.parse(plan.to_json())
        assert [e["kind"] for e in again.events] == \
            [e["kind"] for e in plan.events]

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown chaos event kind"):
            ChaosPlan([{"kind": "meteor", "gen": 1}])

    def test_fire_once_in_memory(self):
        plan = ChaosPlan([{"kind": "die", "gen": 1}])
        (ev,) = plan.events_at(1)
        assert plan.fire(ev) is True
        assert plan.fire(ev) is False

    def test_ledger_survives_process_restart(self, tmp_path):
        """A second plan instance (a restarted process) must see events
        the first instance fired — the property that stops a supervisor
        restart from replaying the SIGKILL that caused it forever."""
        ledger = str(tmp_path / "ledger")
        text = json.dumps({"events": [{"kind": "die", "gen": 12}],
                           "ledger": ledger})
        first = ChaosPlan.parse(text)
        assert first.fire(first.events_at(12)[0]) is True
        second = ChaosPlan.parse(text)  # "restarted" process
        assert second.fire(second.events_at(12)[0]) is False

    def test_generate_is_deterministic_in_seed(self):
        a = ChaosPlan.generate(seed=7, n_generations=50, kill_every=10,
                               n_workers=4, p_rollout_exc=0.2,
                               population_size=16)
        b = ChaosPlan.generate(seed=7, n_generations=50, kill_every=10,
                               n_workers=4, p_rollout_exc=0.2,
                               population_size=16)
        assert a.to_json() == b.to_json()
        assert len(a.events) >= 5  # the kills alone


# ---------------------------------------------------------------------
# ProcessPool: detection race, same-generation retry, respawn, close
# ---------------------------------------------------------------------

class TestProcessPoolRecovery:
    def test_dead_worker_bails_fast_and_slice_is_retried(self):
        """The satellite race: a worker that dies leaves nothing on its
        pipe — collection must notice in poll slices and retry its slice
        on the survivor, NOT block out the full generation timeout."""
        es = _make_es()
        try:
            es.train(1, n_proc=2, verbose=False)  # builds the pool
            pool = es.engine._proc_pool
            os.kill(pool._procs[0].pid, signal.SIGKILL)
            time.sleep(0.3)  # let the kill land
            offs = es.engine._pair_offsets(es.state)
            t0 = time.monotonic()
            fitness, _bc, _steps = pool.evaluate(
                es.state.params_flat, es.engine.sigma, offs,
                timeout_s=120.0, generation=int(es.state.generation))
            elapsed = time.monotonic() - t0
            # 120s timeout, dead pipe: the old code would sit out the full
            # timeout; slice-polling + retry must finish in seconds
            assert elapsed < 20.0
            # the survivor covered the dead worker's members: FULL
            # participation, and the values are the analytic truth
            assert np.isfinite(fitness).all()
            expected = np.array(
                [-float(((es.engine.member_theta(es.state, i) - 0.1) ** 2)
                        .sum()) for i in range(8)], np.float32)
            np.testing.assert_allclose(fitness, expected, rtol=1e-4,
                                       atol=1e-5)
        finally:
            es.engine.close()

    def test_chaos_kill_recovers_and_respawns_bit_exact(self, monkeypatch):
        """Worker kill at gen 1: the generation retries the slice (full
        participation, n_failed 0), the next generation respawns the
        worker, and the trained parameters equal a run never faulted."""
        clean = _make_es()
        try:
            clean.train(3, n_proc=2, verbose=False)
            clean_params = np.asarray(clean.state.params_flat).copy()
        finally:
            clean.engine.close()

        monkeypatch.setenv(CHAOS_ENV, json.dumps({"events": [
            {"kind": "kill_worker", "gen": 1, "worker": 0}]}))
        chaos_mod.reset_cache()
        es = _make_es()
        try:
            es.train(3, n_proc=2, verbose=False)
            assert [r["n_failed"] for r in es.history] == [0, 0, 0]
            pool = es.engine._proc_pool
            assert all(p.is_alive() for p in pool._procs)  # respawned
            assert es.obs.counters.get("workers_respawned") >= 1
            assert es.obs.counters.get("chaos_worker_kills") == 1
            assert es.obs.counters.get("members_retried") == 4
            np.testing.assert_array_equal(
                np.asarray(es.state.params_flat), clean_params)
        finally:
            es.engine.close()

    def test_close_reclaims_dead_worker_pipes_and_joins_respawned(
            self, monkeypatch):
        monkeypatch.setenv(CHAOS_ENV, json.dumps({"events": [
            {"kind": "kill_worker", "gen": 0, "worker": 1}]}))
        chaos_mod.reset_cache()
        es = _make_es()
        es.train(2, n_proc=2, verbose=False)  # gen 0 kill, gen 1 respawn
        pool = es.engine._proc_pool
        assert pool._retired, "respawn should have parked the corpse"
        everything = [*pool._procs, *pool._retired]
        pool.close()
        assert all(c.closed for c in pool._conns)
        assert all(not p.is_alive() for p in everything)
        assert pool._retired == []
        es.engine.close()

    def test_rollout_exc_in_fork_worker_is_nan_not_crash(self, monkeypatch):
        monkeypatch.setenv(CHAOS_ENV, json.dumps({"events": [
            {"kind": "rollout_exc", "gen": 0, "member": 5}]}))
        chaos_mod.reset_cache()
        es = _make_es()
        try:
            es.train(1, n_proc=2, verbose=False)
            assert es.history[0]["n_failed"] == 1
        finally:
            es.engine.close()


# ---------------------------------------------------------------------
# update anomaly guards (ES.train rejection policy)
# ---------------------------------------------------------------------

class TestAnomalyGuards:
    def test_nan_update_rejected_then_bit_exact(self, monkeypatch):
        """An injected non-finite update is rejected — previous state
        restored, counted, flight-recorded — and the re-run proceeds from
        the pre-fault state, ending bit-identical to a clean run."""
        clean = _make_es("thread")
        clean.train(4, verbose=False)
        clean_params = np.asarray(clean.state.params_flat).copy()

        monkeypatch.setenv(CHAOS_ENV, json.dumps({"events": [
            {"kind": "nan_update", "gen": 2}]}))
        chaos_mod.reset_cache()
        es = _make_es("thread")
        es.train(4, verbose=False)
        assert es.generation == 4  # the rejected attempt did not count
        assert es.obs.counters.get("generations_rejected") == 1
        assert any(e["name"] == "generation_rejected"
                   for e in es.obs.recorder.events())
        assert np.isfinite(np.asarray(es.state.params_flat)).all()
        np.testing.assert_array_equal(
            np.asarray(es.state.params_flat), clean_params)

    def test_nan_fitness_burst_rejected_then_bit_exact(self, monkeypatch):
        """A full-population NaN burst collapses the generation (<2
        valid); rejection + deterministic re-run keeps the trajectory."""
        clean = _make_es("thread")
        clean.train(3, verbose=False)
        clean_params = np.asarray(clean.state.params_flat).copy()

        monkeypatch.setenv(CHAOS_ENV, json.dumps({"events": [
            {"kind": "nan_fitness", "gen": 1, "member": "all"}]}))
        chaos_mod.reset_cache()
        es = _make_es("thread")
        es.train(3, verbose=False)
        assert es.generation == 3
        assert es.obs.counters.get("generations_rejected") == 1
        np.testing.assert_array_equal(
            np.asarray(es.state.params_flat), clean_params)

    def test_persistent_collapse_raises_with_state_intact(self):
        es = _make_es("thread", agent=AlwaysDeadAgent)
        before = np.asarray(es.state.params_flat).copy()
        with pytest.raises(RuntimeError, match="valid fitness"):
            es.train(1, verbose=False)
        # bounded retries: default cap rejected 4 attempts, then raised
        assert es.obs.counters.get("generations_rejected") == 4
        assert es.generation == 0
        np.testing.assert_array_equal(
            np.asarray(es.state.params_flat), before)


# ---------------------------------------------------------------------
# run_resilient: in-process skip/rollback
# ---------------------------------------------------------------------

class TestRunResilient:
    def test_checkpoint_write_crash_skipped_and_bit_exact(
            self, tmp_path, monkeypatch):
        """A crash INSIDE a checkpoint save rolls the finished generation
        back (it re-runs deterministically and re-saves); the crashed
        directory is not restorable and latest() skips past it."""
        from estorch_tpu.utils.checkpoint import PeriodicCheckpointer

        clean = _make_es("thread")
        clean.train(4, verbose=False)
        clean_params = np.asarray(clean.state.params_flat).copy()

        # every=2 saves after record gens 1 and 3 (es.generation 2 and 4);
        # the crash fires during the first of those saves
        monkeypatch.setenv(CHAOS_ENV, json.dumps({"events": [
            {"kind": "ckpt_crash", "gen": 2}]}))
        chaos_mod.reset_cache()
        es = _make_es("thread")
        ck = PeriodicCheckpointer(es, str(tmp_path / "cks"), every=2)
        run_resilient(es, 4, checkpointer=ck)
        assert es.generation == 4
        assert es.obs.counters.get("generations_skipped") == 1
        assert any(e["name"] == "generation_skipped"
                   for e in es.obs.recorder.events())
        np.testing.assert_array_equal(
            np.asarray(es.state.params_flat), clean_params)
        # the re-run re-saved the same directory, now finalized
        latest = ck.latest()
        assert latest is not None and latest.endswith("gen_00000003")
        assert os.path.isdir(os.path.join(str(tmp_path / "cks"),
                                          "gen_00000001", "state"))
        # exactly 4 records, no duplicate from the rolled-back attempt
        assert [r["generation"] for r in es.history] == [0, 1, 2, 3]

    def test_persistent_failure_reraises(self):
        es = _make_es("thread", agent=AlwaysDeadAgent)
        with pytest.raises(RuntimeError, match="valid fitness"):
            run_resilient(es, 2, max_consecutive_skips=1)


# ---------------------------------------------------------------------
# Supervisor: the end-to-end chaos demo (acceptance criterion)
# ---------------------------------------------------------------------

class TestSupervisor:
    def test_chaos_run_supervised_to_bit_exact_completion(
            self, tmp_path, monkeypatch, capsys):
        """THE deterministic chaos demo: worker SIGKILL at gen 5, a full
        NaN-fitness burst at gen 9, a checkpoint-write crash at gen 8's
        save, and SIGKILL of the whole training process at gen 12 — the
        Supervisor drives the run to generation 16, and the final
        params_flat is BIT-IDENTICAL to an uninterrupted run of the same
        seed on the host backend."""
        clean = _make_es("process")
        try:
            clean.train(16, n_proc=2, verbose=False)
            clean_params = np.asarray(clean.state.params_flat).copy()
        finally:
            clean.engine.close()

        root = tmp_path / "run"
        plan = {"events": [
            {"kind": "kill_worker", "gen": 5, "worker": 0},
            {"kind": "ckpt_crash", "gen": 8},
            {"kind": "nan_fitness", "gen": 9, "member": "all"},
            {"kind": "die", "gen": 12},
        ], "ledger": str(tmp_path / "chaos_ledger")}
        monkeypatch.setenv(CHAOS_ENV, json.dumps(plan))
        chaos_mod.reset_cache()

        sup = Supervisor(_child_factory, str(root), target_generation=16,
                         every=4, n_proc=2, max_restarts=3,
                         backoff_s=0.1, poll_s=0.25,
                         startup_grace_s=300.0)
        res = sup.run()
        assert res["ok"], f"supervisor failed: {res}"
        assert len(res["restarts"]) == 1  # exactly the gen-12 SIGKILL
        assert res["restarts"][0]["exitcode"] == -signal.SIGKILL

        # resume is bit-exact: restore the final checkpoint and compare
        from estorch_tpu.utils.checkpoint import restore_checkpoint

        es = _make_es("process")
        try:
            restore_checkpoint(es, res["checkpoint"])
            assert es.generation == 16
            np.testing.assert_array_equal(
                np.asarray(es.state.params_flat), clean_params)
        finally:
            es.engine.close()

        # restart provenance + cross-restart counters in the manifest:
        # the SIGKILLed child's rejected/skipped counters survive via its
        # last heartbeat
        with open(root / "manifest.json") as f:
            manifest = json.load(f)
        resil = manifest["resilience"]
        assert resil["completed"] is True
        assert resil["restart_count"] == 1
        assert resil["counters"]["generations_rejected"] >= 1  # NaN burst
        assert resil["counters"]["generations_skipped"] >= 1  # ckpt crash
        assert resil["counters"]["workers_respawned"] >= 1  # gen-5 kill

        # every trained generation logged exactly once across both child
        # processes (the rolled-back attempts never reached the sink)
        from estorch_tpu.obs.summarize import load_records

        records = load_records(str(root / "run.jsonl"))
        assert [r["generation"] for r in records] == list(range(16))
        assert all(r["n_failed"] == 0 for r in records)  # full participation

        # `python -m estorch_tpu.obs summarize` surfaces the chaos run's
        # rejection + restart counters (acceptance criterion)
        from estorch_tpu.obs.__main__ import main as obs_main

        rc = obs_main(["summarize", str(root / "run.jsonl")])
        out = capsys.readouterr().out
        assert rc == 0
        assert "generations_rejected" in out
        assert "restarts         1" in out

    @pytest.mark.slow
    def test_wedged_child_killed_by_heartbeat_watchdog_and_resumed(
            self, tmp_path, monkeypatch):
        """A child that stops beating (chaos wedge: a long silent sleep)
        is killed by the staleness watchdog and the run resumes from the
        last checkpoint to the same final parameters.  Slow-marked: two
        child spawns + the staleness detection window (~80s); the
        non-slow acceptance test above already exercises the supervisor's
        death-detection restart path."""
        clean = _make_es("process")
        try:
            clean.train(4, n_proc=2, verbose=False)
            clean_params = np.asarray(clean.state.params_flat).copy()
        finally:
            clean.engine.close()

        root = tmp_path / "run"
        plan = {"events": [
            {"kind": "wedge", "gen": 2, "sleep_s": 300.0},
        ], "ledger": str(tmp_path / "chaos_ledger")}
        monkeypatch.setenv(CHAOS_ENV, json.dumps(plan))
        chaos_mod.reset_cache()

        # stale_after must exceed the slowest legitimate inter-beat gap
        # (child-side setup IO on this loaded 1-core box) while staying
        # far below the 300s wedge sleep it exists to catch
        sup = Supervisor(_child_factory, str(root), target_generation=4,
                         every=1, n_proc=2, max_restarts=2,
                         backoff_s=0.1, poll_s=0.25,
                         stale_after_s=10.0, startup_grace_s=300.0)
        res = sup.run()
        assert res["ok"], f"supervisor failed: {res}"
        assert len(res["restarts"]) == 1
        assert "stale" in res["restarts"][0]["reason"]

        from estorch_tpu.utils.checkpoint import restore_checkpoint

        es = _make_es("process")
        try:
            restore_checkpoint(es, res["checkpoint"])
            assert es.generation == 4
            np.testing.assert_array_equal(
                np.asarray(es.state.params_flat), clean_params)
        finally:
            es.engine.close()


# ---------------------------------------------------------------------
# deterministic interleaving harness (resilience/interleave.py)
# ---------------------------------------------------------------------

class _Counter:
    """Shared state with a deliberately torn read-modify-write."""

    def __init__(self):
        self.n = 0


def _racy_workers(box, per_worker=20):
    def worker():
        for _ in range(per_worker):
            cur = box.n
            cur = cur + 1
            box.n = cur
    return [worker, worker]


class TestInterleaver:
    def test_same_seed_is_bit_identical(self):
        """The acceptance criterion: a seeded run replays exactly —
        same schedule, same switches, same final (racy) state."""
        from estorch_tpu.resilience import run_interleaved

        runs = []
        for _ in range(2):
            box = _Counter()
            runs.append((run_interleaved(_racy_workers(box), seed=1234),
                         box.n))
        (r1, n1), (r2, n2) = runs
        assert r1.replays(r2)
        assert r1.schedule == r2.schedule
        assert r1.switches == r2.switches
        assert n1 == n2

    def test_a_seed_exists_that_loses_updates(self):
        """The harness's reason to exist: some seed interleaves the
        read-modify-write so updates vanish — deterministically."""
        from estorch_tpu.resilience import run_interleaved

        losing = None
        for seed in range(32):
            box = _Counter()
            run_interleaved(_racy_workers(box), seed=seed)
            if box.n < 40:
                losing = seed
                break
        assert losing is not None, "no seed exposed the race"
        # the losing seed is a reproducer: same seed, same loss
        box_a, box_b = _Counter(), _Counter()
        ra = run_interleaved(_racy_workers(box_a), seed=losing)
        rb = run_interleaved(_racy_workers(box_b), seed=losing)
        assert ra.replays(rb)
        assert box_a.n == box_b.n < 40

    def test_different_seeds_differ(self):
        from estorch_tpu.resilience import run_interleaved

        schedules = set()
        for seed in range(6):
            box = _Counter()
            schedules.add(
                run_interleaved(_racy_workers(box), seed=seed).schedule)
        assert len(schedules) > 1

    def test_cooplock_fixes_every_seed(self):
        """The fix side: the SAME seeds that lose updates bare are
        correct under CoopLock, and stay deterministic."""
        from estorch_tpu.resilience import CoopLock, Interleaver

        for seed in range(8):
            box = _Counter()
            holder = []

            def worker():
                for _ in range(20):
                    with holder[0]:
                        cur = box.n
                        cur = cur + 1
                        box.n = cur

            itl = Interleaver([worker, worker], seed=seed)
            holder.append(CoopLock(itl))
            itl.run()
            assert box.n == 40, f"seed {seed} lost updates under lock"

    def test_values_and_errors_propagate(self):
        from estorch_tpu.resilience import run_interleaved

        res = run_interleaved([lambda: "a", lambda: "b"], seed=0)
        assert res.values == ("a", "b")

        def boom():
            raise ValueError("torn")

        with pytest.raises(ValueError, match="torn"):
            run_interleaved([boom, lambda: None], seed=0)

    def test_runaway_loop_fails_fast(self):
        from estorch_tpu.resilience import DeadlockError, run_interleaved

        def spin():
            while True:
                pass

        with pytest.raises(DeadlockError):
            run_interleaved([spin, spin], seed=0, max_steps=200)
