"""Decomposed population forward: z = x@W + c(x@E) must be EXACTLY the
standard materialized-weights path (it is a reordering, not an
approximation), across feature combinations."""

import numpy as np
import optax
import pytest

import jax

from estorch_tpu import ES, JaxAgent, MLPPolicy, PooledAgent
from estorch_tpu.envs import CartPole, Pendulum


def _pair(decomposed, **over):
    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=32,
        sigma=0.1,
        seed=0,
        policy_kwargs={"action_dim": 2, "hidden": (16,)},
        agent_kwargs={"env": CartPole(), "horizon": 60},
        optimizer_kwargs={"learning_rate": 2e-2},
        table_size=1 << 16,
    )
    kw.update(over)
    return ES(decomposed=decomposed, **kw)


def _assert_equivalent(a, b, gens=3, exact=True, params_atol=1e-3):
    """``exact`` asserts tight float tolerance (the decomposition reorders
    IEEE sums, so bitwise equality would be flaky by construction — observed
    bit-identical today, but a near-tie argmax flip under a last-ulp logit
    difference is allowed to move one fitness value).  ``params_atol``
    loosens only the non-exact params check: in bf16 a rounding-induced
    argmax flip changes one member's whole fitness, which moves that
    member's rank weight and compounds through the update — the
    trajectories stay close (reward assert), not identical."""
    a.train(gens, verbose=False)
    b.train(gens, verbose=False)
    for ra, rb in zip(a.history, b.history):
        tol = 1e-6 if exact else 5e-2
        assert ra["reward_mean"] == pytest.approx(rb["reward_mean"], rel=tol, abs=1.0)
    pa = np.asarray(a.state.params_flat)
    pb = np.asarray(b.state.params_flat)
    if exact:
        np.testing.assert_allclose(pa, pb, rtol=1e-4, atol=1e-5)
    else:
        np.testing.assert_allclose(pa, pb, rtol=1e-3, atol=params_atol)


class TestDecomposedEquivalence:
    def test_identical_to_standard_path(self):
        _assert_equivalent(_pair(False), _pair(True))

    def test_identical_with_unmirrored_and_annealing(self):
        over = dict(mirrored=False, sigma_decay=0.9, sigma_min=0.02)
        _assert_equivalent(_pair(False, **over), _pair(True, **over))

    def test_continuous_with_episodes_matches_to_rounding(self):
        """Continuous rewards accumulate transcendental terms, so reordered
        matmul rounding shows at ~1e-7 — tolerance, not exactness, here."""
        over = dict(
            policy_kwargs={"action_dim": 1, "hidden": (16,), "discrete": False,
                           "action_scale": 2.0},
            agent_kwargs={"env": Pendulum(), "horizon": 40},
            episodes_per_member=2,
        )
        _assert_equivalent(_pair(False, **over), _pair(True, **over), exact=False)

    def test_bf16_close_to_standard_bf16(self):
        # bf16 admits a near-tie argmax flip between the two orderings
        # (observed on XLA:CPU jax 0.4: one flipped member ⇒ ~5e-2 param
        # drift over 3 gens); f32 exactness above pins the identity itself
        over = dict(compute_dtype="bfloat16")
        _assert_equivalent(_pair(False, **over), _pair(True, **over),
                           exact=False, params_atol=0.1)


class TestDecomposedValidation:
    def test_vbn_rejected(self):
        with pytest.raises(ValueError, match="decomposed"):
            _pair(True, policy_kwargs={"action_dim": 2, "hidden": (16,),
                                       "use_vbn": True})

    def test_host_rejected(self):
        import torch

        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.l = torch.nn.Linear(4, 2)

            def forward(self, x):
                return self.l(x)

        class A:
            def rollout(self, policy):
                return 0.0

        with pytest.raises(ValueError, match="device-path"):
            ES(P, A, __import__("torch").optim.Adam, population_size=8,
               optimizer_kwargs={"lr": 1e-2}, table_size=1 << 12,
               decomposed=True)

    def test_pooled_rejected(self):
        with pytest.raises(ValueError, match="device-path"):
            _pair(True, agent=PooledAgent,
                  agent_kwargs={"env_name": "cartpole", "horizon": 30})