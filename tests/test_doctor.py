"""Environment doctor (estorch_tpu/doctor.py).

The device probe itself runs a REAL subprocess against whatever backend
this machine has — in CI that may be healthy CPU or a wedged tunnel — so
the tests pin the classifier's behavior on controlled child processes and
the report's shape, not the machine's health.
"""

import pytest

import json
import sys

from estorch_tpu import doctor


class TestProbeClassifier:
    def test_healthy_parse(self, monkeypatch):
        """A child that prints PROBE_OK is classified healthy with fields."""
        monkeypatch.setattr(doctor, "_PROBE", "print('PROBE_OK cpu 8')")
        out = doctor.probe_device(timeout_s=60)
        assert out == {"status": "healthy", "platform": "cpu",
                       "n_devices": 8}

    @pytest.mark.slow
    def test_wedge_detected_by_timeout_with_stderr_clue(self, monkeypatch):
        """A child that hangs past the timeout is classified wedged, and
        whatever it wrote to stderr before hanging survives in the report
        (the only clue about WHERE the runtime hung)."""
        monkeypatch.setattr(doctor, "_PROBE", (
            "import sys, time\n"
            "sys.stderr.write('initializing device plugin...')\n"
            "sys.stderr.flush()\n"
            "time.sleep(60)\n"
        ))
        # interpreter startup alone can take ~5s here (site hooks import
        # the device plugin); give the child time to reach its writes
        out = doctor.probe_device(timeout_s=12)
        assert out["status"] == "wedged"
        assert out["timeout_s"] == 12
        assert "initializing device plugin" in out["stderr_tail"]

    def test_fast_failure_is_error_not_wedge(self, monkeypatch):
        """A child that raises quickly is an init error with stderr tail."""
        monkeypatch.setattr(doctor, "_PROBE",
                            "raise RuntimeError('backend exploded')")
        out = doctor.probe_device(timeout_s=60)
        assert out["status"] == "error"
        assert "backend exploded" in out["stderr_tail"]


class TestOptionalDeps:
    def test_missing_parent_package_never_crashes(self, monkeypatch):
        """find_spec('pkg.sub') raises ModuleNotFoundError when pkg itself
        is absent; the report must say unavailable, not traceback."""
        import importlib.util as ilu

        real = ilu.find_spec

        def raising(name, *a, **k):
            if name.startswith("mujoco"):
                raise ModuleNotFoundError("No module named 'mujoco'")
            return real(name, *a, **k)

        monkeypatch.setattr(ilu, "find_spec", raising)
        out = doctor.check_optional_deps()
        assert out["mujoco.mjx"]["available"] is False
        assert out["mujoco"]["available"] is False
        assert out["gymnasium"]["available"] is True


class TestReport:
    def test_report_shape_and_hints(self, monkeypatch):
        monkeypatch.setattr(doctor, "probe_device",
                            lambda timeout_s: {"status": "wedged",
                                               "timeout_s": timeout_s})
        rep = doctor.report()
        assert rep["device"]["status"] == "wedged"
        assert "cpu" in rep["hint"]
        assert isinstance(rep["native"]["cpp_pool"], bool)
        assert rep["optional"]["gymnasium"]["available"] is True

    def test_cli_json_and_exit_code(self, monkeypatch, capsys):
        monkeypatch.setattr(doctor, "probe_device",
                            lambda timeout_s: {"status": "healthy",
                                               "platform": "cpu",
                                               "n_devices": 8})
        rc = doctor.main(["--timeout", "5"])
        rep = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert rep["device"]["platform"] == "cpu"
        assert "hint" not in rep
