"""Environment doctor (estorch_tpu/doctor.py).

The device probe itself runs a REAL subprocess against whatever backend
this machine has — in CI that may be healthy CPU or a wedged tunnel — so
the tests pin the classifier's behavior on controlled child processes and
the report's shape, not the machine's health.
"""

import pytest

import json
import sys

from estorch_tpu import doctor


class TestProbeClassifier:
    def test_healthy_parse(self, monkeypatch):
        """A child that prints PROBE_OK is classified healthy with fields."""
        monkeypatch.setattr(doctor, "_PROBE", "print('PROBE_OK cpu 8')")
        out = doctor.probe_device(timeout_s=60)
        assert out == {"status": "healthy", "platform": "cpu",
                       "n_devices": 8}

    @pytest.mark.slow
    def test_wedge_detected_by_timeout_with_stderr_clue(self, monkeypatch):
        """A child that hangs past the timeout is classified wedged, and
        whatever it wrote to stderr before hanging survives in the report
        (the only clue about WHERE the runtime hung)."""
        monkeypatch.setattr(doctor, "_PROBE", (
            "import sys, time\n"
            "sys.stderr.write('initializing device plugin...')\n"
            "sys.stderr.flush()\n"
            "time.sleep(60)\n"
        ))
        # interpreter startup alone can take ~5s here (site hooks import
        # the device plugin); give the child time to reach its writes
        out = doctor.probe_device(timeout_s=12)
        assert out["status"] == "wedged"
        assert out["timeout_s"] == 12
        assert "initializing device plugin" in out["stderr_tail"]

    def test_fast_failure_is_error_not_wedge(self, monkeypatch):
        """A child that raises quickly is an init error with stderr tail."""
        monkeypatch.setattr(doctor, "_PROBE",
                            "raise RuntimeError('backend exploded')")
        out = doctor.probe_device(timeout_s=60)
        assert out["status"] == "error"
        assert "backend exploded" in out["stderr_tail"]


class TestCheckDevice:
    """The typed staged probe (check_device): reason-code taxonomy on
    the pure classifier, hang classification on controlled children that
    wedge at a KNOWN stage, and the healthy path against this image's
    CPU backend."""

    def test_classifier_taxonomy(self):
        c = doctor.classify_device_probe
        ok = "PROBE_START\nPROBE_JAX_OK\nPROBE_DEVICES_OK cpu 1\n" \
             "PROBE_COMPILE_OK\nPROBE_EXEC_OK\n"
        assert c(ok, False, 0) == ("ok", None)
        assert c("", True, None) == ("failed", "init-hang")
        assert c("PROBE_START\nPROBE_JAX_OK\n", True, None) == \
            ("failed", "init-hang")
        assert c("PROBE_START\nPROBE_JAX_OK\nPROBE_DEVICES_OK cpu 1\n",
                 True, None) == ("failed", "compile-hang")
        assert c("PROBE_START\nPROBE_JAX_OK\nPROBE_DEVICES_OK cpu 1\n"
                 "PROBE_COMPILE_OK\n", True, None) == \
            ("failed", "exec-hang")
        # failed FAST before device init: the backend said no — not a wedge
        assert c("PROBE_START\nPROBE_JAX_OK\n", False, 1) == \
            ("failed", "no-device")
        # failed fast AFTER devices existed: error, read the stderr
        assert c("PROBE_START\nPROBE_JAX_OK\nPROBE_DEVICES_OK cpu 1\n",
                 False, 1) == ("failed", "error")

    def test_healthy_cpu_probe_is_fast_and_typed(self):
        """On this image's CPU backend the full staged probe (import →
        devices → compile → execute) must come back ok in seconds — the
        <30s platform-decision contract bench.py builds on."""
        out = doctor.check_device(timeout_s=60.0)
        assert out["status"] == "ok"
        assert out["platform"] == "cpu"
        assert out["n_devices"] >= 1
        assert "reason" not in out
        assert out["elapsed_s"] < 30.0

    def test_compile_hang_classified(self, monkeypatch):
        monkeypatch.setattr(doctor, "_STAGED_PROBE", (
            'print("PROBE_START", flush=True)\n'
            'print("PROBE_JAX_OK", flush=True)\n'
            'print("PROBE_DEVICES_OK cpu 1", flush=True)\n'
            "import time; time.sleep(60)\n"))
        out = doctor.check_device(timeout_s=1.0)
        assert out["status"] == "failed"
        assert out["reason"] == "compile-hang"
        assert out["platform"] == "cpu"  # the layer that DID answer

    def test_init_hang_classified(self, monkeypatch):
        monkeypatch.setattr(doctor, "_STAGED_PROBE", (
            'print("PROBE_START", flush=True)\n'
            "import time; time.sleep(60)\n"))
        out = doctor.check_device(timeout_s=1.0)
        assert out["status"] == "failed"
        assert out["reason"] == "init-hang"

    def test_no_device_failure_is_fast(self, monkeypatch):
        monkeypatch.setattr(doctor, "_STAGED_PROBE", (
            'print("PROBE_START", flush=True)\n'
            'import sys\n'
            'print("no backend here", file=sys.stderr)\n'
            "sys.exit(1)\n"))
        out = doctor.check_device(timeout_s=30.0)
        assert out["status"] == "failed"
        assert out["reason"] == "no-device"
        assert "no backend here" in out["stderr_tail"]
        assert out["elapsed_s"] < 10.0

    def test_platform_pin_reaches_child(self, monkeypatch):
        monkeypatch.setattr(doctor, "_STAGED_PROBE", (
            "import os, sys\n"
            'plat = os.environ.get("JAX_PLATFORMS", "unset")\n'
            'print("PROBE_DEVICES_OK", plat, 1, flush=True)\n'
            "sys.exit(1)\n"))
        out = doctor.check_device(timeout_s=30.0, platform="tpu")
        # the stub echoes the env pin back through the DEVICES marker
        assert out["requested_platform"] == "tpu"
        assert out["platform"] == "tpu"

    def test_report_gains_device_probe_row(self, monkeypatch):
        monkeypatch.setattr(
            doctor, "check_device",
            lambda timeout_s=20.0, platform=None: {
                "status": "failed", "reason": "init-hang",
                "elapsed_s": timeout_s, "timeout_s": timeout_s})
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rep = doctor.report(timeout_s=5)
        assert rep["device_probe"]["reason"] == "init-hang"
        # ONE staged probe serves both rows: the legacy device summary
        # is derived from the same verdict (a *-hang reason = wedged),
        # so a wedged host pays one timeout, not two serial ones
        assert rep["device"]["status"] == "wedged"
        assert rep["device"]["timeout_s"] == 5


class TestMeshCheck:
    """The param-sharded mesh probe (check_mesh): can the 2-D virtual
    CPU mesh build, the default partition rules resolve, and one donated
    sharded program compile+execute here?  (docs/sharding.md)"""

    def test_classifier_taxonomy(self):
        c = doctor.classify_mesh_probe
        ok = ("MESH_START\nMESH_BUILD_OK 8\nMESH_RULES_OK\n"
              "MESH_COMPILE_OK\nMESH_EXEC_OK\n")
        assert c(ok, False, 0) == ("ok", None)
        assert c("MESH_START\n", True, None) == ("failed", "mesh-build")
        assert c("MESH_START\nMESH_BUILD_OK 8\n", False, 1) == \
            ("failed", "partition-rules")
        assert c("MESH_START\nMESH_BUILD_OK 8\nMESH_RULES_OK\n",
                 True, None) == ("failed", "sharded-compile")
        assert c("MESH_START\nMESH_BUILD_OK 8\nMESH_RULES_OK\n"
                 "MESH_COMPILE_OK\n", False, 1) == \
            ("failed", "sharded-exec")

    def test_healthy_mesh_probe(self):
        out = doctor.check_mesh(timeout_s=120.0)
        assert out["status"] == "ok", out
        assert "failed_stage" not in out

    def test_failing_stage_named(self, monkeypatch):
        monkeypatch.setattr(doctor, "_MESH_PROBE", (
            'print("MESH_START", flush=True)\n'
            'print("MESH_BUILD_OK 8", flush=True)\n'
            'raise RuntimeError("no rules for you")\n'))
        out = doctor.check_mesh(timeout_s=30.0)
        assert out["status"] == "failed"
        assert out["failed_stage"] == "partition-rules"
        assert "no rules for you" in out["stderr_tail"]

    def test_report_gains_mesh_row(self, monkeypatch):
        """report() carries the mesh verdict without re-running the
        heavy probe here (stubbed like the device row's test)."""
        monkeypatch.setattr(doctor, "check_mesh",
                            lambda **kw: {"status": "ok", "elapsed_s": 0.1,
                                          "timeout_s": 90.0})
        monkeypatch.setattr(doctor, "check_device",
                            lambda timeout_s=20.0, platform=None: {
                                "status": "ok", "platform": "cpu",
                                "n_devices": 8, "elapsed_s": 0.1,
                                "timeout_s": timeout_s})
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rep = doctor.report(timeout_s=5.0)
        assert rep["mesh"]["status"] == "ok"


class TestScenariosCheck:
    """The scenario-suite probe (check_scenarios): deterministic
    distribution draws + one tiny traced-operand rollout across 3
    variants (docs/scenarios.md), findings-not-tracebacks on failure."""

    def test_classifier_taxonomy(self):
        c = doctor.classify_scenario_probe
        ok = "SCEN_START\nSCEN_DRAW_OK\nSCEN_ROLLOUT_OK\n"
        assert c(ok, False, 0) == ("ok", None)
        assert c("SCEN_START\n", True, None) == \
            ("failed", "draw-determinism")
        assert c("SCEN_START\nSCEN_DRAW_OK\n", False, 1) == \
            ("failed", "traced-rollout")
        # all markers but a dirty exit: the last stage takes the blame
        assert c(ok, False, 1) == ("failed", "traced-rollout")

    def test_healthy_scenario_probe(self):
        out = doctor.check_scenarios(timeout_s=120.0)
        assert out["status"] == "ok", out
        assert "failed_stage" not in out

    def test_failing_stage_named_not_raised(self, monkeypatch):
        monkeypatch.setattr(doctor, "_SCENARIO_PROBE", (
            'print("SCEN_START", flush=True)\n'
            'print("SCEN_DRAW_OK", flush=True)\n'
            'raise RuntimeError("variant rollout exploded")\n'))
        out = doctor.check_scenarios(timeout_s=30.0)
        assert out["status"] == "failed"
        assert out["failed_stage"] == "traced-rollout"
        assert "variant rollout exploded" in out["stderr_tail"]

    def test_report_gains_scenarios_row(self, monkeypatch):
        monkeypatch.setattr(doctor, "check_scenarios",
                            lambda **kw: {"status": "ok", "elapsed_s": 0.1,
                                          "timeout_s": 90.0})
        monkeypatch.setattr(doctor, "check_mesh",
                            lambda **kw: {"status": "ok", "elapsed_s": 0.1,
                                          "timeout_s": 90.0})
        monkeypatch.setattr(doctor, "check_device",
                            lambda timeout_s=20.0, platform=None: {
                                "status": "ok", "platform": "cpu",
                                "n_devices": 8, "elapsed_s": 0.1,
                                "timeout_s": timeout_s})
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rep = doctor.report(timeout_s=5.0)
        assert rep["scenarios"]["status"] == "ok"


class TestElasticCheck:
    """The elastic multi-host probe (check_elastic): staged subprocess —
    2-process jax.distributed bring-up over loopback (Gloo CPU
    collectives) → cross-process mesh → one cross-process psum →
    the jax-free coordinator TCP round-trip (docs/multihost.md);
    findings-not-tracebacks, the first missing marker names the layer."""

    def test_classifier_taxonomy(self):
        c = doctor.classify_elastic_probe
        ok = ("ELASTIC_START\nELASTIC_INIT_OK\nELASTIC_MESH_OK\n"
              "ELASTIC_PSUM_OK\nELASTIC_COORD_OK\n")
        assert c(ok, False, 0) == ("ok", None)
        assert c("ELASTIC_START\n", True, None) == \
            ("failed", "distributed-init")
        assert c("ELASTIC_START\nELASTIC_INIT_OK\n", False, 1) == \
            ("failed", "mesh-build")
        assert c("ELASTIC_START\nELASTIC_INIT_OK\nELASTIC_MESH_OK\n",
                 False, 1) == ("failed", "cross-process-psum")
        # all markers but a dirty exit: the last stage takes the blame
        assert c(ok, False, 1) == ("failed", "coordinator-roundtrip")

    def test_healthy_elastic_probe(self):
        out = doctor.check_elastic(timeout_s=120.0)
        assert out["status"] == "ok", out
        assert "failed_stage" not in out

    def test_failing_stage_named_not_raised(self, monkeypatch):
        monkeypatch.setattr(doctor, "_ELASTIC_PROBE", (
            'print("ELASTIC_START", flush=True)\n'
            'print("ELASTIC_INIT_OK", flush=True)\n'
            'raise RuntimeError("no cross-process mesh here")\n'))
        out = doctor.check_elastic(timeout_s=30.0)
        assert out["status"] == "failed"
        assert out["failed_stage"] == "mesh-build"
        assert "no cross-process mesh here" in out["stderr_tail"]

    def test_report_gains_elastic_row(self, monkeypatch):
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "failed",
                                          "failed_stage": "distributed-init",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        monkeypatch.setattr(doctor, "check_mesh",
                            lambda **kw: {"status": "ok"})
        monkeypatch.setattr(doctor, "check_scenarios",
                            lambda **kw: {"status": "ok"})
        monkeypatch.setattr(doctor, "check_device",
                            lambda timeout_s=20.0, platform=None: {
                                "status": "ok", "platform": "cpu",
                                "n_devices": 8, "elapsed_s": 0.1,
                                "timeout_s": timeout_s})
        rep = doctor.report(timeout_s=5.0)
        assert rep["elastic"]["failed_stage"] == "distributed-init"


class TestOptionalDeps:
    def test_missing_parent_package_never_crashes(self, monkeypatch):
        """find_spec('pkg.sub') raises ModuleNotFoundError when pkg itself
        is absent; the report must say unavailable, not traceback."""
        import importlib.util as ilu

        real = ilu.find_spec

        def raising(name, *a, **k):
            if name.startswith("mujoco"):
                raise ModuleNotFoundError("No module named 'mujoco'")
            return real(name, *a, **k)

        monkeypatch.setattr(ilu, "find_spec", raising)
        out = doctor.check_optional_deps()
        assert out["mujoco.mjx"]["available"] is False
        assert out["mujoco"]["available"] is False
        assert out["gymnasium"]["available"] is True


class TestObsCheck:
    def test_trace_dir_and_tensorboard_reported(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("ESTORCH_OBS_DIR", str(tmp_path))
        out = doctor.check_obs()
        assert out["trace_dir"]["path"] == str(tmp_path)
        assert out["trace_dir"]["writable"] is True
        assert isinstance(out["tensorboard"]["available"], bool)
        assert "heartbeat" not in out  # no run dir given

    def test_unwritable_trace_dir_never_crashes(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("ESTORCH_OBS_DIR",
                           str(tmp_path / "does" / "not" / "exist"))
        out = doctor.check_obs()
        assert out["trace_dir"]["writable"] is False
        assert "error" in out["trace_dir"]

    def test_heartbeat_fresh_vs_stale_vs_missing(self, tmp_path):
        import time

        from estorch_tpu.obs import Heartbeat
        from estorch_tpu.obs.recorder import STALE_AFTER_S

        out = doctor.check_obs(str(tmp_path))
        assert out["heartbeat"]["found"] is False
        assert "hint" in out["heartbeat"]

        Heartbeat(str(tmp_path / "heartbeat.json")).beat("eval", 5)
        out = doctor.check_obs(str(tmp_path))
        hb = out["heartbeat"]
        assert hb["found"] is True and hb["stale"] is False
        assert hb["phase"] == "eval" and hb["generation"] == 5

        with open(tmp_path / "heartbeat.json", "w") as f:
            json.dump({"ts": time.time() - 10 * STALE_AFTER_S,
                       "pid": 1, "phase": "device", "generation": 2}, f)
        out = doctor.check_obs(str(tmp_path))
        assert out["heartbeat"]["stale"] is True
        assert out["heartbeat"]["age_s"] > STALE_AFTER_S

    def test_export_probe_scrapes_and_parses(self):
        """The export probe: loopback-scrape the metrics sidecar over a
        synthetic temp run-dir and validate the exposition parses, with
        the published+live counter composition checked end to end."""
        out = doctor.check_obs()
        probe = out["export"]
        assert probe["ok"] is True, probe
        assert probe["samples"] > 0

    def test_export_probe_failure_is_reported_not_raised(self,
                                                         monkeypatch):
        """A diagnostic tool never crashes the report — a broken sidecar
        surfaces as ok=False with the error."""
        from estorch_tpu.obs.export import sidecar as sidecar_mod

        def boom(*a, **k):
            raise RuntimeError("bind refused")

        monkeypatch.setattr(sidecar_mod.MetricsSidecar, "__init__", boom)
        probe = doctor.check_obs()["export"]
        assert probe["ok"] is False
        assert "bind refused" in probe["error"]


class TestCollectorCheck:
    def test_collector_probe_end_to_end(self):
        """check_collector: synthetic sidecar target + dead port under a
        real collector for one tick — stored sample, rules evaluation
        (dead fires, live doesn't), /alerts and /metrics parse."""
        out = doctor.check_collector()
        assert out["ok"] is True, out

    def test_refused_port_never_crashes_the_report(self, monkeypatch):
        """The ISSUE's explicit hazard: a host that cannot bind loopback
        must get a finding, not a traceback."""
        from estorch_tpu.obs.agg import collector as collector_mod

        def boom(*a, **k):
            raise OSError("port refused")

        monkeypatch.setattr(collector_mod.Collector, "__init__", boom)
        out = doctor.check_collector()
        assert out["ok"] is False
        assert "port refused" in out["error"]

    def test_report_gains_collector_row(self, monkeypatch):
        """report() carries the collector verdict (heavy probes stubbed
        like the device/mesh row tests)."""
        monkeypatch.setattr(doctor, "check_mesh",
                            lambda **kw: {"status": "ok"})
        monkeypatch.setattr(doctor, "check_device",
                            lambda timeout_s=20.0, platform=None: {
                                "status": "ok", "platform": "cpu",
                                "n_devices": 8, "elapsed_s": 0.1,
                                "timeout_s": timeout_s})
        monkeypatch.setattr(doctor, "check_collector",
                            lambda: {"ok": True})
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rep = doctor.report(timeout_s=5.0)
        assert rep["collector"] == {"ok": True}


class TestRouterCheck:
    def test_router_probe_failover_end_to_end(self):
        """check_router: a 2-replica toy fleet behind a real Router —
        kill one replica, the next requests must still answer (retry on
        the survivor) and /metrics must parse with the per-replica
        breaker gauges."""
        out = doctor.check_router()
        assert out["ok"] is True, out
        assert out["retries"] >= 1  # the probe's health is STALE by
        # design, so failover HAD to go through the retry budget
        assert out["breakers"]["ra"] == "open"
        assert out["breakers"]["rb"] == "closed"

    def test_router_probe_never_crashes_the_report(self, monkeypatch):
        from estorch_tpu.serve import router as router_mod

        def boom(*a, **k):
            raise OSError("no loopback")

        monkeypatch.setattr(router_mod.Router, "__init__", boom)
        out = doctor.check_router()
        assert out["ok"] is False
        assert "no loopback" in out["error"]

    def test_report_gains_router_row(self, monkeypatch):
        monkeypatch.setattr(doctor, "check_mesh",
                            lambda **kw: {"status": "ok"})
        monkeypatch.setattr(doctor, "check_device",
                            lambda timeout_s=20.0, platform=None: {
                                "status": "ok", "platform": "cpu",
                                "n_devices": 8, "elapsed_s": 0.1,
                                "timeout_s": timeout_s})
        monkeypatch.setattr(doctor, "check_collector",
                            lambda: {"ok": True})
        monkeypatch.setattr(doctor, "check_router",
                            lambda: {"ok": True, "retries": 1})
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rep = doctor.report(timeout_s=5.0)
        assert rep["router"] == {"ok": True, "retries": 1}


class TestTracingCheck:
    def test_tracing_probe_assembles_across_processes(self):
        """check_tracing: one forced-sampled request through a real
        Router to a tracer-equipped toy replica must assemble into a
        single trace spanning both processes, with a cross-process hop
        and a schema-clean Perfetto export."""
        out = doctor.check_tracing()
        assert out["ok"] is True, out
        assert out["procs"] == ["router", "replica"]
        assert out["segments"] >= 3  # route + upstream leg + request
        assert out["cross_hops"] >= 1
        assert out["sampled"] == "forced"

    def test_tracing_probe_never_crashes_the_report(self, monkeypatch):
        from estorch_tpu.serve import router as router_mod

        def boom(*a, **k):
            raise OSError("no loopback")

        monkeypatch.setattr(router_mod.Router, "__init__", boom)
        out = doctor.check_tracing()
        assert out["ok"] is False
        assert "no loopback" in out["error"]

    def test_report_gains_tracing_row(self, monkeypatch):
        monkeypatch.setattr(doctor, "check_mesh",
                            lambda **kw: {"status": "ok"})
        monkeypatch.setattr(doctor, "check_device",
                            lambda timeout_s=20.0, platform=None: {
                                "status": "ok", "platform": "cpu",
                                "n_devices": 8, "elapsed_s": 0.1,
                                "timeout_s": timeout_s})
        monkeypatch.setattr(doctor, "check_collector",
                            lambda: {"ok": True})
        monkeypatch.setattr(doctor, "check_router",
                            lambda: {"ok": True})
        monkeypatch.setattr(doctor, "check_tracing",
                            lambda: {"ok": True, "cross_hops": 1})
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rep = doctor.report(timeout_s=5.0)
        assert rep["tracing"] == {"ok": True, "cross_hops": 1}


class TestResilienceCheck:
    def test_config_checks_without_probe(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ESTORCH_CKPT_ROOT", str(tmp_path))
        out = doctor.check_resilience()
        assert out["ckpt_root"]["path"] == str(tmp_path)
        assert out["ckpt_root"]["writable"] is True
        assert "roundtrip" not in out  # probe is opt-in (subprocess cost)
        assert out["fork"]["available"] is True  # this CI image is posix
        assert out["heartbeat_watchdog"]["telemetry_enabled"] in (True, False)

    def test_unwritable_ckpt_root_never_crashes(self, tmp_path):
        out = doctor.check_resilience(
            ckpt_root=str(tmp_path / "missing" / "deep"))
        assert out["ckpt_root"]["writable"] is False
        assert "error" in out["ckpt_root"]

    def test_watchdog_warns_on_heartbeat_with_telemetry_off(
            self, tmp_path, monkeypatch):
        """The config trap the sanity check exists for: a heartbeat path
        with ESTORCH_OBS=0 means no beats ever — a staleness watchdog
        would kill perfectly healthy runs."""
        monkeypatch.setenv("ESTORCH_OBS_HEARTBEAT",
                           str(tmp_path / "hb.json"))
        monkeypatch.setenv("ESTORCH_OBS", "0")
        out = doctor.check_resilience(ckpt_root=str(tmp_path))
        wd = out["heartbeat_watchdog"]
        assert wd["heartbeat_env_set"] is True
        assert wd["telemetry_enabled"] is False
        assert "warning" in wd
        assert wd["heartbeat_dir_writable"] is True

    def test_roundtrip_probe_classifier(self, tmp_path, monkeypatch):
        """Probe protocol pinned on controlled children (the real probe
        builds a tiny ES — exercised once in test_resilience.py's
        supervisor flow, not per doctor test)."""
        monkeypatch.setattr(doctor, "_RESILIENCE_PROBE",
                            "print('RESILIENCE_PROBE_OK')")
        out = doctor.check_resilience(ckpt_root=str(tmp_path), probe=True)
        assert out["roundtrip"] == {"status": "ok"}

        monkeypatch.setattr(doctor, "_RESILIENCE_PROBE",
                            "raise RuntimeError('orbax exploded')")
        out = doctor.check_resilience(ckpt_root=str(tmp_path), probe=True)
        assert out["roundtrip"]["status"] == "error"
        assert "orbax exploded" in out["roundtrip"]["stderr_tail"]

    @pytest.mark.slow
    def test_roundtrip_probe_wedge_detected(self, tmp_path, monkeypatch):
        monkeypatch.setattr(doctor, "_RESILIENCE_PROBE",
                            "import time; time.sleep(60)")
        out = doctor.check_resilience(ckpt_root=str(tmp_path), probe=True,
                                      probe_timeout_s=8)
        assert out["roundtrip"]["status"] == "wedged"


class TestServeCheck:
    def test_loopback_and_batcher_smoke(self):
        out = doctor.check_serve()
        assert out["loopback"]["bindable"] is True
        assert out["batcher"]["ok"] is True
        # the numpy-only smoke compiles nothing, but the accounting must
        # still bound "recompiles" by the ladder it reports
        assert out["batcher"]["recompiles"] <= len(out["batcher"]["buckets"])
        assert "bundle" not in out  # no bundle given

    def test_bundle_validation_without_jax_import(self, tmp_path):
        """A structurally-broken bundle is diagnosed (not crashed on),
        and validation never needs the policy module to be importable."""
        out = doctor.check_serve(bundle=str(tmp_path / "missing"))
        assert out["bundle"]["valid"] is False
        assert "error" in out["bundle"]

        import json

        bdir = tmp_path / "b"
        bdir.mkdir()
        (bdir / "arrays.npz").write_bytes(b"junk")
        (bdir / "MANIFEST.json").write_text(json.dumps({
            "schema": 1, "version": "x",
            "module": {"import": "not.importable:Ghost", "kwargs": {}},
            "obs_shape": [3], "param_dim": 7,
            "sha256": {"arrays.npz": "0" * 64},
        }))
        out = doctor.check_serve(bundle=str(bdir))
        assert out["bundle"]["valid"] is False
        assert "checksum" in out["bundle"]["error"]

    def test_valid_bundle_reported(self, tmp_path):
        import hashlib
        import json

        import numpy as np

        bdir = tmp_path / "b"
        bdir.mkdir()
        arrays = bdir / "arrays.npz"
        with open(arrays, "wb") as f:
            np.savez(f, params_flat=np.zeros(7, np.float32))
        sha = hashlib.sha256(arrays.read_bytes()).hexdigest()
        (bdir / "MANIFEST.json").write_text(json.dumps({
            "schema": 1, "version": "v9",
            "module": {"import": "whatever:NotImported", "kwargs": {}},
            "obs_shape": [3], "param_dim": 7, "obs_norm": False,
            "sha256": {"arrays.npz": sha},
        }))
        out = doctor.check_serve(bundle=str(bdir))
        assert out["bundle"]["valid"] is True
        assert out["bundle"]["version"] == "v9"
        assert out["bundle"]["param_dim"] == 7
        assert out["bundle"]["warm"] == {"present": False}

    @staticmethod
    def _warm_bundle(tmp_path, jax_version, **warm_over):
        """Hand-crafted warm bundle — the probe must stay jax-free, so
        the fixture is raw files + checksums, no export machinery."""
        import hashlib
        import json

        import numpy as np

        bdir = tmp_path / "wb"
        bdir.mkdir()
        arrays = bdir / "arrays.npz"
        with open(arrays, "wb") as f:
            np.savez(f, params_flat=np.zeros(7, np.float32))
        (bdir / "warm").mkdir()
        entry = bdir / "warm" / "jit_one-abc123-cache"
        entry.write_bytes(b"fake executable bytes")
        sha = {
            "arrays.npz": hashlib.sha256(arrays.read_bytes()).hexdigest(),
            "warm/jit_one-abc123-cache": hashlib.sha256(
                entry.read_bytes()).hexdigest(),
        }
        warm = {
            "format": "xla_cache", "max_batch": 4,
            "buckets": [2, 4], "buckets_excluded": [],
            "dtypes": ["f32"],
            "entries": {"jit_one-abc123-cache": entry.stat().st_size},
            "jax_version": jax_version, "platform": "cpu",
            "device_count": 8,
        }
        warm.update(warm_over)
        (bdir / "MANIFEST.json").write_text(json.dumps({
            "schema": 1, "version": "v9",
            "module": {"import": "whatever:NotImported", "kwargs": {}},
            "obs_shape": [3], "param_dim": 7, "obs_norm": False,
            "sha256": sha, "warm": warm,
        }))
        return bdir

    def test_warm_probe_compatible(self, tmp_path):
        from importlib.metadata import version

        bdir = self._warm_bundle(tmp_path, version("jax"))
        out = doctor.check_serve(bundle=str(bdir))
        warm = out["bundle"]["warm"]
        assert warm["present"] and warm["compatible"] is True
        assert warm["entries"] == 1
        assert "finding" not in warm

    def test_warm_probe_version_mismatch_is_finding(self, tmp_path):
        """The satellite contract: stale warmth (built under another jax)
        is a structured FINDING naming the fix, never a traceback — and
        the bundle itself still validates."""
        bdir = self._warm_bundle(tmp_path, "0.0.0")
        out = doctor.check_serve(bundle=str(bdir))
        assert out["bundle"]["valid"] is True
        warm = out["bundle"]["warm"]
        assert warm["compatible"] is False
        assert "0.0.0" in warm["finding"]
        assert "re-export" in warm["finding"]

    def test_warm_probe_ladder_incomplete_rejected(self, tmp_path):
        """Structural breakage IS an error: a warm block whose buckets
        don't cover its own max_batch ladder can't be trusted."""
        bdir = self._warm_bundle(tmp_path, "0.0.0", buckets=[2])
        out = doctor.check_serve(bundle=str(bdir))
        assert out["bundle"]["valid"] is False
        assert "ladder incomplete" in out["bundle"]["error"]


class TestReport:
    def test_report_shape_and_hints(self, monkeypatch):
        monkeypatch.setattr(
            doctor, "check_device",
            lambda timeout_s=20.0, platform=None: {
                "status": "failed", "reason": "init-hang",
                "elapsed_s": timeout_s, "timeout_s": timeout_s,
                "stderr_tail": ""})
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rep = doctor.report()
        assert rep["device"]["status"] == "wedged"
        assert "cpu" in rep["hint"]
        assert isinstance(rep["native"]["cpp_pool"], bool)
        assert rep["optional"]["gymnasium"]["available"] is True
        assert rep["obs"]["trace_dir"]["writable"] in (True, False)
        # resilience config checks ride every report (probe is opt-in)
        assert rep["resilience"]["fork"]["available"] is True
        assert "ckpt_root" in rep["resilience"]
        # serving readiness rides every report too (bundle is opt-in)
        assert rep["serve"]["loopback"]["bindable"] is True
        assert rep["serve"]["batcher"]["ok"] is True

    def test_report_run_dir_flows_to_obs_check(self, tmp_path,
                                               monkeypatch):
        from estorch_tpu.obs import Heartbeat

        monkeypatch.setattr(
            doctor, "check_device",
            lambda timeout_s=20.0, platform=None: {
                "status": "ok", "platform": "cpu", "n_devices": 8,
                "elapsed_s": 1.0, "timeout_s": timeout_s})
        Heartbeat(str(tmp_path / "heartbeat.json")).beat("update", 11)
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rep = doctor.report(run_dir=str(tmp_path))
        assert rep["obs"]["heartbeat"]["generation"] == 11

    def test_cli_json_and_exit_code(self, monkeypatch, capsys):
        monkeypatch.setattr(
            doctor, "check_device",
            lambda timeout_s=20.0, platform=None: {
                "status": "ok", "platform": "cpu", "n_devices": 8,
                "elapsed_s": 1.0, "timeout_s": timeout_s})
        monkeypatch.setattr(doctor, "check_elastic",
                            lambda **kw: {"status": "ok",
                                          "elapsed_s": 0.1,
                                          "timeout_s": 120.0})
        rc = doctor.main(["--timeout", "5"])
        rep = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert rep["device"]["platform"] == "cpu"
        assert "hint" not in rep
