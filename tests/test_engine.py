"""Sharded generation engine tests (SURVEY.md §4 'Distributed without a pod').

The key invariants of the broadcast-free design:
- the update computed on an 8-device mesh equals the 1-device update up to
  psum reduction order;
- the same seed gives the same trajectory (exact determinism on one mesh);
- the split evaluate→weights→update path (novelty family) reproduces the
  fused generation_step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from estorch_tpu.envs import CartPole
from estorch_tpu.ops import centered_rank, make_noise_table, make_param_spec
from estorch_tpu.parallel import (
    EngineConfig,
    ESEngine,
    pairs_per_device,
    population_mesh,
    single_device_mesh,
)


def _mlp_setup():
    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {
            "w1": jax.random.normal(k1, (4, 16)) * 0.5,
            "b1": jnp.zeros(16),
            "w2": jax.random.normal(k2, (16, 2)) * 0.5,
            "b2": jnp.zeros(2),
        }

    def apply(params, obs):
        h = jnp.tanh(obs @ params["w1"] + params["b1"])
        return h @ params["w2"] + params["b2"]

    params = init_params(jax.random.PRNGKey(0))
    flat, spec = make_param_spec(params)
    return flat, spec, apply


@pytest.fixture(scope="module")
def setup():
    flat, spec, apply = _mlp_setup()
    env = CartPole()
    table = make_noise_table(1 << 18, seed=0)
    cfg = EngineConfig(population_size=32, sigma=0.1, horizon=100, eval_chunk=8)
    opt = optax.adam(3e-2)
    return dict(flat=flat, spec=spec, apply=apply, env=env, table=table, cfg=cfg, opt=opt)


def _engine(s, mesh):
    return ESEngine(s["env"], s["apply"], s["spec"], s["table"], s["opt"], s["cfg"], mesh)


class TestShardingEquivalence:
    def test_8dev_equals_1dev(self, setup, devices8):
        e8 = _engine(setup, population_mesh())
        e1 = _engine(setup, single_device_mesh())
        s8 = e8.init_state(setup["flat"], jax.random.PRNGKey(7))
        s1 = e1.init_state(setup["flat"], jax.random.PRNGKey(7))
        for gen in range(4):
            s8, m8 = e8.generation_step(s8)
            s1, m1 = e1.generation_step(s1)
            np.testing.assert_array_equal(
                np.asarray(m8["fitness"]), np.asarray(m1["fitness"]),
                err_msg=f"fitness diverged at gen {gen}",
            )
            np.testing.assert_allclose(
                np.asarray(s8.params_flat), np.asarray(s1.params_flat),
                rtol=2e-5, atol=1e-6, err_msg=f"params diverged at gen {gen}",
            )

    def test_same_seed_exact_determinism(self, setup):
        e = _engine(setup, population_mesh())
        sa = e.init_state(setup["flat"], jax.random.PRNGKey(3))
        sb = e.init_state(setup["flat"], jax.random.PRNGKey(3))
        for _ in range(3):
            sa, _ = e.generation_step(sa)
            sb, _ = e.generation_step(sb)
        np.testing.assert_array_equal(np.asarray(sa.params_flat), np.asarray(sb.params_flat))

    def test_different_seed_differs(self, setup):
        e = _engine(setup, population_mesh())
        sa = e.init_state(setup["flat"], jax.random.PRNGKey(3))
        sb = e.init_state(setup["flat"], jax.random.PRNGKey(4))
        sa, _ = e.generation_step(sa)
        sb, _ = e.generation_step(sb)
        assert not np.array_equal(np.asarray(sa.params_flat), np.asarray(sb.params_flat))


class TestSplitPath:
    def test_split_equals_fused(self, setup):
        """evaluate → centered_rank → apply_weights == generation_step."""
        e = _engine(setup, population_mesh())
        s0 = e.init_state(setup["flat"], jax.random.PRNGKey(11))
        fused_state, fused_metrics = e.generation_step(s0)

        ev = e.evaluate(s0)
        np.testing.assert_array_equal(
            np.asarray(ev.fitness), np.asarray(fused_metrics["fitness"])
        )
        weights = centered_rank(jnp.asarray(ev.fitness))
        split_state, _ = e.apply_weights(s0, weights)
        np.testing.assert_allclose(
            np.asarray(split_state.params_flat), np.asarray(fused_state.params_flat),
            rtol=1e-6, atol=1e-7,
        )
        assert int(split_state.generation) == int(fused_state.generation) == 1

    def test_center_eval_is_deterministic(self, setup):
        e = _engine(setup, population_mesh())
        s0 = e.init_state(setup["flat"], jax.random.PRNGKey(11))
        r1 = e.evaluate_center(s0)
        r2 = e.evaluate_center(s0)
        assert float(r1.total_reward) == float(r2.total_reward)
        assert r1.bc.shape == (setup["env"].bc_dim,)


class TestMeshValidation:
    def test_odd_population_rejected(self):
        with pytest.raises(ValueError, match="even"):
            pairs_per_device(65, 8)

    def test_indivisible_pairs_padded(self, setup, devices8):
        """Regression for the old hard-error case: 17 pairs over 8 devices
        used to raise "use a population that is a multiple of 2·n_devices";
        now the population is ghost-padded (zero-weighted, clamped rows)
        and trains IDENTICALLY to the same population on one device —
        padding must be unobservable in fitness, steps, and the update."""
        assert pairs_per_device(34, 8) == 3  # ceil(17/8): padded pairs
        cfg = EngineConfig(population_size=34, sigma=0.1, horizon=30)
        e8 = ESEngine(setup["env"], setup["apply"], setup["spec"],
                      setup["table"], setup["opt"], cfg, population_mesh())
        e1 = ESEngine(setup["env"], setup["apply"], setup["spec"],
                      setup["table"], setup["opt"], cfg, single_device_mesh())
        s8 = e8.init_state(setup["flat"], jax.random.PRNGKey(7))
        s1 = e1.init_state(setup["flat"], jax.random.PRNGKey(7))
        for gen in range(2):
            s8, m8 = e8.generation_step(s8)
            s1, m1 = e1.generation_step(s1)
            assert m8["fitness"].shape == (34,)
            np.testing.assert_array_equal(
                np.asarray(m8["fitness"]), np.asarray(m1["fitness"]),
                err_msg=f"padded fitness diverged at gen {gen}")
            assert int(m8["steps"]) == int(m1["steps"])
            np.testing.assert_allclose(
                np.asarray(s8.params_flat), np.asarray(s1.params_flat),
                rtol=2e-5, atol=1e-6,
                err_msg=f"padded update diverged at gen {gen}")

    def test_member_reconstruction_matches_eval_perturbation(self, setup):
        """member_params(i) must be exactly the θ the engine evaluated for i."""
        e = _engine(setup, single_device_mesh())
        s0 = e.init_state(setup["flat"], jax.random.PRNGKey(2))
        ev = e.evaluate(s0)
        # re-evaluate member 5's reconstructed params by hand: same fitness
        from estorch_tpu.envs.rollout import make_rollout

        theta5 = e.member_params(s0, 5)
        # rollout key: pair 2 (member 5 = pair 2, sign -) shares the pair key
        import estorch_tpu.parallel.engine as eng_mod

        okey, rkey = eng_mod._gen_keys(s0)
        pair_keys = jax.random.split(rkey, setup["cfg"].population_size // 2)
        rollout = make_rollout(setup["env"], setup["apply"], setup["cfg"].horizon)
        res = rollout(setup["spec"].unravel(theta5), pair_keys[5 // 2])
        assert float(res.total_reward) == float(ev.fitness[5])


class TestSigmaAnnealing:
    def test_sigma_decays_with_floor(self, setup):
        cfg = EngineConfig(
            population_size=32, sigma=0.1, horizon=20, eval_chunk=8,
            sigma_decay=0.5, sigma_min=0.02,
        )
        e = ESEngine(setup["env"], setup["apply"], setup["spec"], setup["table"],
                     setup["opt"], cfg, population_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(0))
        sigmas = [float(s.sigma)]
        for _ in range(4):
            s, _ = e.generation_step(s)
            sigmas.append(float(np.asarray(s.sigma)))
        np.testing.assert_allclose(sigmas, [0.1, 0.05, 0.025, 0.02, 0.02], rtol=1e-6)

    def test_member_reconstruction_uses_state_sigma(self, setup):
        cfg = EngineConfig(
            population_size=32, sigma=0.1, horizon=20, eval_chunk=8,
            sigma_decay=0.5,
        )
        e = ESEngine(setup["env"], setup["apply"], setup["spec"], setup["table"],
                     setup["opt"], cfg, single_device_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(0))
        s, _ = e.generation_step(s)  # sigma now 0.05
        theta = np.asarray(e.member_params(s, 0))
        # exact reconstruction with the DECAYED state sigma
        offs = e.all_pair_offsets(s)
        eps = np.asarray(setup["table"].slice(offs[0], setup["spec"].dim))
        expected = np.asarray(s.params_flat) + float(np.asarray(s.sigma)) * eps
        np.testing.assert_allclose(theta, expected, rtol=1e-6, atol=1e-7)

    def test_default_no_decay_keeps_sigma(self, setup):
        e = _engine(setup, population_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(0))
        s, _ = e.generation_step(s)
        assert float(np.asarray(s.sigma)) == pytest.approx(setup["cfg"].sigma)


class TestUnmirroredSampling:
    """Reference's plain ES: independent noise per member, no antithetic
    pairs (mirroring is the opt-in of BASELINE config 3)."""

    def _engine(self, setup, mesh, pop=32):
        cfg = EngineConfig(
            population_size=pop, sigma=0.1, horizon=100, eval_chunk=8,
            mirrored=False,
        )
        return ESEngine(setup["env"], setup["apply"], setup["spec"],
                        setup["table"], setup["opt"], cfg, mesh)

    def test_learns_cartpole(self, setup):
        e = self._engine(setup, population_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(0))
        first = None
        for _ in range(10):
            s, m = e.generation_step(s)
            mean = float(np.asarray(m["fitness"]).mean())
            first = mean if first is None else first
        assert mean > first + 15, (first, mean)

    def test_8dev_equals_1dev(self, setup, devices8):
        e8 = self._engine(setup, population_mesh())
        e1 = self._engine(setup, single_device_mesh())
        s8 = e8.init_state(setup["flat"], jax.random.PRNGKey(5))
        s1 = e1.init_state(setup["flat"], jax.random.PRNGKey(5))
        for _ in range(3):
            s8, m8 = e8.generation_step(s8)
            s1, m1 = e1.generation_step(s1)
        np.testing.assert_array_equal(
            np.asarray(m8["fitness"]), np.asarray(m1["fitness"])
        )
        np.testing.assert_allclose(
            np.asarray(s8.params_flat), np.asarray(s1.params_flat),
            rtol=2e-5, atol=1e-6,
        )

    def test_member_reconstruction(self, setup):
        e = self._engine(setup, single_device_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(2))
        ev = e.evaluate(s)
        # member 3's reconstructed theta re-rolls to its recorded fitness
        from estorch_tpu.envs.rollout import make_rollout
        import estorch_tpu.parallel.engine as eng_mod

        theta3 = e.member_params(s, 3)
        _, rkey = eng_mod._gen_keys(s)
        keys = jax.random.split(rkey, 32)
        rollout = make_rollout(setup["env"], setup["apply"], 100)
        res = rollout(setup["spec"].unravel(theta3), keys[3])
        assert float(res.total_reward) == float(ev.fitness[3])

    def test_odd_population_allowed(self, setup):
        """No pair structure -> odd populations are legal when they divide
        the mesh (single device here)."""
        cfg = EngineConfig(population_size=7, sigma=0.1, horizon=10, mirrored=False)
        e = ESEngine(setup["env"], setup["apply"], setup["spec"], setup["table"],
                     setup["opt"], cfg, single_device_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(0))
        s, m = e.generation_step(s)
        assert np.asarray(m["fitness"]).shape == (7,)


class TestEpisodesPerMember:
    def test_multi_episode_fitness_and_steps(self, setup):
        cfg = EngineConfig(population_size=16, sigma=0.1, horizon=50,
                           episodes_per_member=3)
        e = ESEngine(setup["env"], setup["apply"], setup["spec"], setup["table"],
                     setup["opt"], cfg, single_device_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(1))
        ev = e.evaluate(s)
        assert ev.fitness.shape == (16,)
        # 3 episodes per member: total alive steps must exceed the
        # single-episode engine's for the same seed
        cfg1 = EngineConfig(population_size=16, sigma=0.1, horizon=50)
        e1 = ESEngine(setup["env"], setup["apply"], setup["spec"], setup["table"],
                      setup["opt"], cfg1, single_device_mesh())
        ev1 = e1.evaluate(e1.init_state(setup["flat"], jax.random.PRNGKey(1)))
        assert int(ev.steps) > int(ev1.steps)

    def test_multi_episode_fitness_is_exact_episode_mean(self, setup):
        """Member fitness must equal the mean of its episode returns,
        replayed manually with the same keys."""
        from estorch_tpu.envs.rollout import make_rollout
        import estorch_tpu.parallel.engine as eng_mod

        cfg = EngineConfig(population_size=4, sigma=0.1, horizon=40,
                           episodes_per_member=3)
        e = ESEngine(setup["env"], setup["apply"], setup["spec"], setup["table"],
                     setup["opt"], cfg, single_device_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(7))
        ev = e.evaluate(s)

        member = 1
        theta = e.member_params(s, member)
        _, rkey = eng_mod._gen_keys(s)
        pair_keys = jax.random.split(rkey, 2)  # population 4 → 2 pairs
        member_key = pair_keys[member // 2]
        rollout = make_rollout(setup["env"], setup["apply"], 40)
        rets = [
            float(rollout(setup["spec"].unravel(theta), k).total_reward)
            for k in jax.random.split(member_key, 3)
        ]
        np.testing.assert_allclose(
            float(np.asarray(ev.fitness)[member]), np.mean(rets), rtol=1e-6
        )


class TestMinimumPopulation:
    def test_population_of_two(self, setup):
        """One antithetic pair — the smallest legal population — must run."""
        cfg = EngineConfig(population_size=2, sigma=0.1, horizon=20)
        e = ESEngine(setup["env"], setup["apply"], setup["spec"], setup["table"],
                     setup["opt"], cfg, single_device_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(0))
        s, m = e.generation_step(s)
        assert np.asarray(m["fitness"]).shape == (2,)
        assert int(s.generation) == 1


class _NaNBombEnv:
    """Continuous-action toy env whose reward is NaN whenever action[0]
    exceeds a threshold — so the perturbation's SIGN decides which members
    fail, deterministically for a fixed seed.  Episode = 5 steps."""

    obs_dim = 4
    action_dim = 2
    discrete = False
    bc_dim = 2

    def reset(self, key):
        del key
        return jnp.int32(0), jnp.zeros(4, jnp.float32)

    def step(self, state, action):
        reward = 1.0 - jnp.sum(action**2)
        reward = jnp.where(action[0] > 0.05, jnp.nan, reward)
        nstate = state + 1
        return nstate, jnp.zeros(4, jnp.float32), reward, nstate >= 5

    def behavior(self, state, obs):
        del state
        return obs[:2]


class TestNaNFitnessMasking:
    """VERDICT round-1 weak #1: the fused device path must not promote a
    NaN-fitness member to the top rank — it must match the host backend's
    drop-and-renormalize semantics (utils/fault.py)."""

    def _engine(self, setup, mesh):
        cfg = EngineConfig(population_size=32, sigma=0.1, horizon=8, eval_chunk=8)
        return ESEngine(_NaNBombEnv(), setup["apply"], setup["spec"],
                        setup["table"], setup["opt"], cfg, mesh)

    def test_fused_update_matches_host_renormalization(self, setup):
        from estorch_tpu.utils.fault import rank_weights_with_failures

        e = self._engine(setup, single_device_mesh())
        s0 = e.init_state(setup["flat"], jax.random.PRNGKey(9))
        ev = e.evaluate(s0)
        fit = np.asarray(ev.fitness)
        # the seed must actually produce a mixed population or the test is vacuous
        assert np.isnan(fit).any(), "seed produced no NaN members — adjust threshold"
        assert np.isfinite(fit).sum() >= 2

        fused_state, m = e.generation_step(s0)
        assert int(m["n_valid"]) == int(np.isfinite(fit).sum())
        assert np.isfinite(np.asarray(fused_state.params_flat)).all()

        # split path with the HOST weighting = the required semantics
        w = rank_weights_with_failures(fit)
        split_state, _ = e.apply_weights(s0, jnp.asarray(w))
        np.testing.assert_allclose(
            np.asarray(fused_state.params_flat),
            np.asarray(split_state.params_flat),
            rtol=1e-6, atol=1e-7,
        )

    def test_nan_member_contributes_zero_weight(self, setup):
        """Sanity on the weights themselves: re-derive them in-program and
        check the NaN members got exactly 0."""
        from estorch_tpu.ops import centered_rank_safe

        e = self._engine(setup, population_mesh())
        s0 = e.init_state(setup["flat"], jax.random.PRNGKey(9))
        fit = np.asarray(e.evaluate(s0).fitness)
        w, _ = centered_rank_safe(jnp.asarray(fit))
        w = np.asarray(w)
        assert (w[~np.isfinite(fit)] == 0.0).all()
        assert abs(w.sum()) < 1e-4  # still centered over survivors

    @pytest.mark.slow
    def test_all_invalid_generation_raises_via_api(self, setup):
        """Backend parity: host/pooled raise when <2 members survive; the
        device path must too (ES.train acts on the n_valid metric)."""
        import optax as _optax

        from estorch_tpu import ES
        from estorch_tpu.envs.agent import JaxAgent
        from estorch_tpu.models import MLPPolicy

        class _AlwaysNaN(_NaNBombEnv):
            def step(self, state, action):
                nstate, obs, _, done = _NaNBombEnv.step(self, state, action)
                return nstate, obs, jnp.float32(jnp.nan), done

        es = ES(
            MLPPolicy, JaxAgent(_AlwaysNaN(), horizon=5), _optax.adam,
            policy_kwargs={"action_dim": 2, "hidden": (8,), "discrete": False},
            optimizer_kwargs={"learning_rate": 1e-2},
            population_size=16, sigma=0.1, seed=0,
        )
        flat_before = np.asarray(es.state.params_flat).copy()
        gen_before = int(es.state.generation)
        with pytest.raises(RuntimeError, match="valid fitness"):
            es.train(1, verbose=False)
        # state must be rolled back — a catcher that checkpoints es.state
        # must not persist the dead-generation update
        np.testing.assert_array_equal(np.asarray(es.state.params_flat), flat_before)
        assert int(es.state.generation) == gen_before

    def test_all_finite_metrics_report_full_population(self, setup):
        # a HEALTHY env (module fixture's CartPole, not the NaN bomb):
        # every member must count as valid
        cartpole_engine = _engine(setup, population_mesh())
        s = cartpole_engine.init_state(setup["flat"], jax.random.PRNGKey(0))
        _, m = cartpole_engine.generation_step(s)
        assert int(m["n_valid"]) == setup["cfg"].population_size


class TestLearning:
    def test_cartpole_learns(self, setup):
        """Fitness must rise substantially within a few generations (smoke =
        BASELINE config 1, scaled down for CI speed)."""
        e = _engine(setup, population_mesh())
        s = e.init_state(setup["flat"], jax.random.PRNGKey(0))
        first_mean = None
        for gen in range(10):
            s, m = e.generation_step(s)
            mean = float(np.asarray(m["fitness"]).mean())
            if first_mean is None:
                first_mean = mean
        assert mean > first_mean + 20, (first_mean, mean)
