"""BASELINE config harness smoke tests (scaled down for CI speed)."""

import numpy as np
import pytest

from estorch_tpu.configs import CONFIGS, cartpole_smoke, halfcheetah_vbn


class TestConfigs:
    def test_all_baseline_configs_present(self):
        assert set(CONFIGS) == {
            "cartpole_smoke",
            "swimmer2d_device",
            "hopper2d_device",
            "walker2d_device",
            "humanoid2d_device",
            "humanoid2d_pop10k",
            "cheetah2d_device",
            "halfcheetah_vbn",
            "humanoid_mirrored",
            "humanoid_nsres",
            "halfcheetah_pooled",
            "halfcheetah_nsres",
            "humanoid_pooled",
            "pong84_conv",
            "atari_frostbite",
        }

    def test_cartpole_smoke_runs_device_path(self):
        es = cartpole_smoke(population_size=32, table_size=1 << 16)
        es.train(2, verbose=False)
        assert es.backend == "device"
        assert len(es.history) == 2

    @pytest.mark.slow
    def test_locomotion_configs_run_device_path(self):
        from estorch_tpu.configs import (
            cheetah2d_device,
            hopper2d_device,
            humanoid2d_device,
            swimmer2d_device,
            walker2d_device,
        )

        # hopper/walker included deliberately: they are the locomotion envs
        # with a termination path (falling) through the rollout done-mask
        for recipe in (swimmer2d_device, hopper2d_device, walker2d_device,
                       humanoid2d_device, cheetah2d_device):
            es = recipe(population_size=16, table_size=1 << 16)
            es.train(1, verbose=False)
            assert es.backend == "device"
            assert np.isfinite(es.history[0]["reward_mean"])

    @pytest.mark.slow
    def test_halfcheetah_vbn_runs_host_path(self):
        es = halfcheetah_vbn(population_size=16)
        es.train(1, verbose=False)
        assert es.backend == "host"
        assert np.isfinite(es.history[0]["reward_mean"])
        # VBN layers must be frozen (initialized) in master AND workers
        for policy, _ in es.engine._workers:
            for m in policy.modules():
                if type(m).__name__ == "TorchVirtualBatchNorm":
                    assert bool(m.initialized)

    @pytest.mark.slow
    def test_halfcheetah_nsres_runs_pooled_with_x_bc(self):
        """Config 4 on real MuJoCo: NSR-ES pooled, BC = final x-position."""
        from estorch_tpu.configs import halfcheetah_nsres

        from estorch_tpu.parallel.mesh import single_device_mesh

        es = halfcheetah_nsres(
            population_size=8,
            meta_population_size=2,
            k=3,
            mesh=single_device_mesh(),
            agent_kwargs={
                "env_name": "gym:HalfCheetah-v5",
                "horizon": 20,
                "env_kwargs": {
                    "exclude_current_positions_from_observation": False
                },
                "bc_indices": (0,),
            },
        )
        es.train(1, verbose=False)
        assert es.backend == "pooled"
        assert es.engine.bc_dim == 1
        # archive holds 1-dim BCs: meta seeds + this generation's center
        assert es.archive.bcs.shape[1] == 1
        assert np.isfinite(es.history[0]["reward_mean"])
        es.engine.pool.close()
        es.engine.center_pool.close()

    @pytest.mark.slow
    def test_humanoid_pooled_runs_real_mujoco(self):
        """Config 3's pooled edition: Humanoid-v5 physics, obs_norm on,
        actions squashed to the env's ±0.4 bound (round-5)."""
        from estorch_tpu.configs import humanoid_pooled
        from estorch_tpu.parallel.mesh import single_device_mesh

        es = humanoid_pooled(
            population_size=8,
            mesh=single_device_mesh(),
            agent_kwargs={"env_name": "gym:Humanoid-v5", "horizon": 30},
        )
        es.train(1, verbose=False)
        assert es.backend == "pooled"
        assert es._obs_norm
        assert es.module.action_scale == 0.4
        assert float(es.state.obs_stats[0]) > 0  # member obs fed the stats
        assert np.isfinite(es.history[0]["reward_mean"])
        es.engine.pool.close()
        es.engine.center_pool.close()

    def test_atari_gated_with_clear_error(self):
        with pytest.raises(ImportError, match="ale_py"):
            CONFIGS["atari_frostbite"]()

    @pytest.mark.slow
    def test_cli_main(self, capsys):
        from estorch_tpu.configs import main

        main(["cartpole_smoke", "--generations", "1", "--population", "16"])
        out = capsys.readouterr().out
        assert "best reward" in out
