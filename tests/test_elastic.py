"""Elastic multi-host ES (estorch_tpu/parallel/elastic.py +
algo/scheduler.py ElasticScheduler — docs/multihost.md).

Anchors (ISSUE 15 acceptance): a 2-host elastic run matches the
single-host synchronous run within the documented IW tolerance (rel-L2
< 0.10 over the 8-generation demo config; measured 0.02–0.03), a
declared ``kill_host`` mid-run drops throughput MEASURABLY while the
surviving host drives the run to completion and ``replay=log``
reproduces the final parameters bit-exactly, a host joining mid-run
continues the coordinator's single dispatch-id stream (noise
coordinates are never reused), and membership transitions round-trip
through the event log.

Hosts here are thread-simulated (parallel/elastic.py run_host_thread):
their own engine instances joined through a REAL loopback TCP socket —
everything but the separate interpreter, which ``bench.py --elastic-ab``
and the doctor's staged probe cover with real processes.
"""

import os
import threading
import time

import numpy as np
import pytest

from estorch_tpu.algo.scheduler import AsyncEventLog
from estorch_tpu.parallel.elastic import (ElasticCoordinator,
                                          es_from_spec, recv_msg,
                                          run_host_thread, send_msg)
from estorch_tpu.resilience.chaos import CHAOS_ENV, ChaosPlan, reset_cache

SPEC = {"population_size": 16, "horizon": 64, "seed": 7}

# the documented IW tolerance (docs/multihost.md): stale host
# contributions fold with clipped importance weights, so an elastic run
# is the same estimator perturbed by reweighted-staleness noise — not
# bit-equal to the barrier loop, but within this relative L2 over the
# 8-generation demo config (measured 0.02–0.03 incl. under stragglers)
IW_REL_L2_TOL = 0.10


@pytest.fixture
def chaos_env():
    def set_plan(plan: ChaosPlan):
        os.environ[CHAOS_ENV] = plan.to_json()
        reset_cache()

    yield set_plan
    os.environ.pop(CHAOS_ENV, None)
    reset_cache()


def run_fleet(es, n, hosts=2, start_delay=None, log_fn=None):
    """One elastic run over ``hosts`` thread-simulated hosts; returns
    (coordinator, workers) with the coordinator already closed."""
    coord = ElasticCoordinator(join_grace_s=60.0)
    workers = []
    for i in range(hosts):
        workers.append(run_host_thread(coord.address,
                                       es_from_spec(SPEC), i)[0])
    try:
        es.train_elastic(n, fleet=coord, verbose=False,
                         log_fn=log_fn)
    finally:
        coord.close()
        for w in workers:
            w.stop()
    return coord, workers


class TestParity:
    def test_two_host_elastic_within_documented_iw_tolerance(self):
        """THE demo, part 1: 2 elastic hosts vs the single-host
        synchronous loop, same seed — final params within the
        documented IW tolerance, with the fold path actually exercised
        (pipelined dispatches arrive one version stale by design)."""
        es_ref = es_from_spec(SPEC)
        es_ref.train(8, verbose=False)
        ref = np.asarray(es_ref.state.params_flat, np.float64)

        es = es_from_spec(SPEC)
        run_fleet(es, 8)
        got = np.asarray(es.state.params_flat, np.float64)
        rel = float(np.linalg.norm(got - ref) / np.linalg.norm(ref))
        assert rel < IW_REL_L2_TOL, rel
        # the tolerance is not hiding a dead run: every update landed
        # with finite fitness
        assert len(es.history) == 8
        assert all(np.isfinite(r["reward_mean"]) for r in es.history)
        counters = es.obs.counters.snapshot()
        assert counters.get("results_folded", 0) > 0
        assert counters.get("hosts_joined") == 2

    def test_live_replay_bit_identical(self, chaos_env):
        """replay=log re-drives the recorded schedule as pure math —
        bit-identical params, no fleet, even for a straggler-torn run
        whose batches mixed fresh and stale sources."""
        chaos_env(ChaosPlan.generate(
            seed=0, n_generations=40, straggle_host_every=1,
            straggle_host=1, straggle_host_sleep_s=0.15,
            straggle_host_jitter_s=0.05))
        es = es_from_spec(SPEC)
        run_fleet(es, 6)
        live = np.asarray(es.state.params_flat, np.float32).tobytes()
        log = es.async_event_log
        assert es.obs.counters.snapshot().get("results_folded", 0) > 0
        os.environ.pop(CHAOS_ENV, None)
        reset_cache()

        es2 = es_from_spec(SPEC)
        es2.train_elastic(6, replay=log, verbose=False)
        assert np.asarray(
            es2.state.params_flat, np.float32).tobytes() == live
        # replay of the replay: the log is closed under its own math
        es3 = es_from_spec(SPEC)
        es3.train_elastic(6, replay=es2.async_event_log, verbose=False)
        assert np.asarray(
            es3.state.params_flat, np.float32).tobytes() == live


class TestMembership:
    def test_host_join_mid_run_continues_dispatch_stream(self, chaos_env):
        """A host joining MID-RUN syncs center+version and starts
        contributing; the coordinator's single dispatch counter keeps
        flowing, so no noise coordinate is ever reused."""
        # pace the run (every host pays a declared 50ms per dispatch) so
        # "mid-run" is a real window, and pre-compile the late host's
        # eval program so its join cost is the protocol, not XLA
        chaos_env(ChaosPlan([{"kind": "straggle_host", "gen": g,
                              "host": "all", "sleep_s": 0.05}
                             for g in range(64)]))
        late_es = es_from_spec(SPEC)
        late_es.engine.compile_split(late_es.state)
        es = es_from_spec(SPEC)
        coord = ElasticCoordinator(join_grace_s=60.0)
        w0 = run_host_thread(coord.address, es_from_spec(SPEC), 0)[0]
        late: list = []

        def join_late(rec):
            if rec["generation"] >= 3 and not late:
                late.append(run_host_thread(coord.address, late_es, 1)[0])

        try:
            es.train_elastic(14, fleet=coord, verbose=False,
                             log_fn=join_late)
        finally:
            coord.close()
            w0.stop()
            for w in late:
                w.stop()
        log = es.async_event_log
        ids = [d[0] for d in log.dispatches]
        assert len(ids) == len(set(ids)), "dispatch id reused"
        assert ids == sorted(ids)
        joins = [m for m in log.membership if m["event"] == "join"]
        assert [m["host"] for m in joins] == [0, 1]
        assert joins[1]["at_dispatch"] > joins[0]["at_dispatch"], \
            "the second join was not mid-run"
        assert late and late[0].dispatches_done > 0, \
            "late host never contributed"

    def test_host_kill_loses_throughput_not_the_run(self, chaos_env):
        """THE demo, part 2: every host pays a declared 60ms stall per
        dispatch (so throughput is host-bound and measurable); a
        declared kill_host takes host 1 mid-run.  The surviving host
        drives the run to completion, the death lands on the event log
        (membership leave + counted losses + replacement dispatches),
        per-update wall time degrades measurably toward the single-host
        rate, and replay=log reproduces final params bit-exactly."""
        events = [{"kind": "straggle_host", "gen": g, "host": "all",
                   "sleep_s": 0.06} for g in range(64)]
        # kill host 1 at whichever of dispatches 8..13 it evaluates
        # first (routing alternates, so the exact id is schedule-
        # dependent; the RANGE guarantees the death happens mid-run)
        events.extend({"kind": "kill_host", "gen": g, "host": 1}
                      for g in range(8, 14))
        chaos_env(ChaosPlan(events))
        es = es_from_spec(SPEC)
        walls: list[float] = []
        last = [None]

        def clock(rec):
            now = time.perf_counter()
            if last[0] is not None:
                walls.append(now - last[0])
            last[0] = now

        run_fleet(es, 16, log_fn=clock)
        log = es.async_event_log
        counters = es.obs.counters.snapshot()
        assert len(log.updates) == 16  # the survivor finished the run
        leaves = [m for m in log.membership if m["event"] == "leave"]
        assert len(leaves) == 1 and leaves[0]["host"] == 1
        assert counters.get("hosts_lost") == 1
        # the kill cost results: counted, and replaced by extra
        # dispatches (dispatched > consumed)
        assert len(log.lost) > 0
        assert counters.get("results_lost", 0) == len(log.lost)
        n = es.population_size
        assert len(log.dispatches) * n == (
            sum(len(u["consumed"]) for u in log.updates)
            + len(log.discarded) + len(log.lost))
        # throughput: with 2 hosts, pairs of 60ms-stalled dispatches
        # land together (update gaps ALTERNATE long/short), averaging
        # ~one stall per two updates; after the kill every update pays
        # its full stall.  Window MEANS absorb the alternation — the
        # tail must be measurably slower than the 2-host head (roughly
        # proportional; 1.35x leaves room for a loaded box)
        head = sum(walls[2:6]) / 4
        tail = sum(walls[-4:]) / 4
        assert tail > 1.35 * head, (head, tail, walls)
        # replay: bit-exact without any fleet
        os.environ.pop(CHAOS_ENV, None)
        reset_cache()
        es2 = es_from_spec(SPEC)
        es2.train_elastic(16, replay=log, verbose=False)
        assert (np.asarray(es2.state.params_flat, np.float32).tobytes()
                == np.asarray(es.state.params_flat, np.float32).tobytes())


class TestEventLog:
    def test_membership_event_log_round_trip(self):
        """Membership transitions survive to_dict/from_dict — the
        forensic half of the replay contract (replay is pure math over
        dispatches/updates; membership explains the schedule)."""
        log = AsyncEventLog()
        log.dispatches.append((0, 0))
        log.membership.append({"event": "join", "host": 0,
                               "at_dispatch": 0})
        log.membership.append({"event": "leave", "host": 0,
                               "at_dispatch": 3})
        d = log.to_dict()
        back = AsyncEventLog.from_dict(d)
        assert back.membership == log.membership
        assert back.to_dict() == d
        # a membership-free log stays schema-identical to PR-8 logs
        assert "membership" not in AsyncEventLog().to_dict()
        assert AsyncEventLog.from_dict(
            {"schema": 1, "dispatches": [], "updates": [],
             "discarded": [], "lost": []}).membership == []

    def test_wire_protocol_round_trip(self):
        """The framed send/recv carries headers + typed arrays exactly
        over a real socketpair, and a poll slice with nothing buffered
        returns None instead of blocking (the R17 contract)."""
        import socket

        a, b = socket.socketpair()
        a.settimeout(0.05)
        b.settimeout(0.05)
        try:
            arr = np.arange(5, dtype=np.float32)
            send_msg(a, {"t": "result", "dispatch": 3}, {"fitness": arr})
            header, arrays = recv_msg(b, 1.0)
            assert header["t"] == "result" and header["dispatch"] == 3
            np.testing.assert_array_equal(arrays["fitness"], arr)
            assert arrays["fitness"].dtype == np.float32
            assert recv_msg(b, 0.05) is None  # bounded empty poll
        finally:
            a.close()
            b.close()
