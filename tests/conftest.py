"""Test harness configuration.

Forces the CPU backend with 8 virtual devices BEFORE any jax computation, so
the multi-device sharding tests run without TPU hardware — the standard JAX
"multi-node tests without a cluster" pattern (SURVEY.md §4).

Note: this image's sitecustomize pins ``JAX_PLATFORMS=axon`` (the TPU tunnel),
so env vars are not enough — we override via jax.config, which works because
pytest imports this conftest before any test module touches a device.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
