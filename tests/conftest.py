"""Test harness configuration.

Forces the CPU backend with 8 virtual devices BEFORE any jax computation, so
the multi-device sharding tests run without TPU hardware — the standard JAX
"multi-node tests without a cluster" pattern (SURVEY.md §4).

Note: this image's sitecustomize pins ``JAX_PLATFORMS=axon`` (the TPU tunnel),
so env vars are not enough — we override via jax.config, which works because
pytest imports this conftest before any test module touches a device.
"""

import os

# ONE implementation of the version-portable "CPU with 8 virtual devices"
# switch (jax_num_cpu_devices on new jax, XLA_FLAGS replacement on old) —
# utils/backend.py; importing estorch_tpu/jax here does not initialize a
# backend, so the config still takes effect
from estorch_tpu.utils import force_cpu_backend

force_cpu_backend(8)

import jax  # noqa: E402

# XLA compile time dominates this suite (dozens of engine builds, each a
# fresh closure jax's in-memory cache can't reuse).  The persistent cache
# keys on HLO, so identical programs ACROSS tests and across runs load
# from disk instead of recompiling.  Opt out with ESTORCH_TEST_NO_CACHE=1
# (e.g. when hunting a miscompile).
if not os.environ.get("ESTORCH_TEST_NO_CACHE"):
    from estorch_tpu.utils import enable_compilation_cache

    enable_compilation_cache(
        os.path.join(os.path.expanduser("~"), ".cache", "estorch_tpu",
                     "test_xla_cache"))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
