"""Novelty family tests: archive k-NN oracle, weight mixing, NSRA schedule,
and the full NS/NSR/NSRA training loops (SURVEY.md §4)."""

import jax
import numpy as np
import optax
import pytest

from estorch_tpu import NS_ES, NSR_ES, NSRA_ES, JaxAgent, MLPPolicy, NoveltyArchive
from estorch_tpu.envs import CartPole
from estorch_tpu.ops import centered_rank_np


class TestArchive:
    def test_knn_matches_bruteforce_oracle(self):
        rng = np.random.RandomState(0)
        ar = NoveltyArchive(k=3)
        for _ in range(20):
            ar.add(rng.randn(4))
        queries = rng.randn(7, 4).astype(np.float32)
        got = ar.novelty(queries)
        # brute force oracle
        a = ar.bcs
        for i, q in enumerate(queries):
            d = np.sort(np.linalg.norm(a - q, axis=1))
            expected = d[:3].mean()
            np.testing.assert_allclose(got[i], expected, rtol=1e-5)

    def test_empty_archive_is_uniformly_novel(self):
        ar = NoveltyArchive(k=5)
        out = ar.novelty(np.random.randn(4, 2))
        np.testing.assert_array_equal(out, np.ones(4, dtype=np.float32))

    def test_k_larger_than_archive(self):
        ar = NoveltyArchive(k=10)
        ar.add(np.zeros(2))
        ar.add(np.ones(2))
        # k=10 > 2 entries: averages over all available
        out = ar.novelty(np.zeros(2))
        expected = (0.0 + np.sqrt(2.0)) / 2
        np.testing.assert_allclose(out, expected, rtol=1e-6)

    def test_dim_mismatch_rejected(self):
        ar = NoveltyArchive(k=2)
        ar.add(np.zeros(3))
        with pytest.raises(ValueError, match="dim"):
            ar.add(np.zeros(4))

    def test_single_query_returns_scalar(self):
        ar = NoveltyArchive(k=2)
        ar.add(np.zeros(2))
        out = ar.novelty(np.ones(2))
        assert np.ndim(out) == 0 or out.shape == ()

    def test_max_size_evicts_oldest(self):
        ar = NoveltyArchive(k=2, max_size=3)
        for i in range(5):
            ar.add(np.full(2, float(i)))
        assert len(ar) == 3
        np.testing.assert_array_equal(ar.bcs[:, 0], [2.0, 3.0, 4.0])
        # roundtrip preserves the cap
        ar2 = NoveltyArchive.from_state_dict(ar.state_dict())
        assert ar2.max_size == 3 and len(ar2) == 3

    def test_state_dict_roundtrip(self):
        ar = NoveltyArchive(k=4)
        for i in range(5):
            ar.add(np.full(3, float(i)))
        ar2 = NoveltyArchive.from_state_dict(ar.state_dict())
        assert len(ar2) == 5
        q = np.random.randn(2, 3)
        np.testing.assert_allclose(ar.novelty(q), ar2.novelty(q))


class TestWeightMixing:
    fitness = np.array([3.0, 1.0, 2.0, 5.0], dtype=np.float32)
    novelty = np.array([0.1, 0.9, 0.5, 0.2], dtype=np.float32)

    def _mk(self, cls, **extra):
        return cls(
            MLPPolicy, JaxAgent, optax.adam,
            population_size=16, sigma=0.1, seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (8,)},
            agent_kwargs={"env": CartPole(), "horizon": 20},
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 16, meta_population_size=2,
            **extra,
        )

    def test_ns_uses_novelty_only(self):
        es = self._mk(NS_ES)
        w = es._combine_weights(self.fitness, self.novelty)
        np.testing.assert_array_equal(w, centered_rank_np(self.novelty))

    def test_nsr_is_equal_mix(self):
        es = self._mk(NSR_ES)
        w = es._combine_weights(self.fitness, self.novelty)
        expected = 0.5 * centered_rank_np(self.fitness) + 0.5 * centered_rank_np(self.novelty)
        np.testing.assert_allclose(w, expected)

    def test_nsra_respects_weight(self):
        es = self._mk(NSRA_ES, weight=0.25)
        w = es._combine_weights(self.fitness, self.novelty)
        expected = 0.25 * centered_rank_np(self.fitness) + 0.75 * centered_rank_np(self.novelty)
        np.testing.assert_allclose(w, expected)


class TestNSRASchedule:
    def test_w_rises_on_improvement_and_decays_on_stagnation(self):
        es = TestWeightMixing()._mk(
            NSRA_ES, weight=0.5, weight_delta=0.1, stagnation_patience=2
        )
        # improvement → w up
        es._post_update({"improved_best": True})
        assert es.weight == pytest.approx(0.6)
        # two stagnant generations → one decay step
        es._post_update({"improved_best": False})
        assert es.weight == pytest.approx(0.6)
        es._post_update({"improved_best": False})
        assert es.weight == pytest.approx(0.5)
        # bounds: repeated improvement pushes w up, capped at 1.0
        for _ in range(30):
            es._post_update({"improved_best": True})
        assert es.weight == 1.0

    def test_w_floor_at_zero(self):
        es = TestWeightMixing()._mk(
            NSRA_ES, weight=0.1, weight_delta=0.2, stagnation_patience=1
        )
        es._post_update({"improved_best": False})
        assert es.weight == 0.0
        es._post_update({"improved_best": False})
        assert es.weight == 0.0


class TestNoveltyTraining:
    def _train(self, cls, **extra):
        es = cls(
            MLPPolicy, JaxAgent, optax.adam,
            population_size=16, sigma=0.1, seed=1,
            policy_kwargs={"action_dim": 2, "hidden": (8,)},
            agent_kwargs={"env": CartPole(), "horizon": 50},
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 16, meta_population_size=2, k=3,
            **extra,
        )
        es.train(3, verbose=False)
        return es

    def test_ns_es_trains_and_archive_grows(self):
        es = self._train(NS_ES)
        # archive: meta_population_size seeds + 1 per generation
        assert len(es.archive) == 2 + 3
        assert len(es.history) == 3
        rec = es.history[-1]
        for key in ("meta_index", "novelty_mean", "archive_size", "center_reward"):
            assert key in rec

    @pytest.mark.slow
    def test_nsr_es_on_locomotion_bc(self):
        """Novelty family composes with the device-native locomotion envs:
        the BC is the env's own behavior() (final torso x, y), so archive
        entries are 2-D displacement points, and training runs end-to-end
        inside the compiled generation."""
        from estorch_tpu.envs import Hopper2D

        env = Hopper2D()
        es = NSR_ES(
            MLPPolicy, JaxAgent, optax.adam,
            population_size=16, sigma=0.1, seed=1,
            policy_kwargs={"action_dim": env.action_dim, "hidden": (8,),
                           "discrete": False, "action_scale": 1.0},
            agent_kwargs={"env": env, "horizon": 40},
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 16, meta_population_size=2, k=3,
        )
        es.train(2, verbose=False)
        assert es.archive.bc_dim == env.bc_dim == 2
        assert len(es.archive) == 2 + 2
        assert np.isfinite(es.history[-1]["reward_mean"])

    def test_nsr_es_trains(self):
        es = self._train(NSR_ES)
        assert len(es.history) == 3

    def test_nsra_es_trains_and_logs_weight(self):
        es = self._train(NSRA_ES, weight=0.8)
        assert "nsra_weight" in es.history[-1]
        assert 0.0 <= es.history[-1]["nsra_weight"] <= 1.0

    def test_fixed_seed_determinism(self):
        a = self._train(NS_ES)
        b = self._train(NS_ES)
        np.testing.assert_array_equal(
            np.asarray(a.meta_states[0].params_flat),
            np.asarray(b.meta_states[0].params_flat),
        )
        assert a.history[-1]["reward_mean"] == b.history[-1]["reward_mean"]

    def test_evaluate_policy_meta_index(self):
        es = self._train(NS_ES)
        e0 = es.evaluate_policy(n_episodes=2, meta_index=0)
        e1 = es.evaluate_policy(n_episodes=2, meta_index=1)
        assert e0["episodes"] == e1["episodes"] == 2
        # meta_index must select DISTINCT centers.  Their REWARDS can
        # legitimately tie (on jax 0.4's random stream both centers cap
        # the horizon every episode), so the selection contract is pinned
        # on the parameters rather than on the evaluations differing.
        p0 = np.asarray(es.meta_states[0].params_flat)
        p1 = np.asarray(es.meta_states[1].params_flat)
        assert not np.array_equal(p0, p1)

    def test_meta_index_rejected_on_plain_es(self):
        import optax

        from estorch_tpu import ES, JaxAgent, MLPPolicy
        from estorch_tpu.envs import CartPole

        es = ES(MLPPolicy, JaxAgent, optax.adam, population_size=16,
                policy_kwargs={"action_dim": 2, "hidden": (8,)},
                agent_kwargs={"env": CartPole(), "horizon": 20},
                optimizer_kwargs={"learning_rate": 1e-2}, table_size=1 << 14)
        with pytest.raises(ValueError, match="novelty family"):
            es.evaluate_policy(meta_index=0)

    def test_meta_population_centers_start_distinct(self):
        es = self._train(NS_ES)
        p0 = np.asarray(es.meta_states[0].params_flat)
        p1 = np.asarray(es.meta_states[1].params_flat)
        assert not np.array_equal(p0, p1)
