"""Device-native env parity (vs gymnasium oracles) and rollout-scan tests."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from estorch_tpu.envs import (
    Acrobot,
    CartPole,
    MountainCarContinuous,
    Pendulum,
    make_population_rollout,
    make_rollout,
)


def _drive_gym(env_id, set_state, actions, read_obs):
    """Step a gymnasium env through a fixed action sequence from a set state."""
    genv = gym.make(env_id)
    genv.reset(seed=0)
    set_state(genv.unwrapped)
    traj = []
    for a in actions:
        obs, r, term, trunc, _ = genv.step(a)
        traj.append((read_obs(genv.unwrapped, obs), float(r), bool(term)))
        if term or trunc:
            break
    genv.close()
    return traj


class TestCartPoleParity:
    def test_step_for_step_vs_gymnasium(self):
        start = np.array([0.01, -0.02, 0.03, 0.015], dtype=np.float64)
        actions = [1, 1, 0, 1, 0, 0, 1, 1, 1, 0, 1, 0, 1, 1, 0, 0, 0, 1, 1, 1]

        def set_state(u):
            u.state = start.copy()

        gym_traj = _drive_gym("CartPole-v1", set_state, actions, lambda u, o: np.array(u.state))

        env = CartPole()
        state = jnp.array(start, dtype=jnp.float32)
        for i, ((gobs, grew, gterm), a) in enumerate(zip(gym_traj, actions)):
            state, obs, rew, done = env.step(state, jnp.int32(a))
            np.testing.assert_allclose(np.asarray(obs), gobs, rtol=1e-5, atol=1e-6,
                                       err_msg=f"diverged at step {i}")
            assert float(rew) == grew
            assert bool(done) == gterm

    def test_termination_bounds(self):
        env = CartPole()
        st = jnp.array([2.5, 0.0, 0.0, 0.0])  # |x| beyond threshold after step
        _, _, _, done = env.step(st, jnp.int32(0))
        assert bool(done)

    def test_reset_range(self):
        env = CartPole()
        st, obs = env.reset(jax.random.key(0))
        assert np.all(np.abs(np.asarray(st)) <= 0.05)
        np.testing.assert_array_equal(np.asarray(st), np.asarray(obs))


class TestPendulumParity:
    def test_step_for_step_vs_gymnasium(self):
        start = np.array([0.7, -0.3], dtype=np.float64)  # (theta, thdot)
        actions = [np.array([0.5]), np.array([-1.2]), np.array([2.5]), np.array([0.0]),
                   np.array([-2.5]), np.array([1.0])]

        def set_state(u):
            u.state = start.copy()

        gym_traj = _drive_gym("Pendulum-v1", set_state, actions,
                              lambda u, o: np.asarray(o, dtype=np.float64))

        env = Pendulum()
        state = jnp.array(start, dtype=jnp.float32)
        for i, ((gobs, grew, _), a) in enumerate(zip(gym_traj, actions)):
            state, obs, rew, done = env.step(state, jnp.array(a, dtype=jnp.float32))
            np.testing.assert_allclose(np.asarray(obs), gobs, rtol=1e-4, atol=1e-5,
                                       err_msg=f"diverged at step {i}")
            np.testing.assert_allclose(float(rew), grew, rtol=1e-4, atol=1e-5)


class TestMountainCarParity:
    def test_step_for_step_vs_gymnasium(self):
        start = np.array([-0.5, 0.0], dtype=np.float64)
        actions = [np.array([1.0]), np.array([1.0]), np.array([-0.3]), np.array([0.8])]

        def set_state(u):
            u.state = start.copy()

        gym_traj = _drive_gym("MountainCarContinuous-v0", set_state, actions,
                              lambda u, o: np.asarray(o, dtype=np.float64))

        env = MountainCarContinuous()
        state = jnp.array(start, dtype=jnp.float32)
        for i, ((gobs, grew, _), a) in enumerate(zip(gym_traj, actions)):
            state, obs, rew, done = env.step(state, jnp.array(a, dtype=jnp.float32))
            np.testing.assert_allclose(np.asarray(obs), gobs, rtol=1e-4, atol=1e-5,
                                       err_msg=f"diverged at step {i}")
            np.testing.assert_allclose(float(rew), grew, rtol=1e-4, atol=1e-5)


class TestMountainCarDiscreteParity:
    def test_step_for_step_vs_gymnasium(self):
        from estorch_tpu.envs import MountainCar

        start = np.array([-0.5, 0.0], dtype=np.float64)
        actions = [2, 2, 0, 1, 2, 2, 0, 2]

        def set_state(u):
            u.state = start.copy()

        gym_traj = _drive_gym("MountainCar-v0", set_state, actions,
                              lambda u, o: np.asarray(o, dtype=np.float64))

        env = MountainCar()
        state = jnp.array(start, dtype=jnp.float32)
        for i, ((gobs, grew, gterm), a) in enumerate(zip(gym_traj, actions)):
            state, obs, rew, done = env.step(state, jnp.int32(a))
            np.testing.assert_allclose(np.asarray(obs), gobs, rtol=1e-4, atol=1e-6,
                                       err_msg=f"diverged at step {i}")
            assert float(rew) == grew
            assert bool(done) == gterm


class TestAcrobotParity:
    def test_step_for_step_vs_gymnasium(self):
        start = np.array([0.05, -0.08, 0.02, 0.06], dtype=np.float64)
        actions = [0, 2, 1, 2, 2, 0, 1, 2, 0, 2]

        def set_state(u):
            u.state = start.copy()

        gym_traj = _drive_gym(
            "Acrobot-v1", set_state, actions,
            lambda u, o: np.asarray(o, dtype=np.float64),
        )

        env = Acrobot()
        state = jnp.array(start, dtype=jnp.float32)
        for i, ((gobs, grew, gterm), a) in enumerate(zip(gym_traj, actions)):
            state, obs, rew, done = env.step(state, jnp.int32(a))
            np.testing.assert_allclose(np.asarray(obs), gobs, rtol=1e-3, atol=2e-4,
                                       err_msg=f"diverged at step {i}")
            assert float(rew) == grew
            assert bool(done) == gterm

    def test_swingup_termination(self):
        """A state with both links up must read as terminal after a step."""
        env = Acrobot()
        # theta1 = pi (first link up), theta2 = 0 -> height = 2 > 1
        s = jnp.array([jnp.pi, 0.0, 0.0, 0.0])
        _, _, rew, done = env.step(s, jnp.int32(1))
        assert bool(done)
        assert float(rew) == 0.0


class TestRolloutScan:
    def _zero_policy(self, params, obs):
        # always pushes left (action 0 for discrete argmax of [1, 0])
        return jnp.array([1.0, 0.0])

    def test_done_masking_freezes_reward(self):
        """Always-left on CartPole falls quickly; return == alive steps, < horizon."""
        env = CartPole()
        rollout = make_rollout(env, self._zero_policy, horizon=200)
        res = jax.jit(rollout)({}, jax.random.key(0))
        assert 1 <= int(res.steps) < 200
        # CartPole gives +1 per alive step, so return must equal steps
        assert float(res.total_reward) == float(res.steps)

    def test_rollout_matches_python_loop(self):
        """Scan result == plain Python loop over env.step with same policy."""
        env = CartPole()
        horizon = 50
        rollout = make_rollout(env, self._zero_policy, horizon)
        key = jax.random.key(3)
        res = rollout({}, key)

        state, obs = env.reset(key)
        total, steps, done = 0.0, 0, False
        for _ in range(horizon):
            if done:
                break
            action = jnp.argmax(self._zero_policy({}, obs))
            state, obs, r, d = env.step(state, action)
            total += float(r)
            steps += 1
            done = bool(d)
        assert float(res.total_reward) == pytest.approx(total)
        assert int(res.steps) == steps

    def test_bc_reads_final_alive_frame(self):
        """BC must come from the state at termination, not the horizon end."""
        env = CartPole()
        horizon = 300
        rollout = make_rollout(env, self._zero_policy, horizon)
        key = jax.random.key(1)
        res = rollout({}, key)

        state, obs = env.reset(key)
        done = False
        for _ in range(horizon):
            if done:
                break
            action = jnp.argmax(self._zero_policy({}, obs))
            state, obs, r, d = env.step(state, action)
            done = bool(d)
        expected_bc = np.asarray(env.behavior(state, obs))
        np.testing.assert_allclose(np.asarray(res.bc), expected_bc, rtol=1e-5, atol=1e-6)

    def test_population_vmap_shapes(self):
        env = Pendulum()
        n = 8

        def policy(params, obs):
            return jnp.tanh(params["w"] @ obs) * 2.0

        pop_rollout = make_population_rollout(env, policy, horizon=20)
        params = {"w": jax.random.normal(jax.random.key(0), (n, 1, 3))}
        keys = jax.random.split(jax.random.key(1), n)
        res = jax.jit(pop_rollout)(params, keys)
        assert res.total_reward.shape == (n,)
        assert res.bc.shape == (n, env.bc_dim)
        assert res.steps.shape == (n,)
        # pendulum never terminates: all members run the full horizon
        assert np.all(np.asarray(res.steps) == 20)
        # different params must give different returns
        assert len(set(np.asarray(res.total_reward).round(4).tolist())) > 1


class TestSyntheticEnv:
    """Benchmark env: protocol shape + honest dynamics (obs varies, no term)."""

    def test_rollout_contract(self):
        from estorch_tpu.envs import SyntheticEnv

        env = SyntheticEnv(obs_dim=16, action_dim=3)

        def policy(params, obs):
            return jnp.tanh(params["w"] @ obs)

        rollout = make_rollout(env, policy, horizon=30)
        params = {"w": jax.random.normal(jax.random.key(0), (3, 16))}
        res = jax.jit(rollout)(params, jax.random.key(1))
        assert int(res.steps) == 30  # never terminates
        assert res.bc.shape == (env.bc_dim,)
        assert np.isfinite(float(res.total_reward))

    def test_observations_vary_and_respond_to_action(self):
        from estorch_tpu.envs import SyntheticEnv

        env = SyntheticEnv(obs_dim=8, action_dim=2)
        state, obs0 = env.reset(jax.random.key(0))
        state1, obs1, r1, d1 = env.step(state, jnp.ones(2))
        state2, obs2, r2, d2 = env.step(state, -jnp.ones(2))
        assert not bool(d1) and not bool(d2)
        assert not np.allclose(np.asarray(obs1), np.asarray(obs0))
        # opposite actions produce different successor observations
        assert not np.allclose(np.asarray(obs1), np.asarray(obs2))

    def test_state_stays_bounded(self):
        from estorch_tpu.envs import SyntheticEnv

        env = SyntheticEnv(obs_dim=8, action_dim=2)
        state, _ = env.reset(jax.random.key(0))
        for i in range(500):
            state, obs, r, d = env.step(state, jnp.ones(2))
        assert np.all(np.isfinite(np.asarray(obs)))
        assert np.max(np.abs(np.asarray(obs))) < 100.0
