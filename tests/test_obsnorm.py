"""Observation normalization (EngineConfig.obs_norm): running raw-obs
moments carried in ESState, refreshed in-program from center-policy probe
episodes, applied to every policy input.

The reference has no such machinery (its only input trick is VBN); this
is the OpenAI-ES MuJoCo staple rebuilt TPU-first — the stats ride the
replicated training state, so the whole generation (members + probe +
center eval) normalizes with one consistent snapshot and resumes
bit-exactly from checkpoints.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from estorch_tpu import ES, JaxAgent, MLPPolicy, RecurrentPolicy
from estorch_tpu.envs import CartPole, Pendulum
from estorch_tpu.ops import centered_rank_np
from estorch_tpu.parallel.engine import normalize_obs


def _pendulum_es(**over):
    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=64,
        sigma=0.05,
        policy_kwargs={"action_dim": 1, "hidden": (16,), "discrete": False,
                       "action_scale": 2.0},
        agent_kwargs={"env": Pendulum(), "horizon": 100},
        optimizer_kwargs={"learning_rate": 1e-2},
        seed=0,
        obs_norm=True,
    )
    kw.update(over)
    return ES(**kw)


class TestNormalizeObsMath:
    def test_oracle(self):
        rng = np.random.default_rng(0)
        obs = rng.normal(size=7).astype(np.float32)
        cnt = 50.0
        mean = rng.normal(size=7).astype(np.float32)
        m2 = (rng.random(7).astype(np.float32) + 0.5) * cnt
        got = np.asarray(normalize_obs(
            jnp.asarray(obs),
            (jnp.float32(cnt), jnp.asarray(mean), jnp.asarray(m2)),
            5.0,
        ))
        var = np.maximum(m2 / cnt, 1e-8)
        want = np.clip((obs - mean) / np.sqrt(var), -5, 5)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_clip_applied(self):
        stats = (jnp.float32(1.0), jnp.zeros(3), jnp.full((3,), 1e-6))
        out = np.asarray(normalize_obs(jnp.full((3,), 100.0), stats, 5.0))
        assert (out == 5.0).all()

    def test_merge_matches_batch_moments(self):
        """Chan-merging per-generation sums must reproduce the exact batch
        mean/var of the concatenated samples."""
        from estorch_tpu.parallel.engine import merge_obs_moments

        rng = np.random.default_rng(1)
        a = rng.normal(2.0, 3.0, size=(400, 5)).astype(np.float32)
        b = rng.normal(-1.0, 0.5, size=(250, 5)).astype(np.float32)
        stats = (
            jnp.float32(len(a)),
            jnp.asarray(a.mean(0)),
            jnp.asarray(((a - a.mean(0)) ** 2).sum(0)),
        )
        merged = merge_obs_moments(
            stats,
            jnp.float32(len(b)),
            jnp.asarray(b.sum(0)),
            jnp.asarray((b * b).sum(0)),
        )
        both = np.concatenate([a, b])
        assert float(merged[0]) == len(both)
        np.testing.assert_allclose(np.asarray(merged[1]), both.mean(0),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(merged[2]) / len(both),
                                   both.var(0), rtol=1e-3, atol=1e-3)

    def test_large_mean_no_cancellation(self):
        """|mean| >> std — the case naive sum/sumsq accumulation destroys
        in f32 (E[x²]−mean² cancels catastrophically at mean≈100,
        std≈0.1). The Welford triple must recover the tiny variance."""
        from estorch_tpu.parallel.engine import merge_obs_moments

        rng = np.random.default_rng(2)
        stats = (jnp.float32(1.0), jnp.zeros(1), jnp.ones(1))
        for _ in range(50):
            batch = rng.normal(100.0, 0.1, size=(200, 1)).astype(np.float32)
            stats = merge_obs_moments(
                stats,
                jnp.float32(len(batch)),
                jnp.asarray(batch.sum(0)),
                jnp.asarray((batch * batch).sum(0)),
            )
        var = float(stats[2][0] / stats[0])
        # init (mean 0, var 1) washes out after 10k samples; the estimate
        # must land near 0.01, not at the 1e-8 floor or negative
        assert 0.004 < var < 1.1, var
        assert abs(float(stats[1][0]) - 100.0) < 0.5


class TestStatsAccounting:
    @pytest.mark.slow
    def test_probe_count_is_exact(self):
        """Pendulum never terminates, so after G generations with E probe
        episodes of H steps each: count = 1 (init) + G*E*H, exactly."""
        es = _pendulum_es(obs_probe_episodes=2)
        es.train(3, verbose=False)
        cnt, mean, m2 = es.state.obs_stats
        assert float(cnt) == 1.0 + 3 * 2 * 100
        mean = np.asarray(mean)
        var = np.asarray(m2 / cnt)
        # Pendulum obs = (cosθ, sinθ, θ̇): trig dims bounded by 1, so only
        # THEIR means are bounded; θ̇ is unbounded and its mean depends on
        # the jax version's random stream (observed 1.95 on jax 0.4)
        assert np.all(np.abs(mean[:2]) <= 1.0 + 1e-6) and np.all(var > 0)
        assert var[2] > var[0], "velocity variance should dominate trig dims"

    @pytest.mark.slow
    def test_stats_only_when_enabled(self):
        es = _pendulum_es(obs_norm=False)
        es.train(1, verbose=False)
        assert es.state.obs_stats is None

    @pytest.mark.slow
    def test_warmup_folds_init_probes_exactly(self):
        """obs_warmup_episodes=3 on Pendulum (h=100, never terminates):
        init count = 1 + 3·100, real (non-identity) moments before
        generation 0, then the per-gen probes keep the count exact."""
        es = _pendulum_es(obs_warmup_episodes=3)
        cnt, mean, m2 = es.state.obs_stats
        assert float(cnt) == 1.0 + 3 * 100
        assert float(np.abs(np.asarray(mean)).max()) > 0.0
        es.train(2, verbose=False)
        assert float(es.state.obs_stats[0]) == 1.0 + 3 * 100 + 2 * 100

    def test_warmup_is_deterministic(self):
        a = _pendulum_es(obs_warmup_episodes=2)
        b = _pendulum_es(obs_warmup_episodes=2)
        for x, y in zip(a.state.obs_stats, b.state.obs_stats):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_warmup_requires_obs_norm(self):
        with pytest.raises(ValueError, match="obs_norm"):
            _pendulum_es(obs_norm=False, obs_warmup_episodes=2)

    def test_warmup_rejected_on_pooled(self):
        from estorch_tpu import PooledAgent

        with pytest.raises(ValueError, match="device-path"):
            ES(
                policy=MLPPolicy, agent=PooledAgent, optimizer=optax.adam,
                population_size=16, sigma=0.1,
                policy_kwargs={"action_dim": 2, "hidden": (8,),
                               "discrete": True},
                agent_kwargs={"env_name": "cartpole", "horizon": 32},
                optimizer_kwargs={"learning_rate": 1e-2},
                obs_norm=True, obs_warmup_episodes=2,
            )


class TestSplitEqualsFused:
    @pytest.mark.slow
    def test_split_path_matches_generation_step(self):
        """The novelty family's evaluate→rank→apply path must produce the
        SAME params and the SAME refreshed obs_stats as the fused program."""
        es = _pendulum_es()
        eng, state = es.engine, es.state
        fused, _ = eng.generation_step(state)

        ev = eng.evaluate(state)
        w = centered_rank_np(np.asarray(ev.fitness))
        split, _ = eng.apply_weights(state, jnp.asarray(w))

        np.testing.assert_allclose(
            np.asarray(split.params_flat), np.asarray(fused.params_flat),
            rtol=1e-5, atol=1e-7,
        )
        for a, b in zip(split.obs_stats, fused.obs_stats):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestCheckpointRoundtrip:
    @pytest.mark.slow
    def test_bit_exact_resume_with_obs_norm(self, tmp_path):
        from estorch_tpu.utils import restore_checkpoint, save_checkpoint

        es = _pendulum_es()
        es.train(2, verbose=False)
        save_checkpoint(es, tmp_path / "ck")

        es2 = _pendulum_es()
        restore_checkpoint(es2, tmp_path / "ck")
        for a, b in zip(es.state.obs_stats, es2.state.obs_stats):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        es.train(1, verbose=False)
        es2.train(1, verbose=False)
        np.testing.assert_array_equal(
            np.asarray(es.state.params_flat), np.asarray(es2.state.params_flat)
        )
        for a, b in zip(es.state.obs_stats, es2.state.obs_stats):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestGuards:
    def test_host_rejected(self):
        with pytest.raises(ValueError, match="TorchRunningObsNorm"):
            ES(
                policy=lambda: None, agent=_DummyHostAgent,
                optimizer=optax.adam, population_size=8, sigma=0.1,
                obs_norm=True,
            )

    def test_vbn_rejected(self):
        with pytest.raises(ValueError, match="VirtualBatchNorm"):
            ES(
                policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
                population_size=16, sigma=0.1,
                policy_kwargs={"action_dim": 2, "hidden": (8,),
                               "discrete": True, "use_vbn": True},
                agent_kwargs={"env": CartPole(), "horizon": 32},
                optimizer_kwargs={"learning_rate": 1e-2},
                obs_norm=True,
            )

    @pytest.mark.slow
    def test_obs_norm_checkpoint_mismatch_rejected(self, tmp_path):
        from estorch_tpu.utils import restore_checkpoint, save_checkpoint

        es = _pendulum_es()
        es.train(1, verbose=False)
        save_checkpoint(es, tmp_path / "ck")
        es_off = _pendulum_es(obs_norm=False)
        with pytest.raises(ValueError, match="obs_norm"):
            restore_checkpoint(es_off, tmp_path / "ck")

    def test_pooled_prep_rejected(self):
        from estorch_tpu import PooledAgent

        with pytest.raises(ValueError, match="preprocessing"):
            ES(
                policy=MLPPolicy, agent=PooledAgent, optimizer=optax.adam,
                population_size=16, sigma=0.1,
                policy_kwargs={"action_dim": 3, "hidden": (8,),
                               "discrete": True},
                agent_kwargs={"env_name": "pong84", "horizon": 32,
                              "frame_stack": 4},
                optimizer_kwargs={"learning_rate": 1e-2},
                obs_norm=True,
            )


class _DummyHostAgent:
    def rollout(self, policy):
        return 0.0


class TestCombosAndLearning:
    @pytest.mark.slow
    def test_recurrent_plus_obs_norm_runs(self):
        from estorch_tpu.envs import RecallEnv

        es = ES(
            policy=RecurrentPolicy, agent=JaxAgent, optimizer=optax.adam,
            population_size=32, sigma=0.1,
            policy_kwargs={"action_dim": 1, "hidden": (8,), "gru_size": 8,
                           "discrete": False},
            agent_kwargs={"env": RecallEnv(), "horizon": 16},
            optimizer_kwargs={"learning_rate": 5e-2}, seed=0,
            obs_norm=True,
        )
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])
        assert es.state.obs_stats is not None

    @pytest.mark.slow
    def test_cartpole_learns_with_obs_norm(self):
        es = ES(
            policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
            population_size=128, sigma=0.1,
            policy_kwargs={"action_dim": 2, "hidden": (16,), "discrete": True},
            agent_kwargs={"env": CartPole(), "horizon": 200},
            optimizer_kwargs={"learning_rate": 3e-2}, seed=0,
            obs_norm=True,
        )
        es.train(25, verbose=False)
        assert es.history[-1]["reward_mean"] > 150, es.history[-1]

    @pytest.mark.slow
    def test_bf16_obs_norm_runs(self):
        es = _pendulum_es(compute_dtype="bfloat16")
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])


class TestObsNormModeCombos:
    """obs_norm composes with every noise representation (round-3 VERDICT
    missing #2: the north-star Humanoid config wants obs_norm AND low_rank).
    Normalization is an input-side transform — each specialized forward
    (decomposed, streamed, low_rank) normalizes raw obs in f32 against the
    same per-generation stats snapshot the standard path uses."""

    def _es(self, **over):
        kw = dict(
            policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
            population_size=32, sigma=0.1, seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (16,)},
            agent_kwargs={"env": CartPole(), "horizon": 60},
            optimizer_kwargs={"learning_rate": 2e-2},
            table_size=1 << 16, obs_norm=True,
        )
        kw.update(over)
        return ES(**kw)

    def test_decomposed_identical_to_standard(self):
        """decomposed is a reordering, not an approximation — with obs_norm
        on, params AND refreshed obs stats must match the standard path."""
        a, b = self._es(), self._es(decomposed=True)
        a.train(3, verbose=False)
        b.train(3, verbose=False)
        for ra, rb in zip(a.history, b.history):
            assert ra["reward_mean"] == pytest.approx(
                rb["reward_mean"], rel=1e-6, abs=1.0)
        np.testing.assert_allclose(
            np.asarray(a.state.params_flat), np.asarray(b.state.params_flat),
            rtol=1e-4, atol=1e-5,
        )
        for sa, sb in zip(a.state.obs_stats, b.state.obs_stats):
            np.testing.assert_allclose(np.asarray(sa), np.asarray(sb),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.slow
    def test_streamed_matches_decomposed(self):
        """streamed is the Pallas kernel form of decomposed — same math,
        obs normalized before the population-batched forward."""
        a, b = self._es(decomposed=True), self._es(streamed=True)
        a.train(2, verbose=False)
        b.train(2, verbose=False)
        for ra, rb in zip(a.history, b.history):
            assert ra["reward_mean"] == pytest.approx(
                rb["reward_mean"], rel=1e-5, abs=1.0)
        np.testing.assert_allclose(
            np.asarray(a.state.params_flat), np.asarray(b.state.params_flat),
            rtol=1e-4, atol=1e-5,
        )

    def test_low_rank_trains_and_stats_exact(self):
        """low_rank is a different search distribution (no standard-path
        equivalence); assert it trains, the probe count stays exact, and
        normalization demonstrably reaches the forward (stats converge)."""
        es = self._es(low_rank=1, obs_probe_episodes=2,
                      agent_kwargs={"env": Pendulum(), "horizon": 50},
                      policy_kwargs={"action_dim": 1, "hidden": (16,),
                                     "discrete": False, "action_scale": 2.0})
        es.train(3, verbose=False)
        cnt, mean, m2 = es.state.obs_stats
        assert float(cnt) == 1.0 + 3 * 2 * 50  # Pendulum never terminates
        assert np.isfinite(es.history[-1]["reward_mean"])
        assert (np.asarray(m2) > 0).all()

    def test_low_rank_split_equals_fused(self):
        es_a = self._es(low_rank=1)
        eng, state = es_a.engine, es_a.state
        fused, _ = eng.generation_step(state)
        ev = eng.evaluate(state)
        w = centered_rank_np(np.asarray(ev.fitness))
        split, _ = eng.apply_weights(state, jnp.asarray(w))
        np.testing.assert_allclose(
            np.asarray(split.params_flat), np.asarray(fused.params_flat),
            rtol=1e-5, atol=1e-7,
        )
        for a, b in zip(split.obs_stats, fused.obs_stats):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_low_rank_checkpoint_roundtrip(self, tmp_path):
        from estorch_tpu.utils import restore_checkpoint, save_checkpoint

        es = self._es(low_rank=1)
        es.train(2, verbose=False)
        save_checkpoint(es, tmp_path / "ck")
        es2 = self._es(low_rank=1)
        restore_checkpoint(es2, tmp_path / "ck")
        es.train(1, verbose=False)
        es2.train(1, verbose=False)
        np.testing.assert_array_equal(
            np.asarray(es.state.params_flat), np.asarray(es2.state.params_flat)
        )
        for a, b in zip(es.state.obs_stats, es2.state.obs_stats):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_low_rank_bf16_runs(self):
        es = self._es(low_rank=1, compute_dtype="bfloat16")
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])


class TestTorchHostTwin:
    """TorchRunningObsNorm must match the device path's math exactly."""

    def test_matches_device_normalize_and_merge(self):
        import torch

        from estorch_tpu.models import TorchRunningObsNorm
        from estorch_tpu.parallel.engine import merge_obs_moments

        rng = np.random.default_rng(3)
        tn = TorchRunningObsNorm(5)
        stats = (jnp.float32(1.0), jnp.zeros(5), jnp.ones(5))
        for _ in range(4):
            batch = rng.normal(3.0, 2.0, size=(100, 5)).astype(np.float32)
            tn.update(torch.from_numpy(batch))
            stats = merge_obs_moments(
                stats,
                jnp.float32(len(batch)),
                jnp.asarray(batch.sum(0)),
                jnp.asarray((batch * batch).sum(0)),
            )
        np.testing.assert_allclose(tn.count.numpy(), float(stats[0]))
        np.testing.assert_allclose(tn.mean.numpy(), np.asarray(stats[1]),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(tn.m2.numpy(), np.asarray(stats[2]),
                                   rtol=1e-3, atol=1e-2)

        obs = rng.normal(3.0, 2.0, size=(5,)).astype(np.float32)
        got_t = tn(torch.from_numpy(obs)).numpy()
        got_j = np.asarray(normalize_obs(jnp.asarray(obs), stats, 5.0))
        np.testing.assert_allclose(got_t, got_j, rtol=1e-4, atol=1e-4)

    def test_state_dict_roundtrip(self):
        import torch

        from estorch_tpu.models import TorchRunningObsNorm

        a = TorchRunningObsNorm(3)
        a.update(torch.randn(50, 3) * 4 + 1)
        b = TorchRunningObsNorm(3)
        b.load_state_dict(a.state_dict())
        x = torch.randn(3)
        np.testing.assert_array_equal(a(x).numpy(), b(x).numpy())


class TestPooledObsNorm:
    """Pooled-path obs_norm: normalization + moment accumulation happen
    host-side in the step loop; the Welford stats ride ESState.obs_stats
    exactly like the device path (checkpointed, split==fused), fed by
    EVERY member's observations rather than a center probe."""

    def _pooled_es(self, **over):
        from estorch_tpu import PooledAgent

        kw = dict(
            policy=MLPPolicy, agent=PooledAgent, optimizer=optax.adam,
            population_size=16, sigma=0.1,
            policy_kwargs={"action_dim": 2, "hidden": (8,),
                           "discrete": True},
            agent_kwargs={"env_name": "cartpole", "horizon": 32},
            optimizer_kwargs={"learning_rate": 1e-2}, seed=0,
            obs_norm=True,
        )
        kw.update(over)
        return ES(**kw)

    @pytest.mark.slow
    def test_trains_and_stats_grow(self):
        es = self._pooled_es()
        es.train(2, verbose=False)
        cnt, mean, m2 = es.state.obs_stats
        # every alive member-step fed the stats: count = 1 + total steps
        total_steps = sum(r["env_steps"] for r in es.history)
        assert float(cnt) == 1.0 + total_steps
        assert np.isfinite(np.asarray(mean)).all()
        assert (np.asarray(m2) > 0).all()
        assert np.isfinite(es.history[-1]["reward_mean"])
        ev = es.evaluate_policy(n_episodes=2)
        assert np.isfinite(ev["mean"])

    @pytest.mark.slow
    def test_split_equals_fused_pooled(self):
        """Two same-seeded instances (fresh pools → identical episode
        sequences): the fused generation_step must equal the explicit
        evaluate→rank→apply split, INCLUDING the merged obs stats.  (A
        single instance cannot be compared against itself — the pool RNG
        advances with every evaluation.)"""
        es_a = self._pooled_es()
        fused, _ = es_a.engine.generation_step(es_a.state)

        es_b = self._pooled_es()
        ev = es_b.engine.evaluate(es_b.state)
        from estorch_tpu.utils import rank_weights_with_failures

        w = rank_weights_with_failures(np.asarray(ev.fitness))
        split, _ = es_b.engine.apply_weights(es_b.state, w)
        np.testing.assert_allclose(
            np.asarray(split.params_flat), np.asarray(fused.params_flat),
            rtol=1e-5, atol=1e-7,
        )
        for a, b in zip(split.obs_stats, fused.obs_stats):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_checkpoint_roundtrip(self, tmp_path):
        from estorch_tpu.utils import restore_checkpoint, save_checkpoint

        es = self._pooled_es()
        es.train(2, verbose=False)
        save_checkpoint(es, tmp_path / "ck")
        es2 = self._pooled_es()
        restore_checkpoint(es2, tmp_path / "ck")
        for a, b in zip(es.state.obs_stats, es2.state.obs_stats):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_discarded_evaluation_moments_dropped(self):
        """A discarded evaluate() (eval-only probe, exception between the
        calls) must NOT fold its observations into a later, unrelated
        apply_weights — pending moments are generation-stamped and dropped
        on mismatch (round-3 ADVICE #3)."""
        from estorch_tpu.utils import rank_weights_with_failures

        es = self._pooled_es()
        eng = es.engine
        # probe evaluation whose update never happens
        eng.evaluate(es.state)
        assert eng._pending_moments is not None
        # a state from a DIFFERENT generation arrives at apply_weights
        later = es.state._replace(generation=es.state.generation + 1)
        n = es.population_size
        w = rank_weights_with_failures(np.zeros(n, np.float32))
        new_state, _ = eng.apply_weights(later, w)
        # stale moments dropped, stats untouched by the probe's samples
        assert eng._pending_moments is None
        assert float(new_state.obs_stats[0]) == float(es.state.obs_stats[0])

    @pytest.mark.slow
    def test_double_buffer_runs(self):
        es = self._pooled_es(
            agent_kwargs={"env_name": "cartpole", "horizon": 32,
                          "double_buffer": True},
        )
        es.train(1, verbose=False)
        assert float(es.state.obs_stats[0]) > 1.0

    @pytest.mark.slow
    def test_double_buffer_count_invariant(self):
        """Double-buffered stats must obey count == 1 + env_steps exactly
        like the sync path (moments accumulate at STEP time, not at
        dispatch — the trailing dispatch's actions are never stepped)."""
        es = self._pooled_es(
            agent_kwargs={"env_name": "cartpole", "horizon": 32,
                          "double_buffer": True},
        )
        es.train(2, verbose=False)
        total_steps = sum(r["env_steps"] for r in es.history)
        assert float(es.state.obs_stats[0]) == 1.0 + total_steps
