"""Observability subsystem (estorch_tpu/obs/): spans, counters, flight
recorder + heartbeat, manifest round-trip, summarize CLI, and the
record-schema contract against REAL training records.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from estorch_tpu.obs import (Counters, FlightRecorder, Heartbeat,
                             JsonlSink, Telemetry, collect_manifest,
                             load_manifest, read_heartbeat,
                             resolve_telemetry, summarize, validate_record,
                             write_manifest)
from estorch_tpu.obs.recorder import STALE_AFTER_S
from estorch_tpu.obs.summarize import GOLDEN_RECORD, selfcheck


# ---------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------

class TestSpans:
    def test_basic_phase_accumulation(self):
        t = Telemetry()
        with t.phase("eval"):
            time.sleep(0.005)
        with t.phase("eval"):
            time.sleep(0.005)
        with t.phase("update"):
            pass
        ph = t.take_phases()
        assert set(ph) == {"eval", "update"}
        assert ph["eval"] >= 0.01
        # take_phases flushes: the next generation starts clean
        assert t.take_phases() == {}

    def test_nesting_records_parent_and_child(self):
        t = Telemetry()
        with t.phase("update"):
            with t.phase("obsnorm_merge"):
                time.sleep(0.005)
        ph = t.take_phases()
        assert set(ph) == {"update", "update/obsnorm_merge"}
        # the parent's time includes the child's
        assert ph["update"] >= ph["update/obsnorm_merge"]

    def test_fence_runs_inside_the_clock(self):
        t = Telemetry()
        fenced = []

        def fence():
            fenced.append(time.perf_counter())
            time.sleep(0.01)

        with t.phase("device", fence=fence):
            pass
        ph = t.take_phases()
        assert fenced, "fence must be invoked"
        assert ph["device"] >= 0.01, "fence time must land in the span"

    def test_generation_advances_and_counters_ride(self):
        t = Telemetry()
        with t.phase("eval"):
            pass
        t.take_phases()
        with t.phase("eval"):
            pass
        t.take_phases()
        assert t.generation == 2
        snap = t.counters.snapshot()
        assert snap["generations"] == 2
        assert snap["peak_rss_mb"] > 0

    def test_disabled_is_inert(self):
        t = Telemetry(enabled=False)
        with t.phase("eval"):
            pass
        assert t.take_phases() == {}
        assert len(t.recorder) == 0

    def test_overhead_is_small(self):
        """10k enabled spans in well under a second — the 'low-overhead'
        claim, with enormous CI headroom (the real budget is <2% of a
        bench generation; see bench.py --obs-ab)."""
        t = Telemetry()
        t0 = time.perf_counter()
        for _ in range(10_000):
            with t.phase("eval"):
                pass
        enabled = time.perf_counter() - t0
        assert enabled < 1.0, f"10k spans took {enabled:.3f}s"

    def test_resolve_telemetry_contract(self):
        assert resolve_telemetry(False).enabled is False
        assert resolve_telemetry(True).enabled is True
        t = Telemetry()
        assert resolve_telemetry(t) is t
        assert resolve_telemetry(None).enabled is True  # default-on
        with pytest.raises(TypeError):
            resolve_telemetry("yes")

    def test_env_disable(self, monkeypatch):
        monkeypatch.setenv("ESTORCH_OBS", "0")
        assert resolve_telemetry(None).enabled is False

    def test_aborted_generation_spans_are_discardable(self):
        """A generation that raises mid-phase leaves partial spans; train
        loops discard them on (re-)entry so they never pollute the next
        successful record — but the flight recorder keeps them."""
        t = Telemetry()
        with pytest.raises(RuntimeError):
            with t.phase("eval"):
                raise RuntimeError("dead env")
        assert "eval" in t._acc  # partial span recorded
        t.discard_phases()
        assert t.take_phases() == {}
        assert any(e["name"] == "eval" for e in t.recorder.events())


# ---------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------

class TestCounters:
    def test_inc_gauge_snapshot(self):
        c = Counters()
        c.inc("env_steps", 100)
        c.inc("env_steps", 50)
        c.gauge("compile_time_s", 3.5)
        c.gauge("compile_time_s", 4.5)  # gauges overwrite
        snap = c.snapshot()
        assert snap == {"env_steps": 150, "compile_time_s": 4.5}
        snap["env_steps"] = 0  # snapshot is a copy
        assert c.get("env_steps") == 150

    def test_disabled_telemetry_counters_are_inert(self):
        """Engines inc counters unconditionally, so a disabled hub — in
        particular the process-wide NULL_TELEMETRY every engine defaults
        to — must swallow writes instead of aggregating cross-run state."""
        from estorch_tpu.obs import NULL_TELEMETRY

        t = Telemetry(enabled=False)
        t.counters.inc("recompiles")
        t.counters.gauge("compile_time_s", 9.9)
        assert t.counters.snapshot() == {}
        NULL_TELEMETRY.counters.inc("recompiles")
        assert NULL_TELEMETRY.counters.snapshot() == {}

    def test_thread_safety(self):
        import threading

        c = Counters()

        def worker():
            for _ in range(1000):
                c.inc("n")

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert c.get("n") == 8000


# ---------------------------------------------------------------------
# flight recorder + heartbeat
# ---------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_eviction_keeps_newest(self):
        r = FlightRecorder(capacity=4)
        for i in range(10):
            r.add("span", f"phase{i}", generation=i)
        assert len(r) == 4
        names = [e["name"] for e in r.events()]
        assert names == ["phase6", "phase7", "phase8", "phase9"]
        assert r.last()["name"] == "phase9"

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            FlightRecorder(capacity=0)

    def test_dump_jsonl(self, tmp_path):
        r = FlightRecorder(capacity=8)
        r.add("event", "compile", dur_s=1.5)
        path = str(tmp_path / "ring.jsonl")
        r.dump_jsonl(path)
        rows = [json.loads(ln) for ln in open(path)]
        assert rows[0]["name"] == "compile" and rows[0]["kind"] == "event"


class TestBenchStaysJaxFree:
    def test_bench_import_does_not_pull_jax(self):
        """bench.py's heartbeat helpers must load WITHOUT the estorch_tpu
        package init: importing jax in the bench driver would touch the
        possibly-wedged device runtime before the stage protocol's
        subprocess isolation can protect it (the round-1 lesson)."""
        repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
        r = subprocess.run(
            [sys.executable, "-c",
             "import sys; import bench; "
             "assert 'jax' not in sys.modules, 'bench imported jax'; "
             "assert 'estorch_tpu' not in sys.modules, "
             "'bench ran the package __init__'; "
             "assert callable(bench.describe_heartbeat)"],
            capture_output=True, text=True, cwd=repo, timeout=60,
        )
        assert r.returncode == 0, r.stderr


class TestHeartbeat:
    def test_beat_and_read(self, tmp_path):
        path = str(tmp_path / "hb.json")
        Heartbeat(path).beat("eval", 3, {"env_steps": 10})
        hb = read_heartbeat(path)
        assert hb["phase"] == "eval"
        assert hb["generation"] == 3
        assert hb["counters"] == {"env_steps": 10}
        assert 0 <= hb["age_s"] < STALE_AFTER_S

    def test_staleness_from_old_timestamp(self, tmp_path):
        path = str(tmp_path / "hb.json")
        with open(path, "w") as f:
            json.dump({"ts": time.time() - 10 * STALE_AFTER_S,
                       "pid": 1, "phase": "device", "generation": 7}, f)
        hb = read_heartbeat(path)
        assert hb["age_s"] > STALE_AFTER_S

    def test_missing_and_corrupt_return_none(self, tmp_path):
        assert read_heartbeat(str(tmp_path / "nope.json")) is None
        bad = tmp_path / "bad.json"
        bad.write_text("{half a rec")
        assert read_heartbeat(str(bad)) is None

    def test_telemetry_beats_on_phase_entry(self, tmp_path):
        """A wedge INSIDE a phase must leave that phase's name behind —
        the beat happens at entry, not exit."""
        path = str(tmp_path / "hb.json")
        t = Telemetry(heartbeat_path=path)
        try:
            with t.phase("eval"):
                mid = read_heartbeat(path)
                raise RuntimeError("wedge stand-in")
        except RuntimeError:
            pass
        assert mid["phase"] == "eval"


# ---------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------

class TestManifest:
    def test_round_trip(self, tmp_path):
        man = collect_manifest(config={"population_size": 64},
                               extra={"run_id": "r1"})
        path = str(tmp_path / "runs" / "manifest.json")
        write_manifest(path, man)
        back = load_manifest(path)
        assert back["config"] == {"population_size": 64}
        assert back["run_id"] == "r1"
        assert back["jax"] is not None
        assert back["python"] == sys.version.split()[0]
        # this repo IS a git checkout — the sha must resolve here
        assert isinstance(back["git_sha"], str) and len(back["git_sha"]) == 40

    def test_schema_mismatch_raises(self, tmp_path):
        path = str(tmp_path / "m.json")
        with open(path, "w") as f:
            json.dump({"schema": 999}, f)
        with pytest.raises(ValueError):
            load_manifest(path)

    def test_es_manifest_carries_device_topology(self, small_device_es):
        man = small_device_es.run_manifest()
        assert man["config"]["algorithm"] == "ES"
        assert man["config"]["backend"] == "device"
        assert len(man["devices"]) == 8  # the 8-virtual-device CPU mesh
        assert man["devices"][0]["platform"] == "cpu"


# ---------------------------------------------------------------------
# records from a REAL run + summarize
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_device_es():
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import CartPole

    return ES(
        MLPPolicy, JaxAgent, optax.adam,
        population_size=16, sigma=0.1, seed=0,
        policy_kwargs={"action_dim": 2, "hidden": (8,), "discrete": True},
        agent_kwargs={"env": CartPole(), "horizon": 25},
        optimizer_kwargs={"learning_rate": 0.05},
    )


class TestRealRecords:
    def test_device_records_pass_schema_and_carry_phases(
            self, small_device_es, tmp_path):
        """The contract the selfcheck golden pins must hold for records an
        actual ES produces — this is the test that catches a one-sided
        edit of _base_record vs RECORD_SCHEMA/GOLDEN_RECORD."""
        path = str(tmp_path / "run.jsonl")
        sink = JsonlSink(path)
        small_device_es.train(3, verbose=False, log_fn=sink)
        sink.close()
        recs = JsonlSink.read(path)
        assert len(recs) == 3
        for rec in recs:
            assert validate_record(rec) == [], validate_record(rec)
        # the fused device path's honest span taxonomy
        assert {"dispatch", "device", "host_sync"} <= set(recs[-1]["phases"])
        s = summarize(recs)
        assert s["generations"] == 3
        assert s["env_steps"] == sum(r["env_steps"] for r in recs)
        assert "device" in s["phase_share"]

    def test_golden_matches_schema(self):
        assert validate_record(GOLDEN_RECORD) == []

    def test_selfcheck_clean(self):
        assert selfcheck() == []


def _synthetic_records(n=8, stall_at=None):
    recs = []
    for g in range(n):
        wall = 2.0 if g != stall_at else 40.0
        recs.append(dict(
            GOLDEN_RECORD, generation=g, wall_time_s=wall,
            env_steps=1000, env_steps_per_sec=1000 / wall,
            phases={"sample": 0.05, "eval": 1.5, "update": 0.4,
                    "update/obsnorm_merge": 0.1},
        ))
    return recs


class TestSummarize:
    def test_phase_share_and_nesting(self):
        s = summarize(_synthetic_records())
        share = s["phase_share"]
        assert set(share) == {"sample", "eval", "update"}
        assert share["eval"]["share"] > share["update"]["share"]
        assert "obsnorm_merge" in share["update"]["children"]
        total = sum(row["share"] for row in share.values())
        assert abs(total - 1.0) < 1e-3  # shares are rounded to 4 decimals

    def test_stall_detection(self):
        s = summarize(_synthetic_records(stall_at=5))
        assert [st["generation"] for st in s["stalls"]] == [5]
        assert "took" in s["diagnosis"]

    def test_stale_heartbeat_in_diagnosis(self, tmp_path):
        hb = tmp_path / "heartbeat.json"
        hb.write_text(json.dumps(
            {"ts": time.time() - 10 * STALE_AFTER_S, "pid": 1,
             "phase": "device", "generation": 4}))
        s = summarize(_synthetic_records(), heartbeat_path=str(hb))
        assert "STALE" in s["diagnosis"]
        assert "phase=device" in s["diagnosis"]

    def test_empty_run(self):
        assert summarize([])["generations"] == 0


class TestCLI:
    def _run(self, args, cwd=None):
        return subprocess.run(
            [sys.executable, "-m", "estorch_tpu.obs", *args],
            capture_output=True, text=True, timeout=120, cwd=cwd,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )

    def test_selfcheck_exits_zero(self):
        r = self._run(["summarize", "--selfcheck"])
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout

    def test_summarize_human_output(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with open(path, "w") as f:
            for rec in _synthetic_records(stall_at=3):
                f.write(json.dumps(rec) + "\n")
        r = self._run(["summarize", str(path)])
        assert r.returncode == 0, r.stderr
        for needle in ("sample", "eval", "update", "env steps/s",
                       "diagnosis"):
            assert needle in r.stdout
        # auto-discovers a heartbeat.json beside the JSONL
        hb = tmp_path / "heartbeat.json"
        hb.write_text(json.dumps(
            {"ts": time.time() - 10 * STALE_AFTER_S, "pid": 1,
             "phase": "eval", "generation": 2}))
        r2 = self._run(["summarize", str(path)])
        assert "STALE" in r2.stdout

    def test_summarize_json_output(self, tmp_path):
        path = tmp_path / "run.jsonl"
        with open(path, "w") as f:
            for rec in _synthetic_records():
                f.write(json.dumps(rec) + "\n")
        r = self._run(["summarize", str(path), "--json"])
        s = json.loads(r.stdout)
        assert s["generations"] == 8
        assert s["phase_share"]["eval"]["seconds"] > 0

    def test_missing_file_is_error_not_traceback(self, tmp_path):
        r = self._run(["summarize", str(tmp_path / "nope.jsonl")])
        assert r.returncode == 1
        assert "cannot read" in r.stderr


# ---------------------------------------------------------------------
# ES integration: telemetry kwarg + heartbeat env protocol
# ---------------------------------------------------------------------

class TestESIntegration:
    def test_telemetry_disabled_records_empty_phases(self, monkeypatch):
        import torch

        from estorch_tpu import ES

        class MLP(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.net = torch.nn.Linear(4, 2)

            def forward(self, x):
                return self.net(x)

        class Agent:
            def rollout(self, policy):
                self.last_episode_steps = 1
                with torch.no_grad():
                    v = torch.nn.utils.parameters_to_vector(
                        policy.parameters())
                    return -float((v ** 2).sum())

        recs = []
        es = ES(MLP, Agent, torch.optim.Adam, population_size=8,
                sigma=0.05, table_size=1 << 12, telemetry=False)
        es.train(1, verbose=False, log_fn=recs.append)
        assert recs[0]["phases"] == {}

        # default-on: the host backend emits the canonical taxonomy and
        # the heartbeat env var is honored end to end
        hb_path = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"hb_{os.getpid()}.json")
        monkeypatch.setenv("ESTORCH_OBS_HEARTBEAT", hb_path)
        try:
            recs2 = []
            es2 = ES(MLP, Agent, torch.optim.Adam, population_size=8,
                     sigma=0.05, table_size=1 << 12)
            es2.train(2, verbose=False, log_fn=recs2.append)
            assert {"sample", "eval", "update"} <= set(recs2[0]["phases"])
            hb = read_heartbeat(hb_path)
            assert hb is not None and hb["generation"] == 2
            assert es2.obs.counters.get("env_steps") == sum(
                r["env_steps"] for r in recs2)
        finally:
            try:
                os.remove(hb_path)
            except OSError:
                pass
