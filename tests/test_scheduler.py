"""Barrier-free async generations (estorch_tpu/algo/scheduler.py).

Anchors: deterministic replay (a recorded arrival schedule driven twice
is bit-identical — and matches the live run that recorded it), the
straggler A/B (async beats the barrier loop under an identical chaos
plan while learning comparably), the zero-silent-drop accounting
contract, overlap-mode bit-equality with ``ES.train``, and the async
record/summary schema.
"""

import json
import os
import time

import numpy as np
import pytest
import torch

from estorch_tpu import ES
from estorch_tpu.resilience.chaos import (CHAOS_ENV, ChaosPlan, reset_cache,
                                          straggler_sleep_s)


class TinyPolicy(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.Tanh(), torch.nn.Linear(8, 2)
        )

    def forward(self, x):
        return self.net(x)


class QuadAgent:
    """Deterministic fitness (−‖θ‖²): same θ → same reward, the property
    every bit-exactness assertion below leans on."""

    def rollout(self, policy):
        with torch.no_grad():
            v = torch.nn.utils.parameters_to_vector(policy.parameters())
            r = -float((v**2).sum())
        self.last_episode_steps = 1
        return r


def make_host(**kw):
    base = dict(population_size=8, sigma=0.05, seed=0,
                optimizer_kwargs={"lr": 0.05}, table_size=1 << 12)
    base.update(kw)
    return ES(TinyPolicy, QuadAgent, torch.optim.Adam, **base)


@pytest.fixture
def chaos_env():
    """Set/clear ESTORCH_CHAOS around a test (cache reset both ways)."""
    def set_plan(plan: ChaosPlan):
        os.environ[CHAOS_ENV] = plan.to_json()
        reset_cache()

    yield set_plan
    os.environ.pop(CHAOS_ENV, None)
    reset_cache()


def params_bytes(es) -> bytes:
    return np.asarray(es.state.params_flat, np.float32).tobytes()


# ---------------------------------------------------------------------
# deterministic replay
# ---------------------------------------------------------------------

class TestReplay:
    def test_replay_bit_identical_and_matches_live(self, chaos_env):
        """THE determinism contract: a straggler-perturbed live run's
        recorded schedule, driven twice through replay, produces
        bit-identical parameters — and both equal the live run's."""
        chaos_env(ChaosPlan(events=[
            {"kind": "straggler", "gen": 1, "member": 2, "sleep_s": 0.15,
             "jitter_s": 0.1},
            {"kind": "straggler", "gen": 3, "member": 0, "sleep_s": 0.1},
        ]))
        live = make_host()
        live.train_async(5, n_proc=2, verbose=False)
        log = live.async_event_log.to_dict()
        # the log must JSON round-trip (it is the durable artifact)
        log = json.loads(json.dumps(log))

        r1 = make_host()
        r1.train_async(5, replay=log, verbose=False)
        r2 = make_host()
        r2.train_async(5, replay=log, verbose=False)
        assert params_bytes(r1) == params_bytes(r2)
        assert params_bytes(live) == params_bytes(r1)
        # replay reproduces the history-level facts too
        assert len(r1.history) == len(live.history) == 5
        for a, b in zip(live.history, r1.history):
            assert a["reward_mean"] == b["reward_mean"]
            assert a["async"]["folded"] == b["async"]["folded"]

    def test_process_mode_replay_matches_live(self):
        es = make_host(worker_mode="process")
        try:
            es.train_async(4, n_proc=2, verbose=False)
            log = es.async_event_log.to_dict()
        finally:
            es.engine.close()
        r = make_host()  # replay is pure math — thread-mode es suffices
        r.train_async(4, replay=log, verbose=False)
        assert params_bytes(es) == params_bytes(r)


# ---------------------------------------------------------------------
# the straggler win + learning quality
# ---------------------------------------------------------------------

class TestStragglerFold:
    def test_async_beats_barrier_and_learns_comparably(self, chaos_env):
        """Identical straggler plan (jittered sleeps, deterministic per
        event id), same seed: the fold scheduler must beat the barrier
        loop on wall time — the straggler occupies one worker, not the
        generation — while the final fitness stays in the synchronous
        run's band (the IW-clipped fold trains, not just survives)."""
        plan = ChaosPlan.generate(seed=0, n_generations=12,
                                  straggler_every=2,
                                  straggler_sleep_s=0.2,
                                  straggler_jitter_s=0.1,
                                  population_size=8)
        chaos_env(plan)
        t0 = time.perf_counter()
        es_sync = make_host(seed=1, optimizer_kwargs={"lr": 0.02})
        es_sync.train(12, n_proc=2, verbose=False)
        sync_s = time.perf_counter() - t0

        chaos_env(plan)  # fresh fire-once state for the async leg
        t0 = time.perf_counter()
        es_async = make_host(seed=1, optimizer_kwargs={"lr": 0.02})
        es_async.train_async(12, n_proc=2, verbose=False)
        async_s = time.perf_counter() - t0

        assert async_s < sync_s * 0.85, (async_s, sync_s)
        folded = sum(r["async"]["folded"] for r in es_async.history)
        assert folded > 0  # the stragglers were folded, not waited on

        first = es_sync.history[0]["reward_mean"]
        sync_final = es_sync.history[-1]["reward_mean"]
        async_final = es_async.history[-1]["reward_mean"]
        assert sync_final > first  # the baseline actually learned
        # within the clipped-IW band of the synchronous run: the folded
        # stale-sample estimator pays an update-efficiency tax (it is a
        # clipped self-normalized IS estimate), but must capture a solid
        # fraction of the sync improvement at EQUAL update count — while
        # taking measurably less wall time (asserted above).  Observed
        # fraction at this config is 0.65-0.9; 0.3 is the noise floor.
        assert async_final >= first + 0.3 * (sync_final - first), (
            first, sync_final, async_final)

    def test_overlap_efficiency_and_gauges(self, chaos_env):
        chaos_env(ChaosPlan(events=[
            {"kind": "straggler", "gen": 1, "member": 1, "sleep_s": 0.2}]))
        es = make_host()
        es.train_async(4, n_proc=2, verbose=False)
        snap = es.obs.counters.snapshot()
        assert snap.get("async_updates") == 4
        assert 0.0 <= snap.get("overlap_efficiency", -1) <= 1.0
        assert 0.0 <= snap.get("stale_reuse_ratio", -1) <= 1.0
        assert snap.get("results_folded", 0) > 0
        # async/dispatch + async/fold spans landed on the hub
        phases = {k for r in es.history for k in r["phases"]}
        assert "async/dispatch" in phases
        assert "async/fold" in phases


# ---------------------------------------------------------------------
# zero-silent-drop accounting
# ---------------------------------------------------------------------

class TestAccounting:
    def test_every_result_accounted(self, chaos_env):
        """max_stale=1 plus a long straggler forces discards: every
        dispatched member must end up consumed, discarded (counted), or
        lost — and the counters must agree with the event log."""
        chaos_env(ChaosPlan(events=[
            {"kind": "straggler", "gen": 0, "member": 3, "sleep_s": 0.6}]))
        es = make_host()
        es.train_async(6, n_proc=2, verbose=False, max_stale=1)
        log = es.async_event_log
        consumed = sum(len(u["consumed"]) for u in log.updates)
        dispatched = len(log.dispatches) * es.population_size
        assert dispatched == consumed + len(log.discarded) + len(log.lost)
        snap = es.obs.counters.snapshot()
        assert snap.get("stale_discarded", 0) == len(log.discarded)
        assert len(log.discarded) > 0  # the stale path actually fired
        assert sum(r["async"]["consumed"] for r in es.history) == consumed

    def test_rejected_update_protects_center_and_replays(self, chaos_env):
        """A chaos-poisoned update is rejected with the center intact
        and the SAME batch re-applies cleanly (fire-once semantics).
        The recovery contract in fold mode is replay fidelity: the torn
        run's recorded schedule, replayed (where the poison event is
        already spent), reproduces the live parameters bit-exactly."""
        plan = ChaosPlan(events=[{"kind": "nan_update", "gen": 2}])
        chaos_env(plan)
        es_chaos = make_host()
        es_chaos.train_async(5, verbose=False)
        assert es_chaos.obs.counters.get("generations_rejected") >= 1
        assert len(es_chaos.history) == 5  # every update landed anyway
        assert np.isfinite(np.asarray(es_chaos.state.params_flat)).all()

        r = make_host()
        r.train_async(5, replay=es_chaos.async_event_log.to_dict(),
                      verbose=False)
        assert params_bytes(es_chaos) == params_bytes(r)

    def test_nan_fitness_burst_rejected_then_recovers(self, chaos_env):
        chaos_env(ChaosPlan(events=[
            {"kind": "nan_fitness", "gen": 1, "member": "all"}]))
        es = make_host()
        es.train_async(4, verbose=False)
        assert len(es.history) == 4
        assert es.obs.counters.get("generations_rejected") >= 1
        assert np.isfinite(np.asarray(es.state.params_flat)).all()


# ---------------------------------------------------------------------
# overlap scheduler (device path)
# ---------------------------------------------------------------------

class TestOverlap:
    def _make_device(self):
        import optax

        from estorch_tpu import JaxAgent, MLPPolicy
        from estorch_tpu.envs import CartPole

        return ES(policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
                  population_size=16, sigma=0.1, seed=7,
                  policy_kwargs={"action_dim": 2, "hidden": (8,)},
                  agent_kwargs={"env": CartPole(), "horizon": 50},
                  optimizer_kwargs={"learning_rate": 1e-2},
                  table_size=1 << 15)

    def test_overlap_bit_identical_to_sync(self):
        es_sync = self._make_device()
        es_sync.train(4, verbose=False)
        es_ov = self._make_device()
        es_ov.train_async(4, verbose=False)  # auto → overlap on device
        assert (np.asarray(es_sync.state.params_flat).tobytes()
                == np.asarray(es_ov.state.params_flat).tobytes())
        assert ([r["reward_mean"] for r in es_sync.history]
                == [r["reward_mean"] for r in es_ov.history])
        # the speculative dispatch span landed (all but the last gen)
        assert any("async/dispatch" in r["phases"] for r in es_ov.history)

    def test_overlap_on_host_strategy(self):
        es_sync = make_host()
        es_sync.train(3, verbose=False)
        es_ov = make_host()
        es_ov.train_async(3, strategy="overlap", verbose=False)
        assert params_bytes(es_sync) == params_bytes(es_ov)

    def test_overlap_spans_do_not_interleave_across_threads(self):
        """The engine emits sample/eval/update from the background
        executor thread while the main thread emits dispatch/record:
        per-thread span stacks must keep the names clean (a shared
        stack produced 'async/dispatch/eval'-style corruption)."""
        es = make_host()
        es.train_async(4, strategy="overlap", n_proc=2, verbose=False)
        allowed = {"sample", "eval", "update", "record", "host_sync",
                   "async", "async/dispatch"}
        seen = {k for r in es.history for k in r["phases"]}
        assert seen <= allowed, seen - allowed


# ---------------------------------------------------------------------
# schema / wiring / validation
# ---------------------------------------------------------------------

class TestSchema:
    def test_async_records_validate_and_summarize(self):
        from estorch_tpu.obs.summarize import (format_summary, summarize,
                                               validate_record)

        es = make_host()
        es.train_async(3, verbose=False)
        for r in es.history:
            rec = json.loads(json.dumps(r))
            assert validate_record(rec) == [], validate_record(rec)
            a = r["async"]
            assert a["consumed"] == a["fresh"] + a["folded"]
        s = summarize([json.loads(json.dumps(r)) for r in es.history])
        assert s["async"]["updates"] == 3
        assert "async" in format_summary(s)

    def test_arg_validation(self):
        es = make_host()
        with pytest.raises(ValueError, match="strategy"):
            es.train_async(1, strategy="bogus")
        with pytest.raises(ValueError, match="replay"):
            es.train_async(1, strategy="overlap", replay={"updates": []})
        from estorch_tpu.algo.scheduler import GenerationScheduler

        with pytest.raises(ValueError, match="max_stale"):
            GenerationScheduler(es, max_stale=0)
        with pytest.raises(ValueError, match="iw_clip"):
            GenerationScheduler(es, iw_clip=0.5)

    def test_fold_requires_host_backend(self):
        import optax

        from estorch_tpu import JaxAgent, MLPPolicy
        from estorch_tpu.algo.scheduler import GenerationScheduler
        from estorch_tpu.envs import CartPole

        es = ES(policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
                population_size=4, sigma=0.1, seed=0,
                policy_kwargs={"action_dim": 2, "hidden": (4,)},
                agent_kwargs={"env": CartPole(), "horizon": 10},
                optimizer_kwargs={"learning_rate": 1e-2},
                table_size=1 << 14)
        with pytest.raises(ValueError, match="overlap"):
            GenerationScheduler(es)


# ---------------------------------------------------------------------
# chaos jitter (satellite)
# ---------------------------------------------------------------------

class TestChaosJitter:
    def test_jitter_deterministic_and_bounded(self):
        ev = {"kind": "straggler", "gen": 1, "member": 0, "sleep_s": 0.2,
              "jitter_s": 0.5, "id": 7}
        total = straggler_sleep_s(ev)
        assert total == straggler_sleep_s(dict(ev))  # same id → same stall
        assert 0.2 <= total < 0.7
        other = straggler_sleep_s(dict(ev, id=8))
        assert other != total  # different event → different spread
        assert straggler_sleep_s({"kind": "straggler", "gen": 1,
                                  "sleep_s": 0.3, "id": 1}) == 0.3

    def test_generate_schedules_stragglers(self):
        plan = ChaosPlan.generate(seed=3, n_generations=12,
                                  straggler_every=3,
                                  straggler_sleep_s=0.4,
                                  straggler_jitter_s=0.2,
                                  population_size=16,
                                  kill_every=6, n_workers=2)
        kinds = [e["kind"] for e in plan.events]
        assert kinds.count("straggler") == 4
        assert kinds.count("kill_worker") == 2
        for e in plan.events:
            if e["kind"] == "straggler":
                assert e["sleep_s"] == 0.4 and e["jitter_s"] == 0.2
                assert 0 <= e["member"] < 16
        # generate is deterministic in seed
        again = ChaosPlan.generate(seed=3, n_generations=12,
                                   straggler_every=3,
                                   straggler_sleep_s=0.4,
                                   straggler_jitter_s=0.2,
                                   population_size=16,
                                   kill_every=6, n_workers=2)
        assert plan.to_json() == again.to_json()
