"""Worker entrypoint for the REAL multi-process validation test.

Launched by tests/test_multiprocess.py as ``python _mp_worker.py <pid>
<nprocs> <port> <outdir>``.  Each worker is one JAX process with 4 local
CPU devices; ``jax.distributed`` connects them over Gloo/TCP — the same
runtime layering a TPU pod uses over DCN (SURVEY.md §2 'Distributed
communication backend'), so collectives here genuinely cross process
boundaries instead of staying inside one XLA client.

Must force the CPU platform BEFORE any device use: this image's
sitecustomize pins the axon TPU plugin, which can wedge indefinitely.
"""

import pathlib
import sys

# version-portable CPU pin: jax 0.4.x spells the device count as an
# XLA_FLAGS entry (the repo's shim), newer jax as jax_num_cpu_devices —
# force_cpu_backend handles both (the bare config.update bit-rotted on
# 0.4.37, which lacks the option entirely)
from estorch_tpu.utils.backend import force_cpu_backend

force_cpu_backend(4)


def main() -> None:
    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    outdir = pathlib.Path(sys.argv[4])
    algo = sys.argv[5] if len(sys.argv) > 5 else "es"

    import estorch_tpu.parallel.multihost as mh

    # Gloo CPU collectives: the default CPU client refuses any
    # cross-process psum ("Multiprocess computations aren't implemented")
    assert mh.initialize(f"localhost:{port}", num_processes=nprocs,
                         process_id=pid, cpu_collectives=True), \
        "distributed init did not happen"
    info = mh.process_info()
    assert info["process_count"] == nprocs
    assert info["global_devices"] == nprocs * 4

    import numpy as np
    import optax

    from estorch_tpu import ES, NSR_ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import CartPole

    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=16,
        sigma=0.1,
        policy_kwargs={"action_dim": 2, "hidden": (8,), "discrete": True},
        agent_kwargs={"env": CartPole(), "horizon": 64},
        optimizer_kwargs={"learning_rate": 1e-2},
        seed=7,
        mesh=mh.global_population_mesh(),
    )
    if algo == "nsr":
        # the novelty family keeps archive/meta-selection HOST-side on
        # every process, derived from replicated device results + the
        # seeded RNG — the claim under test is that all processes evolve
        # identical host state with zero communication
        es = NSR_ES(meta_population_size=2, k=3, **kw)
    else:
        es = ES(**kw)
    es.train(2, verbose=False)

    # leader_only must elect exactly one writer
    wrote = mh.leader_only(lambda: True)()

    extra = {}
    if algo == "nsr":
        extra = {
            "archive": np.asarray(es.archive.bcs, np.float64),
            "meta_sums": np.asarray(
                [np.asarray(s.params_flat, np.float64).sum()
                 for s in es.meta_states]
            ),
            "meta_indices": np.asarray(
                [r["meta_index"] for r in es.history], np.int64
            ),
        }
    np.savez(
        outdir / f"proc{pid}.npz",
        params=np.asarray(es.state.params_flat, np.float64),
        fitness=np.asarray(es.history[-1]["reward_mean"], np.float64),
        best=np.float64(es.best_reward),
        is_leader_writer=np.bool_(bool(wrote)),
        **extra,
    )
    print(f"proc {pid}: OK", flush=True)


if __name__ == "__main__":
    main()
