"""Param-sharded hyperscale engine tests (docs/sharding.md).

The acceptance contract of the sharded path:

- partition rules resolve every leaf of the demo policies' trees, error
  on unmatched leaves, and round-trip through config serialization;
- a same-seed sharded run (table noise) matches the replicated fused
  path allclose at f32 (reduction order is the only licensed delta);
- program-mode noise is mesh-shape invariant (GSPMD value semantics);
- a policy whose replicated footprint exceeds the per-device budget
  trains ≥3 generations on the sharded path with per-device peak bytes
  (compile-ledger memory_analysis) under the replicated bound;
- generations are donated (in-place) and the in-program anomaly
  rollback preserves the deterministic re-run contract.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from estorch_tpu.envs import CartPole, SyntheticEnv
from estorch_tpu.models import MLPPolicy, NatureCNN, RecurrentPolicy
from estorch_tpu.ops import make_noise_table, make_param_spec
from estorch_tpu.parallel import (
    DEFAULT_PARTITION_RULES,
    EngineConfig,
    ESEngine,
    MODEL_AXIS,
    ShardedESEngine,
    hyperscale_mesh,
    match_partition_rules,
    partition_rules_from_json,
    partition_rules_to_json,
    population_mesh,
)
from estorch_tpu.parallel.mesh import sharding_summary


def _mlp_setup():
    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {
            "dense_0": {"kernel": jax.random.normal(k1, (4, 16)) * 0.5,
                        "bias": jnp.zeros(16)},
            "head": {"kernel": jax.random.normal(k2, (16, 2)) * 0.5,
                     "bias": jnp.zeros(2)},
        }

    def apply(p, obs):
        h = jnp.tanh(obs @ p["dense_0"]["kernel"] + p["dense_0"]["bias"])
        return h @ p["head"]["kernel"] + p["head"]["bias"]

    params = init_params(jax.random.PRNGKey(0))
    flat, spec = make_param_spec(params)
    return flat, spec, apply


@pytest.fixture(scope="module")
def setup():
    flat, spec, apply = _mlp_setup()
    return dict(
        flat=flat, spec=spec, apply=apply, env=CartPole(),
        table=make_noise_table(1 << 18, seed=0), opt=optax.adam(3e-2),
        cfg=EngineConfig(population_size=32, sigma=0.1, horizon=50,
                         eval_chunk=8),
    )


def _sharded(s, mesh, noise_mode="program", cfg=None, table=None):
    return ShardedESEngine(
        s["env"], s["apply"], s["spec"],
        table if table is not None else (
            s["table"] if noise_mode == "table" else None),
        s["opt"], cfg or s["cfg"], mesh, noise_mode=noise_mode)


# ---------------------------------------------------------------------
# partition rules (satellite: matching, coverage error, serialization)
# ---------------------------------------------------------------------

class TestPartitionRules:
    def _demo_param_trees(self):
        """Shape trees of the bundled demo policies, via eval_shape (no
        compute)."""
        trees = {}
        mlp = MLPPolicy(action_dim=4, hidden=(64, 64))
        trees["mlp"] = jax.eval_shape(
            mlp.init, jax.random.PRNGKey(0), jnp.zeros((8,)))["params"]
        rec = RecurrentPolicy(action_dim=2, hidden=(32,), gru_size=16)
        trees["recurrent"] = jax.eval_shape(
            rec.init, jax.random.PRNGKey(0), jnp.zeros((8,)),
            rec.carry_init())["params"]
        cnn = NatureCNN(action_dim=6)
        trees["cnn"] = jax.eval_shape(
            cnn.init, jax.random.PRNGKey(0),
            jnp.zeros((84, 84, 4)))["params"]
        return trees

    def test_default_rules_cover_demo_policies(self, devices8):
        """Every leaf of every demo policy's tree resolves — the
        rule-coverage contract the engine builds on."""
        mesh = hyperscale_mesh(2, 4)
        for name, tree in self._demo_param_trees().items():
            sh = match_partition_rules(DEFAULT_PARTITION_RULES, tree, mesh)
            summary = sharding_summary(tree, sh)
            assert summary, name
            # at least the big kernels actually shard over model
            assert any(MODEL_AXIS in spec for spec in summary.values()), (
                name, summary)

    def test_unmatched_leaf_errors(self, devices8):
        mesh = hyperscale_mesh(2, 4)
        rules = ((r"kernel$", P(None, MODEL_AXIS)),)  # no catch-all
        tree = {"dense": {"kernel": jnp.zeros((8, 8)),
                          "bias": jnp.zeros((8,))}}
        with pytest.raises(ValueError, match="dense/bias"):
            match_partition_rules(rules, tree, mesh)

    def test_scalars_always_replicate(self, devices8):
        mesh = hyperscale_mesh(2, 4)
        # the sharding rule would be invalid for a scalar — the scalar
        # guard must win before any rule matches
        sh = match_partition_rules(
            ((r".*", P(MODEL_AXIS)),), {"count": jnp.float32(0.0)}, mesh)
        assert sh["count"].spec == P()

    def test_divisibility_fallback_replicates(self, devices8):
        """A dim the mesh axis cannot divide evenly falls back to
        replication for THAT dim (jax requires even shards; padding a
        parameter would change the optimization problem)."""
        mesh = hyperscale_mesh(2, 4)
        tree = {"head": {"kernel": jnp.zeros((16, 17)),
                         "bias": jnp.zeros((68,))}}
        sh = match_partition_rules(DEFAULT_PARTITION_RULES, tree, mesh)
        assert sh["head"]["kernel"].spec == P(None, None)  # 17 % 4 != 0
        assert sh["head"]["bias"].spec == P(MODEL_AXIS)  # 68 % 4 == 0

    def test_optimizer_state_resolves_through_same_rules(self, devices8):
        """adam's mu/nu embed param-shaped subtrees under the same leaf
        names; ONE rule set covers params and optimizer state."""
        mesh = hyperscale_mesh(2, 4)
        params = {"dense": {"kernel": jnp.zeros((8, 16)),
                            "bias": jnp.zeros((16,))}}
        opt_shape = jax.eval_shape(optax.adam(1e-2).init, params)
        sh = match_partition_rules(DEFAULT_PARTITION_RULES, opt_shape, mesh)
        leaves = jax.tree_util.tree_leaves(
            sh, is_leaf=lambda x: hasattr(x, "spec"))
        specs = {str(l.spec) for l in leaves}
        assert str(P(None, MODEL_AXIS)) in specs  # mu/nu kernels sharded
        assert str(P()) in specs  # count replicated

    def test_rules_round_trip_through_config_serialization(self):
        import json

        data = partition_rules_to_json(DEFAULT_PARTITION_RULES)
        # must be plain-JSON serializable (the manifest rides it)
        rebuilt = partition_rules_from_json(json.loads(json.dumps(data)))
        assert len(rebuilt) == len(DEFAULT_PARTITION_RULES)
        for (p0, s0), (p1, s1) in zip(DEFAULT_PARTITION_RULES, rebuilt):
            assert p0 == p1
            assert tuple(s0) == tuple(s1)


# ---------------------------------------------------------------------
# numerical contracts
# ---------------------------------------------------------------------

class TestShardedParity:
    def test_table_mode_matches_replicated_fused_path(self, setup, devices8):
        """THE numerical contract: same-seed sharded (table noise) vs the
        replicated fused engine, allclose at f32 over 3 generations.
        Reduction order is the licensed difference (model-sharded
        contractions psum in a different association), hence allclose,
        not bit-equality — docs/sharding.md."""
        eng = _sharded(setup, hyperscale_mesh(2, 4), noise_mode="table")
        rep = ESEngine(setup["env"], setup["apply"], setup["spec"],
                       setup["table"], setup["opt"], setup["cfg"],
                       population_mesh())
        s = eng.init_state(setup["flat"], jax.random.PRNGKey(7))
        sr = rep.init_state(setup["flat"], jax.random.PRNGKey(7))
        for gen in range(3):
            s, m = eng.generation_step(s)
            sr, mr = rep.generation_step(sr)
            np.testing.assert_allclose(
                np.asarray(m["fitness"]), np.asarray(mr["fitness"]),
                rtol=1e-5, atol=1e-5,
                err_msg=f"fitness diverged at gen {gen}")
            assert int(m["steps"]) == int(mr["steps"])
            np.testing.assert_allclose(
                np.asarray(s.params_flat), np.asarray(sr.params_flat),
                rtol=2e-4, atol=1e-5,
                err_msg=f"params diverged at gen {gen}")

    @pytest.mark.slow  # three engine builds; the (2,4) leg also runs
    # inside every non-slow test above, so tier-1 keeps 2-D coverage
    def test_program_mode_mesh_shape_invariance(self, setup, devices8):
        """GSPMD value semantics: the in-program noise keyed on
        (key, generation, row, leaf) gives the same run on ANY mesh
        shape, f32 reduction order aside."""
        results = []
        for shape in ((1, 8), (8, 1), (2, 4)):
            eng = _sharded(setup, hyperscale_mesh(*shape))
            s = eng.init_state(setup["flat"], jax.random.PRNGKey(3))
            for _ in range(2):
                s, m = eng.generation_step(s)
            results.append((shape, np.asarray(s.params_flat),
                            np.asarray(m["fitness"])))
        ref_shape, ref_p, ref_f = results[0]
        for shape, p, f in results[1:]:
            np.testing.assert_allclose(
                f, ref_f, rtol=1e-5, atol=1e-5,
                err_msg=f"fitness {shape} vs {ref_shape}")
            np.testing.assert_allclose(
                p, ref_p, rtol=5e-4, atol=1e-5,
                err_msg=f"params {shape} vs {ref_shape}")

    def test_member_reconstruction_matches_eval(self, setup, devices8):
        """member_params(i) (eager, off-mesh) must be exactly the θ the
        in-program path evaluated for member i — one keying contract."""
        from estorch_tpu.envs.rollout import make_rollout
        from estorch_tpu.parallel.engine import _gen_keys

        eng = _sharded(setup, hyperscale_mesh(2, 4))
        s0 = eng.init_state(setup["flat"], jax.random.PRNGKey(11))
        _, m = eng.generation_step(s0)
        # s0 was donated — rebuild an identical state for reconstruction
        s0 = eng.init_state(setup["flat"], jax.random.PRNGKey(11))
        theta5 = eng.member_params(s0, 5)
        # program mode runs under the PARTITIONABLE threefry impl
        # (docs/sharding.md): any host-side replay of its key derivations
        # and rollouts must enter the same scope or the streams differ
        with jax.threefry_partitionable(True):
            _, rkey = _gen_keys(s0)
            pair_keys = jax.random.split(rkey, 16)
            rollout = make_rollout(setup["env"], setup["apply"],
                                   setup["cfg"].horizon)
            res = rollout(setup["spec"].unravel(theta5), pair_keys[5 // 2])
            reward = float(res.total_reward)
        assert reward == pytest.approx(float(m["fitness"][5]), abs=1e-4)

    @pytest.mark.slow  # two engine builds; the replicated twin of this
    # regression (test_engine.py::test_indivisible_pairs_padded) and the
    # shared mesh.padded_count machinery stay in tier-1
    def test_arbitrary_population_padding(self, setup, devices8):
        """pop=10 over 8 pop-shards (the old divisibility error class):
        ghost-padded, matching the same run on a padding-free mesh."""
        cfg = EngineConfig(population_size=10, sigma=0.1, horizon=30)
        e_pad = _sharded(setup, hyperscale_mesh(8, 1), cfg=cfg)
        e_one = _sharded(setup, hyperscale_mesh(1, 8), cfg=cfg)
        sp = e_pad.init_state(setup["flat"], jax.random.PRNGKey(5))
        so = e_one.init_state(setup["flat"], jax.random.PRNGKey(5))
        for _ in range(2):
            sp, mp = e_pad.generation_step(sp)
            so, mo = e_one.generation_step(so)
        assert mp["fitness"].shape == (10,)
        np.testing.assert_allclose(np.asarray(mp["fitness"]),
                                   np.asarray(mo["fitness"]),
                                   rtol=1e-5, atol=1e-5)
        assert int(mp["steps"]) == int(mo["steps"])
        np.testing.assert_allclose(np.asarray(sp.params_flat),
                                   np.asarray(so.params_flat),
                                   rtol=5e-4, atol=1e-5)

    def test_low_rank_program_noise_trains(self, setup, devices8):
        """Factored in-program noise (A·Bᵀ/√r generated per row/leaf,
        update einsums the factors): trains finite, and the factored-leaf
        plan follows the (m+n)·r < m·n save-or-dense rule."""
        cfg = EngineConfig(population_size=16, sigma=0.1, horizon=30,
                           low_rank=2)
        eng = _sharded(setup, hyperscale_mesh(2, 4), cfg=cfg)
        # 4x16: 2·(4+16)=40 < 64 → factored;  16x2: 2·18=36 ≥ 32 → dense
        factored_shapes = {eng.leaf_shapes[i] for i in eng._factored}
        assert factored_shapes == {(4, 16)}
        s = eng.init_state(setup["flat"], jax.random.PRNGKey(1))
        for _ in range(2):
            s, m = eng.generation_step(s)
        assert bool(np.asarray(m["update_finite"]))
        assert int(m["n_valid"]) == 16


class TestDonationAndRollback:
    def test_generation_is_donated_in_place(self, setup, devices8):
        """donate_argnums actually took: the input state's buffers are
        deleted after the step (sample→eval→update ran in place)."""
        eng = _sharded(setup, hyperscale_mesh(2, 4))
        s0 = eng.init_state(setup["flat"], jax.random.PRNGKey(0))
        leaf0 = jax.tree_util.tree_leaves(s0.params)[0]
        s1, _ = eng.generation_step(s0)
        assert leaf0.is_deleted(), "input params survived — donation lost"
        jax.block_until_ready(jax.tree_util.tree_leaves(s1.params))

    def test_in_program_rollback_on_collapsed_population(self, devices8):
        """All-NaN fitness → n_valid 0 → the program emits the INPUT
        state unchanged (the donated path's in-program twin of ES.train's
        host-side restore): same generation, same params — so the
        deterministic re-run contract holds."""
        import dataclasses

        class NaNEnv:
            obs_dim = 4
            action_dim = 2
            discrete = False
            bc_dim = 1

            def reset(self, key):
                s = jax.random.normal(key, (4,))
                return s, s

            def step(self, state, action):
                return state, state, jnp.float32(jnp.nan), jnp.bool_(False)

            def behavior(self, state, obs):
                return state[:1]

        flat, spec, apply = _mlp_setup()
        cfg = EngineConfig(population_size=8, sigma=0.1, horizon=5)
        eng = ShardedESEngine(NaNEnv(), apply, spec, None, optax.adam(1e-2),
                              cfg, hyperscale_mesh(2, 4))
        s0 = eng.init_state(flat, jax.random.PRNGKey(0))
        before = np.asarray(s0.params_flat)  # host copy BEFORE donation
        s1, m = eng.generation_step(s0)
        assert int(m["n_valid"]) == 0
        assert int(np.asarray(s1.generation)) == 0  # NOT incremented
        np.testing.assert_array_equal(np.asarray(s1.params_flat), before)


# ---------------------------------------------------------------------
# THE memory acceptance: replicated footprint > per-device budget,
# sharded trains under it
# ---------------------------------------------------------------------

class TestBigPolicyMemory:
    def test_big_policy_trains_under_replicated_bound(self, devices8):
        """A ~900k-param policy (replicated state: params + adam moments
        on EVERY device) trains ≥3 generations on the sharded path with
        per-device peak bytes — XLA's memory_analysis of the compiled
        donated program, via the compile ledger — UNDER the replicated
        program's per-device peak (the 'replicated bound')."""
        from estorch_tpu.obs.profile.costmodel import compiled_cost_facts

        env = SyntheticEnv(obs_dim=376, action_dim=17)
        module = MLPPolicy(action_dim=17, hidden=(768, 768),
                           discrete=False, action_scale=1.0)
        variables = module.init(jax.random.PRNGKey(0),
                                jnp.zeros((376,), jnp.float32))
        flat, spec = make_param_spec(variables["params"])

        def apply(p, obs):
            return module.apply({"params": p}, obs)

        opt = optax.adam(1e-2)
        cfg = EngineConfig(population_size=16, sigma=0.05, horizon=20,
                           eval_chunk=8, grad_chunk=8)
        eng = ShardedESEngine(env, apply, spec, None, opt, cfg,
                              hyperscale_mesh(1, 8))
        s = eng.init_state(flat, jax.random.PRNGKey(1))
        eng.compile(s)
        shard_facts = eng.memory_facts()
        for _ in range(3):
            s, m = eng.generation_step(s)
        assert bool(np.asarray(m["update_finite"]))
        assert int(np.asarray(s.generation)) == 3

        table = make_noise_table(1 << 21, seed=0)
        rep = ESEngine(env, apply, spec, table, opt, cfg, population_mesh())
        sr = rep.init_state(flat, jax.random.PRNGKey(1))
        rep_facts = compiled_cost_facts(
            rep._generation_step.lower(sr).compile())
        assert shard_facts.get("peak_bytes"), shard_facts
        assert rep_facts.get("peak_bytes"), rep_facts
        # the replicated program's per-device peak EXCEEDS the per-device
        # budget this policy's sharded run fits in
        assert shard_facts["peak_bytes"] < rep_facts["peak_bytes"], (
            shard_facts, rep_facts)
        # and the replicated STATE alone (params + adam moments, what
        # every device must hold replicated) exceeds the sharded
        # program's resident state share: dim·12 bytes vs dim·12/8 + pad
        replicated_state_bytes = 3 * spec.dim * 4
        assert replicated_state_bytes > 10_000_000  # genuinely "big"


# ---------------------------------------------------------------------
# ES-level wiring + the sharded bench row
# ---------------------------------------------------------------------

class TestShardedES:
    @pytest.fixture(scope="class")
    def es_cls_common(self):
        import optax as _optax

        from estorch_tpu import ES, JaxAgent
        from estorch_tpu.envs import Pendulum

        return dict(
            policy=MLPPolicy, agent=JaxAgent, optimizer=_optax.adam,
            population_size=16, sigma=0.05,
            policy_kwargs={"action_dim": 1, "hidden": (32, 32),
                           "discrete": False, "action_scale": 2.0},
            agent_kwargs={"env": Pendulum(), "horizon": 60},
            optimizer_kwargs={"learning_rate": 1e-2}, seed=3,
            telemetry=True,
        )

    def test_es_sharded_end_to_end(self, es_cls_common, devices8):
        from estorch_tpu import ES

        es = ES(shard_params=True, **es_cls_common)
        assert es.table is None  # program mode allocates NO noise table
        es.train(2, verbose=False)
        assert len(es.history) == 2
        r = es.history[-1]
        assert r["sigma"] == pytest.approx(0.05)
        assert r["env_steps"] == 16 * 60
        # best-member snapshot via the in-program best_theta protocol
        assert es._best_flat is not None
        assert es._best_flat.shape == (es._spec.dim,)
        # inspection APIs work off the gathered flat
        out = es.predict(np.zeros(3, np.float32))
        assert np.asarray(out).shape == (1,)
        ev = es.evaluate_policy(n_episodes=2)
        assert np.isfinite(ev["mean"])
        # manifest records the sharded config incl. serialized rules
        cfg = es.run_manifest()["config"]
        assert cfg["shard_params"] is True
        assert cfg["noise_mode"] == "program"
        assert cfg["mesh_axes"] == {"pop": 1, "model": 8}
        rebuilt = partition_rules_from_json(cfg["partition_rules"])
        assert len(rebuilt) == len(DEFAULT_PARTITION_RULES)
        # shard-aware cost model rides telemetry
        cm = es.obs.cost_model
        assert cm["noise"] == "program"
        assert cm["sharding"]["model_shards"] == 8
        assert cm["sharding"]["per_device_flops_per_env_step"] == (
            cm["flops_per_env_step"] / 8)

    @pytest.mark.slow  # two full ES builds; the non-slow e2e test above
    # already exercises the best_theta snapshot path itself
    def test_es_sharded_best_theta_matches_member_params(
            self, es_cls_common, devices8):
        """The in-program best-θ (donated path) must equal the replicated
        engine's host-side member_params reconstruction at the same
        seed/table — the two best-tracking protocols cannot drift."""
        from estorch_tpu import ES

        es_t = ES(shard_params=True, noise_mode="table", **es_cls_common)
        es_r = ES(**es_cls_common)
        es_t.train(2, verbose=False)
        es_r.train(2, verbose=False)
        assert es_t._best_flat is not None and es_r._best_flat is not None
        np.testing.assert_allclose(es_t._best_flat, es_r._best_flat,
                                   rtol=2e-4, atol=1e-5)

    def test_option_validation(self, es_cls_common, devices8):
        from estorch_tpu import ES

        with pytest.raises(ValueError, match="shard_params=True"):
            ES(**{**es_cls_common, "model_shards": 4})
        with pytest.raises(ValueError, match="float32"):
            ES(shard_params=True,
               **{**es_cls_common, "compute_dtype": "bfloat16"})
        with pytest.raises(ValueError, match="obs_norm"):
            ES(shard_params=True, **{**es_cls_common, "obs_norm": True})

    def test_bench_sharded_row_reports_mfu(self, devices8):
        """The sharded bench row: non-null mfu derived from the
        shard-aware cost model (acceptance criterion 3)."""
        import os
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        try:
            import bench
        finally:
            sys.path.pop(0)
        row = bench.measure_one(
            {"env": "synthetic", "hidden": [16, 16], "population": 16,
             "horizon": 20, "gens": 1, "eval_chunk": 8, "shard": True,
             "telemetry": True})
        assert row["mfu"] is not None
        assert row["mfu_basis"] == "cpu_calibrated"
        assert row["dtype"] == "float32"
        shard = row["shard"]
        assert shard["mfu_from_cost_model"] is True
        assert shard["noise_mode"] == "program"
        assert shard["per_device_peak_bytes"]


class TestResilienceWithDonation:
    def test_run_resilient_rollback_survives_donated_state(self, devices8):
        """run_resilient's snapshot must deep-copy a SHARDED state: the
        donated generation deletes the live buffers, so a by-reference
        snapshot restores corpses ('buffer has been deleted or donated').
        A one-shot failure injected mid-train must roll back, re-run, and
        end bit-identical to the same run without the fault."""
        import optax as _optax

        from estorch_tpu import ES, JaxAgent
        from estorch_tpu.envs import Pendulum
        from estorch_tpu.resilience import run_resilient

        def build():
            return ES(
                policy=MLPPolicy, agent=JaxAgent, optimizer=_optax.adam,
                population_size=8, sigma=0.05,
                policy_kwargs={"action_dim": 1, "hidden": (16,),
                               "discrete": False, "action_scale": 2.0},
                agent_kwargs={"env": Pendulum(), "horizon": 30},
                optimizer_kwargs={"learning_rate": 1e-2}, seed=2,
                shard_params=True)

        es = build()
        fired = []

        def boom_once(record):
            if record["generation"] == 1 and not fired:
                fired.append(True)
                raise RuntimeError("injected post-generation fault")

        run_resilient(es, 3, log_fn=boom_once, verbose=False)
        assert fired, "fault never injected"
        assert es.generation == 3
        clean = build()
        clean.train(3, verbose=False)
        np.testing.assert_array_equal(
            np.asarray(es.state.params_flat),
            np.asarray(clean.state.params_flat))
