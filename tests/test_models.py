"""Policy model tests: NatureCNN, MLP heads, bf16 compute path."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from estorch_tpu import ES, JaxAgent, MLPPolicy, NatureCNN
from estorch_tpu.envs import CartPole
from estorch_tpu.ops import count_params


class TestNatureCNN:
    def test_shapes_single_and_batched(self):
        cnn = NatureCNN(action_dim=18, use_vbn=False)
        obs = jnp.zeros((84, 84, 4), jnp.uint8)
        vs = cnn.init(jax.random.PRNGKey(0), obs)
        out = cnn.apply(vs, obs)
        assert out.shape == (18,)
        batch = jnp.zeros((7, 84, 84, 4), jnp.uint8)
        out_b = cnn.apply(vs, batch)
        assert out_b.shape == (7, 18)

    def test_param_count_matches_nature_dqn(self):
        """Conv trunk + 512 dense ≈ the canonical ~1.69M params for 18 actions."""
        cnn = NatureCNN(action_dim=18, use_vbn=False)
        vs = cnn.init(jax.random.PRNGKey(0), jnp.zeros((84, 84, 4)))
        n = count_params(vs["params"])
        assert 1_600_000 < n < 1_800_000, n

    def test_vbn_collection_separated(self):
        cnn = NatureCNN(action_dim=4, use_vbn=True)
        vs = cnn.init(jax.random.PRNGKey(0), jnp.zeros((84, 84, 4)))
        assert "vbn_stats" in vs
        # stats never live in params (ES must not perturb them)
        flat_names = [
            "/".join(str(p) for p in path)
            for path, _ in jax.tree_util.tree_leaves_with_path(vs["params"])
        ]
        assert not any("mean" in n or "var" in n for n in flat_names)

    def test_uint8_normalization(self):
        """255-valued input must normalize to ~1.0 before the convs."""
        cnn = NatureCNN(action_dim=2, use_vbn=False)
        full = jnp.full((84, 84, 4), 255, jnp.uint8)
        vs = cnn.init(jax.random.PRNGKey(0), full)
        out_full = cnn.apply(vs, full)
        out_zero = cnn.apply(vs, jnp.zeros((84, 84, 4), jnp.uint8))
        assert not np.allclose(np.asarray(out_full), np.asarray(out_zero))


class TestBf16ComputePath:
    def _es(self, dtype):
        return ES(
            MLPPolicy, JaxAgent, optax.adam,
            population_size=32, sigma=0.1, seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (16,)},
            agent_kwargs={"env": CartPole(), "horizon": 100},
            optimizer_kwargs={"learning_rate": 3e-2},
            table_size=1 << 16, compute_dtype=dtype,
        )

    def test_bf16_learns_cartpole(self):
        es = self._es("bfloat16")
        es.train(8, verbose=False)
        first = es.history[0]["reward_mean"]
        last = es.history[-1]["reward_mean"]
        assert last > first + 10, (first, last)

    def test_params_stay_float32(self):
        es = self._es("bfloat16")
        es.train(1, verbose=False)
        assert es.state.params_flat.dtype == jnp.float32
        assert es.table.data.dtype == jnp.float32

    def test_bf16_close_to_f32_first_generation(self):
        """Same seed: bf16 fitness should agree with f32 for most members in
        generation 0 (CartPole actions are argmax — only near-ties flip)."""
        a = self._es("float32")
        b = self._es("bfloat16")
        ra = a.engine.evaluate(a.state)
        rb = b.engine.evaluate(b.state)
        agree = np.mean(np.asarray(ra.fitness) == np.asarray(rb.fitness))
        assert agree > 0.5, agree

    def test_invalid_dtype_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="compute_dtype"):
            self._es("float16")

    @staticmethod
    def _loop_invariant_bf16_casts(scan_eqn):
        """convert→bf16 eqns in a scan body whose operand derives ONLY from
        scan constants (loop-invariant): each one is a cast XLA must either
        hoist (hope) or redo every step (HBM traffic).  Param casts belong
        OUTSIDE the episode scan; per-step obs casts (carry-derived) are fine."""
        body = scan_eqn.params["jaxpr"].jaxpr
        const_derived = set(body.invars[: scan_eqn.params["num_consts"]])
        bad = []
        for eqn in body.eqns:
            operands_const = all(
                hasattr(v, "val") or v in const_derived  # Literal or const-derived
                for v in eqn.invars
            )
            if operands_const:
                const_derived.update(eqn.outvars)
                if (
                    eqn.primitive.name == "convert_element_type"
                    and eqn.outvars[0].aval.dtype == jnp.bfloat16
                ):
                    bad.append(eqn)
        return bad

    def _episode_scans(self, fn, args, horizon):
        """All scan eqns of length==horizon anywhere in fn's jaxpr."""
        found = []

        def subjaxprs(v):
            if hasattr(v, "jaxpr"):  # ClosedJaxpr
                yield v.jaxpr
            elif hasattr(v, "eqns"):  # raw Jaxpr
                yield v
            elif isinstance(v, (tuple, list)):
                for x in v:
                    yield from subjaxprs(x)

        def walk(jxp):
            for eqn in jxp.eqns:
                if eqn.primitive.name == "scan" and eqn.params.get("length") == horizon:
                    found.append(eqn)
                for v in eqn.params.values():
                    for sub in subjaxprs(v):
                        walk(sub)

        walk(jax.make_jaxpr(fn)(*args).jaxpr)
        return found

    def test_no_per_step_param_cast_in_rollout_scan(self):
        """Round-1 VERDICT weak #6: the bf16 cast of member params must
        happen once per member, not inside the per-step episode scan."""
        es = self._es("bfloat16")
        scans = self._episode_scans(es.engine._generation_step, (es.state,), 100)
        assert scans, "episode scan (length=100) not found in the program"
        for s in scans:
            bad = self._loop_invariant_bf16_casts(s)
            assert not bad, (
                "loop-invariant bf16 casts inside the episode scan: "
                + ", ".join(str(e.outvars[0].aval) for e in bad)
            )

    def test_no_per_step_param_cast_decomposed(self):
        es = ES(
            MLPPolicy, JaxAgent, optax.adam,
            population_size=32, sigma=0.1, seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (16,)},
            agent_kwargs={"env": CartPole(), "horizon": 100},
            optimizer_kwargs={"learning_rate": 3e-2},
            table_size=1 << 16, compute_dtype="bfloat16", decomposed=True,
        )
        scans = self._episode_scans(es.engine._generation_step, (es.state,), 100)
        assert scans, "episode scan (length=100) not found in the program"
        for s in scans:
            assert not self._loop_invariant_bf16_casts(s)