"""The public API surface is a parity CONTRACT (SURVEY.md Appendix A) —
this test freezes it so refactors can't silently drop exports."""

import inspect


def test_reference_parity_imports():
    """The reference's import line, estorch_tpu edition."""
    from estorch_tpu import (  # noqa: F401
        ES,
        NS_ES,
        NSR_ES,
        NSRA_ES,
        VirtualBatchNorm,
    )


def test_extended_surface_imports():
    from estorch_tpu import (  # noqa: F401
        JaxAgent,
        MLPPolicy,
        NatureCNN,
        NoveltyArchive,
        PooledAgent,
    )
    from estorch_tpu.models import TorchVirtualBatchNorm  # noqa: F401
    from estorch_tpu.envs import (  # noqa: F401
        Acrobot,
        CartPole,
        MountainCar,
        MountainCarContinuous,
        Pendulum,
    )
    from estorch_tpu.parallel import (  # noqa: F401
        global_population_mesh,
        initialize_distributed,
        population_mesh,
    )
    from estorch_tpu.utils import (  # noqa: F401
        JsonlWriter,
        PeriodicCheckpointer,
        restore_checkpoint,
        save_checkpoint,
    )
    from estorch_tpu.obs import (  # noqa: F401
        FlightRecorder,
        Heartbeat,
        JsonlSink,
        MultiSink,
        Telemetry,
        read_heartbeat,
        summarize,
        write_manifest,
    )
    from estorch_tpu.resilience import (  # noqa: F401
        CHAOS_ENV,
        ChaosError,
        ChaosPlan,
        Supervisor,
        run_resilient,
    )
    from estorch_tpu.serve import (  # noqa: F401
        BatcherSaturated,
        Bundle,
        BundleError,
        CircuitBreaker,
        DynamicBatcher,
        Fleet,
        FleetError,
        PolicyServer,
        Router,
        ServeClient,
        export_bundle,
        load_bundle,
        load_fleet_config,
        validate_bundle,
    )
    from estorch_tpu.utils import latest_checkpoint  # noqa: F401


def test_es_constructor_signature_matches_reference():
    """Appendix A ctor args must all exist with these names."""
    from estorch_tpu import ES

    params = inspect.signature(ES.__init__).parameters
    for name in ("policy", "agent", "optimizer", "population_size", "sigma",
                 "device", "policy_kwargs", "agent_kwargs", "optimizer_kwargs"):
        assert name in params, f"reference ctor arg {name!r} missing"


def test_train_signature_matches_reference():
    from estorch_tpu import ES

    params = inspect.signature(ES.train).parameters
    assert "n_steps" in params
    assert "n_proc" in params


def test_novelty_ctor_extras_match_reference():
    """Appendix A: k, meta-population size; NSRA: weight, delta, patience."""
    from estorch_tpu import NS_ES, NSRA_ES

    ns = inspect.signature(NS_ES.__init__).parameters
    assert "k" in ns and "meta_population_size" in ns
    nsra = inspect.signature(NSRA_ES.__init__).parameters
    for name in ("weight", "weight_delta", "stagnation_patience"):
        assert name in nsra


def test_instance_attributes_exposed():
    """es.policy / es.best_policy / es.best_reward exist as the reference's."""
    from estorch_tpu import ES

    assert isinstance(ES.policy, property)
    assert isinstance(ES.best_policy, property)
