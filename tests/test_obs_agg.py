"""Fleet-scope observability (estorch_tpu/obs/agg/, docs/observability.md
"Fleet aggregation").

Anchors: the time-series store's atomic segment/retention/reset
contracts, the declarative rules engine's threshold/absence/multi-window
burn-rate state machine, the collector's dead/slow/garbage-target
containment, and THE acceptance demo — a 3-target fleet (two serve
servers, one chaos-killed mid-run, plus a supervised-run sidecar) under
loadgen while the collector scrapes throughout: the absence rule fires
``estorch_up``→down for the killed replica and resolves on restart, an
injected latency spike breaches the p99 burn-rate rule naming the
target and the endpoint metric, stored-history quantiles match the
server's own histogram within the documented ladder bound, and ``obs
dash --once`` renders all three targets with active alerts, jax-free as
a plain file.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from estorch_tpu.obs.agg.collector import (Collector, Target, load_targets,
                                           samples_from_exposition,
                                           scrape_run_dir, validate_targets)
from estorch_tpu.obs.agg.rules import (RulesEngine, append_ledger,
                                       load_rules, read_ledger,
                                       validate_rules)
from estorch_tpu.obs.agg.store import SeriesStore
from estorch_tpu.obs.export.prometheus import (parse_exposition,
                                               render_exposition)
from estorch_tpu.obs.hist import Histogram
from estorch_tpu.obs.recorder import Heartbeat

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


# =====================================================================
# time-series store
# =====================================================================

class TestSeriesStore:
    def _sample(self, name, target, value):
        return {"name": name, "labels": {"target": target}, "value": value}

    def test_append_commit_is_atomic_and_readable(self, tmp_path):
        s = SeriesStore(str(tmp_path / "store"))
        now = 1000.0
        s.append([self._sample("estorch_up", "a", 1)], ts=now)
        s.append([self._sample("estorch_up", "a", 0)], ts=now + 1)
        # no .tmp staging files survive a commit
        files = os.listdir(str(tmp_path / "store"))
        assert files and not [f for f in files if f.endswith(".tmp")]
        got = s.range("estorch_up", {"target": "a"}, window_s=60, now=now + 1)
        assert [(ts, v) for ts, _l, v in got] == [(1000.0, 1.0),
                                                 (1001.0, 0.0)]

    def test_segment_roll_and_retention(self, tmp_path):
        s = SeriesStore(str(tmp_path / "store"), max_segments=3,
                        segment_max_samples=2)
        for i in range(12):
            s.append([self._sample("m", "a", i)], ts=1000.0 + i)
        segs = s.segments()
        assert len(segs) <= 3
        # newest samples survive retention, oldest are pruned
        got = [v for _ts, _l, v in s.range("m", None, window_s=1e6,
                                           now=1012.0)]
        assert got[-1] == 11.0 and 0.0 not in got

    def test_label_subset_match_and_values(self, tmp_path):
        s = SeriesStore(str(tmp_path / "store"))
        s.append([self._sample("estorch_up", "a", 1),
                  self._sample("estorch_up", "b", 0)], ts=1000.0)
        assert len(s.range("estorch_up", None, 60, now=1000.0)) == 2
        assert [v for _t, _l, v in
                s.range("estorch_up", {"target": "b"}, 60, now=1000.0)] \
            == [0.0]
        assert s.label_values("estorch_up", "target", 60,
                              now=1000.0) == ["a", "b"]

    def test_counter_increase_detects_reset(self, tmp_path):
        """A restarted process zeroes its counters; the windowed increase
        must count the post-reset growth, not a bogus negative."""
        s = SeriesStore(str(tmp_path / "store"))
        for i, v in enumerate([100, 150, 170, 5, 25]):  # reset at 5
            s.append([self._sample("estorch_requests_total", "a", v)],
                     ts=1000.0 + i)
        inc = s.increase("estorch_requests_total", {"target": "a"},
                         window_s=60, now=1004.0)
        assert inc == (50 + 20) + 5 + 20

    def test_hist_window_merges_across_restart(self, tmp_path):
        """Cumulative snapshots: latest rules the window, except across
        a count DROP (restart) where the pre-restart snapshot folds in —
        the sidecar composition contract lifted to stored history."""
        s = SeriesStore(str(tmp_path / "store"))
        h1 = Histogram()
        for _ in range(300):
            h1.observe(0.010)
        s.append([{"name": "estorch_lat", "labels": {"target": "a"},
                   "hist": h1.to_dict()}], ts=1000.0)
        h2 = Histogram()  # the restarted process's fresh histogram
        for _ in range(100):
            h2.observe(0.100)
        s.append([{"name": "estorch_lat", "labels": {"target": "a"},
                   "hist": h2.to_dict()}], ts=1001.0)
        merged = s.hist_window("estorch_lat", {"target": "a"},
                               window_s=60, now=1001.0)
        assert merged is not None and merged.count == 400
        direct = Histogram()
        for _ in range(300):
            direct.observe(0.010)
        for _ in range(100):
            direct.observe(0.100)
        assert merged.quantile(0.99) == direct.quantile(0.99)

    def test_hist_window_is_a_window_not_lifetime(self, tmp_path):
        """Snapshots are cumulative, so a window quantile must subtract
        the pre-window anchor: a long-gone spike must NOT sit in every
        short window forever (the burn-rate resolution contract)."""
        s = SeriesStore(str(tmp_path / "store"))
        h = Histogram()
        for _ in range(300):
            h.observe(0.500)  # the old spike
        s.append([{"name": "estorch_lat", "labels": {"target": "a"},
                   "hist": h.to_dict()}], ts=1000.0)
        for _ in range(100):
            h.observe(0.010)  # recovery traffic
        s.append([{"name": "estorch_lat", "labels": {"target": "a"},
                   "hist": h.to_dict()}], ts=1100.0)
        # short window sees ONLY the post-anchor delta: fast traffic
        short = s.hist_window("estorch_lat", {"target": "a"},
                              window_s=50, now=1110.0)
        assert short is not None and short.count == 100
        assert short.quantile(0.99) < 0.05
        # long window (no anchor) still carries the whole history
        long_ = s.hist_window("estorch_lat", {"target": "a"},
                              window_s=200, now=1110.0)
        assert long_.count == 400 and long_.quantile(0.99) >= 0.4
        # sum subtracts too (within float noise)
        assert abs(short.sum - 100 * 0.010) < 1e-6

    def test_reader_skips_garbage_lines(self, tmp_path):
        s = SeriesStore(str(tmp_path / "store"))
        s.append([self._sample("m", "a", 1)], ts=1000.0)
        seg = s.segments()[0]
        with open(seg, "a") as f:
            f.write("{torn json\n")
        assert [v for _t, _l, v in s.range("m", None, 60, now=1000.0)] \
            == [1.0]


# =====================================================================
# rules engine
# =====================================================================

def _mk_store(tmp_path, *batches):
    s = SeriesStore(str(tmp_path / "store"))
    for ts, samples in batches:
        s.append(samples, ts=ts)
    return s


class TestRules:
    def test_validate_rejects_junk(self):
        assert validate_rules({"schema": 1, "rules": [{"kind": "nope"}]})
        assert validate_rules({"schema": 2, "rules": []})
        assert validate_rules({"schema": 1, "rules": [
            {"name": "x", "kind": "burn_rate", "metric": "m",
             "slo_s": 0, "windows": []}]})
        assert not validate_rules({"schema": 1, "rules": [
            {"name": "ok", "kind": "threshold", "metric": "m", "op": ">",
             "value": 1}]})

    def test_threshold_for_s_delays_firing(self, tmp_path):
        store = _mk_store(tmp_path)
        eng = RulesEngine([{"name": "deep", "kind": "threshold",
                            "metric": "estorch_queue_depth", "op": ">",
                            "value": 10, "for_s": 5, "window_s": 60}])
        up = {"name": "estorch_queue_depth", "labels": {"target": "a"},
              "value": 50}
        store.append([up], ts=1000.0)
        assert eng.evaluate(store, ["a"], 1000.0) == []  # pending
        store.append([up], ts=1004.0)
        assert eng.evaluate(store, ["a"], 1004.0) == []  # still pending
        store.append([up], ts=1006.0)
        fired = eng.evaluate(store, ["a"], 1006.0)
        assert [t["event"] for t in fired] == ["firing"]
        assert "estorch_queue_depth" in fired[0]["detail"]
        assert "'a'" in fired[0]["detail"]

    def test_absence_fires_on_missing_and_zero_and_resolves(self, tmp_path):
        store = _mk_store(tmp_path)
        eng = RulesEngine([{"name": "down", "kind": "absence",
                            "metric": "estorch_up", "for_s": 0,
                            "window_s": 30}])
        # no sample at all -> fires
        t1 = eng.evaluate(store, ["a"], 1000.0)
        assert [x["event"] for x in t1] == ["firing"]
        # up=1 lands -> resolves
        store.append([{"name": "estorch_up", "labels": {"target": "a"},
                       "value": 1}], ts=1001.0)
        t2 = eng.evaluate(store, ["a"], 1001.0)
        assert [x["event"] for x in t2] == ["resolved"]
        # up=0 (answers but reports down) -> fires again
        store.append([{"name": "estorch_up", "labels": {"target": "a"},
                       "value": 0}], ts=1002.0)
        t3 = eng.evaluate(store, ["a"], 1002.0)
        assert [x["event"] for x in t3] == ["firing"]
        assert eng.active()[0]["target"] == "a"

    def test_burn_rate_needs_every_window(self, tmp_path):
        """Multi-window semantics: a long-window breach whose SHORT
        window has recovered must NOT fire — that is the whole point of
        the second window (no paging after recovery)."""
        store = _mk_store(tmp_path)
        slow, fast = Histogram(), Histogram()
        for _ in range(300):
            slow.observe(0.500)
        store.append([{"name": "estorch_req", "labels": {"target": "a"},
                       "hist": slow.to_dict()}], ts=1000.0)
        eng = RulesEngine([{
            "name": "p99-slo", "kind": "burn_rate", "metric":
            "estorch_req", "quantile": 0.99, "slo_s": 0.05,
            "windows": [{"window_s": 3600}, {"window_s": 30}]}])
        fired = eng.evaluate(store, ["a"], 1000.0)
        assert [t["event"] for t in fired] == ["firing"]
        assert "p99" in fired[0]["detail"] \
            and "estorch_req" in fired[0]["detail"]
        # 2h later, the short window is empty: quantile None -> resolve
        resolved = eng.evaluate(store, ["a"], 1000.0 + 7200)
        assert [t["event"] for t in resolved] == ["resolved"]

    def test_burn_rate_resolves_when_short_window_clears(self, tmp_path):
        """The multi-window promise end to end: after recovery the
        SHORT window's delta is clean, so the alert resolves even
        though the cumulative (lifetime) histogram still contains the
        spike."""
        store = _mk_store(tmp_path)
        h = Histogram()
        for _ in range(300):
            h.observe(0.500)
        store.append([{"name": "estorch_req", "labels": {"target": "a"},
                       "hist": h.to_dict()}], ts=1000.0)
        eng = RulesEngine([{
            "name": "p99-slo", "kind": "burn_rate",
            "metric": "estorch_req", "quantile": 0.99, "slo_s": 0.05,
            "windows": [{"window_s": 3600}, {"window_s": 30}]}])
        assert [t["event"] for t in eng.evaluate(store, ["a"], 1000.0)] \
            == ["firing"]
        for _ in range(200):
            h.observe(0.010)  # recovery
        store.append([{"name": "estorch_req", "labels": {"target": "a"},
                       "hist": h.to_dict()}], ts=1060.0)
        # lifetime p99 is still the spike, but the 30s delta is clean
        assert store.quantile("estorch_req", 0.99, {"target": "a"},
                              3600, now=1070.0) > 0.4
        assert [t["event"] for t in eng.evaluate(store, ["a"], 1070.0)] \
            == ["resolved"]

    def test_seed_from_ledger_resolves_phantom_alert(self, tmp_path):
        """A collector restart must adopt ledger-active alerts: if the
        condition cleared meanwhile, the fresh engine emits the missing
        resolved (so the dash's ledger reconstruction agrees with
        /alerts), and if it still holds it does NOT re-announce."""
        store = _mk_store(tmp_path)
        ledger = str(tmp_path / "alerts.jsonl")
        append_ledger(ledger, [{"ts": 900.0, "event": "firing",
                                "rule": "down", "target": "a",
                                "detail": "estorch_up absent"}])
        store.append([{"name": "estorch_up", "labels": {"target": "a"},
                       "value": 1}], ts=1000.0)
        eng = RulesEngine([{"name": "down", "kind": "absence",
                            "metric": "estorch_up", "for_s": 0,
                            "window_s": 30}], ledger_path=ledger)
        assert eng.active() and eng.active()[0]["rule"] == "down"
        out = eng.evaluate(store, ["a"], 1000.0)
        assert [t["event"] for t in out] == ["resolved"]
        # the ledger now closes the loop for the dash
        events = [t["event"] for t in read_ledger(ledger)]
        assert events == ["firing", "resolved"]
        # still-holding case: seeded firing is kept, not re-announced
        append_ledger(ledger, [{"ts": 1100.0, "event": "firing",
                                "rule": "down", "target": "b",
                                "detail": "estorch_up absent"}])
        eng2 = RulesEngine([{"name": "down", "kind": "absence",
                             "metric": "estorch_up", "for_s": 0,
                             "window_s": 30}], ledger_path=ledger)
        assert eng2.evaluate(store, ["b"], 1200.0) == []
        assert eng2.active()[0]["target"] == "b"

    def test_ledger_round_trip_and_tail(self, tmp_path):
        path = str(tmp_path / "alerts.jsonl")
        append_ledger(path, [{"ts": 1, "event": "firing", "rule": "r",
                              "target": "a", "detail": "d"}])
        append_ledger(path, [{"ts": 2, "event": "resolved", "rule": "r",
                              "target": "a", "detail": "d"}])
        got = read_ledger(path)
        assert [t["event"] for t in got] == ["firing", "resolved"]
        assert read_ledger(str(tmp_path / "missing.jsonl")) == []

    def test_removed_target_resolves_instead_of_haunting(self, tmp_path):
        """A firing alert for a target deleted from targets.json must be
        closed (rule/target can never re-evaluate) — not shown on
        /alerts and the dash forever and re-adopted by every restart."""
        store = _mk_store(tmp_path)
        eng = RulesEngine([{"name": "down", "kind": "absence",
                            "metric": "estorch_up", "for_s": 0,
                            "window_s": 30}])
        assert [t["event"] for t in eng.evaluate(store, ["gone"], 1000.0)] \
            == ["firing"]
        out = eng.evaluate(store, ["other"], 1001.0)
        events = {(t["event"], t["target"]) for t in out}
        assert ("resolved", "gone") in events
        assert all(a["target"] != "gone" for a in eng.active())

    def test_ledger_compacts_to_a_bound(self, tmp_path):
        """A flapping rule must not grow the ledger (and each atomic
        rewrite's cost) without bound — append compacts to the newest
        max_transitions, which every reader's tail already fits in."""
        path = str(tmp_path / "alerts.jsonl")
        for i in range(30):
            append_ledger(path, [{"ts": i, "event": "firing", "rule": "r",
                                  "target": "a", "detail": "d"}],
                          max_transitions=10)
        got = read_ledger(path, tail=100)
        assert len(got) == 10
        assert [t["ts"] for t in got] == list(range(20, 30))  # newest kept

    def test_load_rules_one_line_errors(self, tmp_path):
        bad = tmp_path / "rules.json"
        bad.write_text(json.dumps({"schema": 1, "rules": [
            {"name": "x", "kind": "wat"}]}))
        with pytest.raises(ValueError) as ei:
            load_rules(str(bad))
        assert "\n" not in str(ei.value) and "wat" in str(ei.value)


# =====================================================================
# collector units
# =====================================================================

class TestCollectorUnits:
    def test_samples_from_exposition_tags_and_collapses_hists(self):
        h = Histogram()
        for v in (0.01, 0.02, 0.5):
            h.observe(v)
        body = render_exposition({"requests_total": 3}, None, up=True,
                                 histograms={"serve/request_s":
                                             h.to_export()})
        samples = samples_from_exposition(body, "serve-a")
        by_name = {s["name"]: s for s in samples}
        assert by_name["estorch_requests_total"]["value"] == 3.0
        assert by_name["estorch_requests_total"]["labels"] == {
            "target": "serve-a"}
        snap = by_name["estorch_serve_request_s"]
        assert "hist" in snap and snap["hist"]["count"] == 3
        # bucket/sum component series collapsed into the one snapshot
        assert "estorch_serve_request_s_bucket" not in by_name
        assert "estorch_serve_request_s_sum" not in by_name
        back = Histogram.from_dict(snap["hist"])
        assert back.count == 3 and back.quantile(0.99) > 0

    def test_garbage_body_raises(self):
        with pytest.raises(ValueError):
            samples_from_exposition("<html>nope</html>", "t")

    def test_scrape_run_dir_composes_like_sidecar(self, tmp_path):
        Heartbeat(str(tmp_path / "heartbeat.json")).beat(
            "eval", 7, {"env_steps": 11})
        samples = scrape_run_dir(str(tmp_path), "run-1")
        by_name = {s["name"]: s["value"] for s in samples
                   if "value" in s}
        assert by_name["estorch_up"] == 1.0
        assert by_name["estorch_env_steps"] == 11.0
        assert by_name["estorch_heartbeat_generation"] == 7.0
        with pytest.raises(ValueError):
            scrape_run_dir(str(tmp_path / "empty"), "x")

    def test_targets_file_validation(self, tmp_path):
        assert validate_targets({"schema": 1, "targets": [
            {"name": "a", "url": "http://x/metrics"},
            {"name": "a", "run_dir": "r"}]})  # dup name
        good = tmp_path / "targets.json"
        good.write_text(json.dumps({"schema": 1, "interval_s": 0.5,
                                    "targets": [
                                        {"name": "a",
                                         "url": "http://x/metrics"},
                                        {"name": "b", "run_dir": "runs/r"},
                                    ]}))
        targets, interval = load_targets(str(good))
        assert interval == 0.5
        assert [t.kind for t in targets] == ["prometheus", "run_dir"]
        # relative run_dir resolves against the targets file's directory
        assert targets[1].run_dir == str(tmp_path / "runs" / "r")

    def test_selfcheck_clean(self):
        from estorch_tpu.obs.agg.collector import selfcheck

        assert selfcheck() == []


# =====================================================================
# dash units
# =====================================================================

class TestDashUnits:
    def test_snapshot_and_render(self, tmp_path):
        from estorch_tpu.obs.agg.dash import fleet_snapshot, render

        root = str(tmp_path / "store")
        s = SeriesStore(root)
        h = Histogram()
        for v in (0.010, 0.020, 0.500):
            h.observe(v)
        now = time.time()
        s.append([
            {"name": "estorch_up", "labels": {"target": "serve-a"},
             "value": 1},
            {"name": "estorch_queue_depth",
             "labels": {"target": "serve-a"}, "value": 3},
            {"name": "estorch_serve_request_s",
             "labels": {"target": "serve-a"}, "hist": h.to_dict()},
            {"name": "estorch_up", "labels": {"target": "serve-b"},
             "value": 0},
        ], ts=now)
        append_ledger(os.path.join(root, "alerts.jsonl"),
                      [{"ts": now, "event": "firing",
                        "rule": "replica-down", "target": "serve-b",
                        "detail": "estorch_up=0 on target 'serve-b'"}])
        snap = fleet_snapshot(root, window_s=60, now=now)
        rows = {r["target"]: r for r in snap["targets"]}
        assert rows["serve-a"]["up"] and not rows["serve-b"]["up"]
        assert rows["serve-a"]["req_p99_s"] == h.quantile(0.99)
        assert rows["serve-b"]["alerts"] == ["replica-down"]
        assert rows["serve-b"]["req_p99_s"] is None  # renders as '-'
        text = render(root, window_s=60, now=now)
        assert "serve-a" in text and "DOWN" in text
        assert "replica-down" in text

    def test_cold_column_renders_startup_and_fresh_builds(self, tmp_path):
        """The PR-12 cold-start gauges land as a `cold` dash column:
        startup seconds, with a `!N` suffix when the replica paid N
        fresh XLA builds at load (a warm bundle makes that 0); targets
        without the gauges (training runs) honestly render '-'."""
        from estorch_tpu.obs.agg.dash import fleet_snapshot, render

        root = str(tmp_path / "store")
        s = SeriesStore(root)
        now = time.time()
        s.append([
            {"name": "estorch_up", "labels": {"target": "warm"},
             "value": 1},
            {"name": "estorch_startup_s", "labels": {"target": "warm"},
             "value": 0.9},
            {"name": "estorch_compiles_at_load",
             "labels": {"target": "warm"}, "value": 0},
            {"name": "estorch_up", "labels": {"target": "coldish"},
             "value": 1},
            {"name": "estorch_startup_s",
             "labels": {"target": "coldish"}, "value": 7.2},
            {"name": "estorch_compiles_at_load",
             "labels": {"target": "coldish"}, "value": 41},
            {"name": "estorch_up", "labels": {"target": "train-run"},
             "value": 1},
            # -1 = the server's "no monitoring stream, warmth unproven"
            # sentinel — must render distinctly from a proven-clean 0
            {"name": "estorch_up", "labels": {"target": "unproven"},
             "value": 1},
            {"name": "estorch_startup_s",
             "labels": {"target": "unproven"}, "value": 1.5},
            {"name": "estorch_compiles_at_load",
             "labels": {"target": "unproven"}, "value": -1},
        ], ts=now)
        snap = fleet_snapshot(root, window_s=60, now=now)
        rows = {r["target"]: r for r in snap["targets"]}
        assert rows["warm"]["startup_s"] == 0.9
        assert rows["warm"]["compiles_at_load"] == 0
        assert rows["train-run"]["startup_s"] is None
        text = render(root, window_s=60, now=now)
        assert "cold" in text.splitlines()[1]  # the header row
        assert "0.9s" in text
        assert "7.2s!41" in text  # fresh builds called out
        assert "1.5s?" in text  # unproven warmth never reads as clean

    def test_elastic_host_columns_from_store_alone(self, tmp_path):
        """An elastic multi-host coordinator (docs/multihost.md) renders
        membership count (with a `!N` suffix when N hosts died inside
        the window), the worst per-host fold-latency p99, and nothing
        invented for targets without a fleet."""
        from estorch_tpu.obs.agg.dash import fleet_snapshot, render

        root = str(tmp_path / "store")
        s = SeriesStore(root)
        now = time.time()
        s.append([
            {"name": "estorch_up", "labels": {"target": "coord"},
             "value": 1},
            {"name": "estorch_elastic_hosts",
             "labels": {"target": "coord"}, "value": 3},
            {"name": "estorch_hosts_lost",
             "labels": {"target": "coord"}, "value": 0},
            {"name": "estorch_elastic_fold_p99_worst_s",
             "labels": {"target": "coord"}, "value": 0.0421},
            {"name": "estorch_up", "labels": {"target": "serve-x"},
             "value": 1},
        ], ts=now - 30)
        # one host died inside the window: count drops, lost increases
        s.append([
            {"name": "estorch_up", "labels": {"target": "coord"},
             "value": 1},
            {"name": "estorch_elastic_hosts",
             "labels": {"target": "coord"}, "value": 2},
            {"name": "estorch_hosts_lost",
             "labels": {"target": "coord"}, "value": 1},
            {"name": "estorch_elastic_fold_p99_worst_s",
             "labels": {"target": "coord"}, "value": 0.0550},
            {"name": "estorch_up", "labels": {"target": "serve-x"},
             "value": 1},
        ], ts=now)
        snap = fleet_snapshot(root, window_s=60, now=now)
        rows = {r["target"]: r for r in snap["targets"]}
        assert rows["coord"]["elastic_hosts"] == 2
        assert rows["coord"]["hosts_lost"] == 1
        assert rows["coord"]["host_fold_p99_s"] == 0.0550
        assert rows["serve-x"]["elastic_hosts"] is None
        text = render(root, window_s=60, now=now)
        assert "hosts" in text.splitlines()[1]  # the header row
        assert "2!1" in text  # membership with the death called out
        assert "55.0" in text  # worst-host fold p99 in ms

    def test_router_columns_from_store_alone(self, tmp_path):
        """A front-router target (serve/router.py) renders breaker
        state, windowed retry/hedge increases, and the worst per-replica
        p99 — all from the stored per-replica labeled gauges; non-router
        targets honestly render '-'."""
        from estorch_tpu.obs.agg.dash import fleet_snapshot, render

        root = str(tmp_path / "store")
        s = SeriesStore(root)
        now = time.time()

        def batch(retries):
            return [
                {"name": "estorch_up", "labels": {"target": "router-1"},
                 "value": 1},
                {"name": "estorch_router_replica_up",
                 "labels": {"target": "router-1", "replica": "r0"},
                 "value": 1},
                {"name": "estorch_router_replica_up",
                 "labels": {"target": "router-1", "replica": "r1"},
                 "value": 0},
                {"name": "estorch_router_breaker_state",
                 "labels": {"target": "router-1", "replica": "r0"},
                 "value": 0},
                {"name": "estorch_router_breaker_state",
                 "labels": {"target": "router-1", "replica": "r1"},
                 "value": 2},
                {"name": "estorch_router_upstream_p99_s",
                 "labels": {"target": "router-1", "replica": "r0"},
                 "value": 0.004},
                {"name": "estorch_router_retries_total",
                 "labels": {"target": "router-1"}, "value": retries},
                {"name": "estorch_router_hedge_wins_total",
                 "labels": {"target": "router-1"}, "value": 1},
                {"name": "estorch_up", "labels": {"target": "serve-a"},
                 "value": 1},
            ]

        s.append(batch(3), ts=now - 5)
        s.append(batch(7), ts=now)  # retries grew by 4 in the window
        snap = fleet_snapshot(root, window_s=60, now=now)
        rows = {r["target"]: r for r in snap["targets"]}
        ro = rows["router-1"]["router"]
        assert ro["breakers_open"] == 1
        assert set(ro["replicas"]) == {"r0", "r1"}
        assert ro["replicas"]["r1"]["breaker"] == 2
        assert ro["retries"] == 4.0
        assert ro["worst_p99_s"] == 0.004
        assert rows["serve-a"]["router"] is None
        text = render(root, window_s=60, now=now)
        header = text.splitlines()[1]
        for col in ("brk", "retry", "hedge", "repl p99"):
            assert col in header, header
        router_line = [ln for ln in text.splitlines()
                       if ln.startswith("router-1")][0]
        assert "1/2!" in router_line  # one of two breakers open
        assert "4.0" in router_line or " 4 " in router_line
        serve_line = [ln for ln in text.splitlines()
                      if ln.startswith("serve-a")][0]
        assert serve_line.count("-") >= 4  # honest dashes

    def test_resolved_alert_leaves_the_dash(self, tmp_path):
        from estorch_tpu.obs.agg.dash import fleet_snapshot

        root = str(tmp_path / "store")
        s = SeriesStore(root)
        now = time.time()
        s.append([{"name": "estorch_up", "labels": {"target": "a"},
                   "value": 1}], ts=now)
        led = os.path.join(root, "alerts.jsonl")
        append_ledger(led, [{"ts": now - 2, "event": "firing",
                             "rule": "r", "target": "a", "detail": "d"}])
        append_ledger(led, [{"ts": now - 1, "event": "resolved",
                             "rule": "r", "target": "a", "detail": "d"}])
        snap = fleet_snapshot(root, window_s=60, now=now)
        assert snap["active_alerts"] == []


# =====================================================================
# THE acceptance demo: 3-target fleet under chaos + load
# =====================================================================

@pytest.fixture(scope="module")
def fleet_bundle(tmp_path_factory):
    import jax
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs.pendulum import Pendulum

    es = ES(policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
            population_size=8, sigma=0.05,
            policy_kwargs={"action_dim": 1, "hidden": (16, 16),
                           "discrete": False, "action_scale": 2.0},
            agent_kwargs={"env": Pendulum(), "horizon": 10},
            optimizer_kwargs={"learning_rate": 1e-2}, seed=0,
            table_size=1 << 14, device=jax.devices()[0])
    es.train(1, verbose=False)
    path = str(tmp_path_factory.mktemp("fleet") / "bundle")
    es.export_bundle(path, version="fleet-v1")
    return path


class TestFleetAcceptance:
    def test_three_target_fleet_with_chaos_kill_and_latency_spike(
            self, fleet_bundle, tmp_path):
        """The E2E acceptance demo (ISSUE 11): two serve servers (one
        chaos-killed mid-run and restarted) + a supervised-run sidecar
        under loadgen while the collector scrapes throughout."""
        from estorch_tpu.obs.export.sidecar import MetricsSidecar
        from estorch_tpu.obs.spans import Telemetry
        from estorch_tpu.serve import PolicyServer
        from estorch_tpu.serve.loadgen import run_load
        from estorch_tpu.serve.server import find_free_port

        store_root = str(tmp_path / "store")
        ledger = os.path.join(store_root, "alerts.jsonl")
        os.makedirs(store_root, exist_ok=True)

        # --- the fleet: serve-a (healthy), serve-b (to be killed),
        # --- run-1 (a supervised-style run dir behind the sidecar)
        srv_a = PolicyServer(fleet_bundle, port=0, max_batch=8,
                             max_wait_ms=1.0,
                             telemetry=Telemetry(enabled=True))
        srv_a.start_background()
        port_b = find_free_port()
        srv_b = PolicyServer(fleet_bundle, port=port_b, max_batch=8,
                             max_wait_ms=1.0,
                             telemetry=Telemetry(enabled=True))
        srv_b.start_background()
        run_dir = str(tmp_path / "run1")
        hb = Heartbeat(os.path.join(run_dir, "heartbeat.json"))
        hb.beat("eval", 41, {"env_steps": 12345})
        sidecar = MetricsSidecar(run_dir, port=0)
        sidecar.start_background()

        # the /stats collector-discovery stanza IS the targets entry
        import urllib.request

        with urllib.request.urlopen(
                f"http://{srv_a.host}:{srv_a.port}/stats", timeout=10) as r:
            stats_a = json.loads(r.read().decode())
        stanza = stats_a["collector_target"]
        assert stanza["url"].endswith("/metrics")
        # a wildcard bind must never leak into the pasteable stanza (a
        # remote collector cannot dial 0.0.0.0)
        srv_a.host, saved = "0.0.0.0", srv_a.host
        try:
            wild = srv_a._collector_target()
            assert "0.0.0.0" not in wild["url"] and wild["url"], wild
        finally:
            srv_a.host = saved

        store = SeriesStore(store_root)
        rules = RulesEngine([
            {"name": "replica-down", "kind": "absence",
             "metric": "estorch_up", "for_s": 0, "window_s": 30},
            {"name": "p99-slo", "kind": "burn_rate",
             "metric": "estorch_serve_request_s", "quantile": 0.99,
             "slo_s": 0.25,
             "windows": [{"window_s": 120}, {"window_s": 120}]},
        ], ledger_path=ledger)
        targets = [
            Target("serve-a", url=stanza["url"], timeout_s=5.0),
            Target("serve-b",
                   url=f"http://{srv_b.host}:{srv_b.port}/metrics",
                   timeout_s=1.0),
            Target("run-1",
                   url=f"http://{sidecar.host}:{sidecar.port}/metrics",
                   timeout_s=5.0),
        ]
        col = Collector(targets, store, rules, port=0)
        col.start_background()
        try:
            # --- loadgen over both replicas while the collector scrapes
            results = {}

            def load(name, srv, total):
                results[name] = run_load(f"{srv.host}:{srv.port}",
                                         conns=4, total=total,
                                         duration_s=60.0,
                                         obs=[0.0, 0.0, 0.0])

            ta = threading.Thread(target=load,
                                  args=("a", srv_a, 300), daemon=True)
            tb = threading.Thread(target=load,
                                  args=("b", srv_b, 60), daemon=True)
            ta.start(), tb.start()
            t1 = col.tick()  # mid-load: every target up, no alerts
            assert all(r["ok"] for r in t1["targets"].values()), t1
            assert t1["transitions"] == []
            ta.join(60), tb.join(60)
            assert results["a"]["requests"] == 300
            assert not results["a"]["errors"]

            # --- chaos: kill serve-b mid-run; the tick must tolerate the
            # dead target (bounded) and the absence rule must fire
            srv_b.shutdown(drain=True)
            t0 = time.perf_counter()
            t2 = col.tick()
            tick_s = time.perf_counter() - t0
            assert tick_s < 5.0, f"tick stalled on the dead target: " \
                                 f"{tick_s:.1f}s"
            assert not t2["targets"]["serve-b"]["ok"]
            assert t2["targets"]["serve-a"]["ok"]  # others unaffected
            fired = {(t["rule"], t["target"]): t
                     for t in t2["transitions"] if t["event"] == "firing"}
            assert ("replica-down", "serve-b") in fired
            assert "serve-b" in fired[("replica-down",
                                       "serve-b")]["detail"]
            assert ("replica-down", "serve-a") not in fired

            # --- restart the replica on the SAME port: absence resolves
            srv_b2 = PolicyServer(fleet_bundle, port=port_b, max_batch=8,
                                  max_wait_ms=1.0,
                                  telemetry=Telemetry(enabled=True))
            srv_b2.start_background()
            try:
                t3 = col.tick()
                resolved = [t for t in t3["transitions"]
                            if t["event"] == "resolved"]
                assert [(t["rule"], t["target"]) for t in resolved] == \
                    [("replica-down", "serve-b")]

                # --- injected latency spike on serve-a breaches the p99
                # burn-rate rule, naming the target and the endpoint
                # metric
                for _ in range(300):
                    srv_a.obs.hists.observe("serve/request_s", 1.0)
                t4 = col.tick()
                burn = [t for t in t4["transitions"]
                        if t["rule"] == "p99-slo"
                        and t["event"] == "firing"]
                assert burn and burn[0]["target"] == "serve-a", t4
                assert "estorch_serve_request_s" in burn[0]["detail"]
                assert "p99" in burn[0]["detail"]

                # --- stored-history quantiles vs the server's own
                # histogram, within the documented ladder bound
                now = time.time()
                h = srv_a.obs.hists.get("serve/request_s")
                bound = h.quantile_error_bound()
                for q in (0.50, 0.99):
                    stored = store.quantile("estorch_serve_request_s", q,
                                            {"target": "serve-a"},
                                            window_s=300, now=now)
                    own = h.quantile(q)
                    assert stored is not None
                    assert abs(stored - own) <= own * bound + 1e-9, (
                        f"p{q * 100:g}: stored {stored} vs server {own}")

                # --- the collector's own plane: /alerts + /metrics
                with urllib.request.urlopen(
                        f"http://{col.host}:{col.port}/alerts",
                        timeout=10) as r:
                    alerts = json.loads(r.read().decode())
                active = {(a["rule"], a["target"])
                          for a in alerts["active"]}
                assert ("p99-slo", "serve-a") in active
                events = [(t["event"], t["rule"], t["target"])
                          for t in alerts["transitions"]]
                assert ("firing", "replica-down", "serve-b") in events
                assert ("resolved", "replica-down", "serve-b") in events
                with urllib.request.urlopen(
                        f"http://{col.host}:{col.port}/metrics",
                        timeout=10) as r:
                    parse_exposition(r.read().decode())

                # --- obs dash --once renders all three targets + alerts,
                # run AS A FILE (jax-free-ness itself is pinned by
                # test_dash_file_run_never_imports_package_or_jax)
                r = subprocess.run(
                    [sys.executable, os.path.join(
                        REPO, "estorch_tpu", "obs", "agg", "dash.py"),
                     "--store", store_root, "--once"],
                    capture_output=True, text=True, timeout=120)
                assert r.returncode == 0, r.stderr
                out = r.stdout
                for name in ("serve-a", "serve-b", "run-1"):
                    assert name in out, out
                assert "p99-slo" in out  # the active alert renders
                assert "3 target(s)" in out
            finally:
                srv_b2.shutdown(drain=True)
        finally:
            col.close()
            sidecar.close()
            srv_a.shutdown(drain=True)

    def test_dash_file_run_never_imports_package_or_jax(self, tmp_path):
        """The dash (and the store/rules it file-loads) must work with
        the package never imported — same discipline as the sidecar."""
        root = str(tmp_path / "store")
        s = SeriesStore(root)
        s.append([{"name": "estorch_up", "labels": {"target": "a"},
                   "value": 1}], ts=time.time())
        dash = os.path.join(REPO, "estorch_tpu", "obs", "agg", "dash.py")
        probe = (
            "import importlib.util, sys\n"
            f"spec = importlib.util.spec_from_file_location('d', {dash!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "assert 'jax' not in sys.modules, 'dash imported jax'\n"
            "assert 'estorch_tpu' not in sys.modules, 'package init ran'\n"
            f"print(m.render({root!r}, window_s=3600))\n"
        )
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        assert "a" in r.stdout and "UP" in r.stdout

    def test_collector_file_run_never_imports_package_or_jax(
            self, tmp_path):
        """collect as a plain file: scrape a run dir, store a sample,
        evaluate a rule — all without the package or jax loading."""
        Heartbeat(str(tmp_path / "heartbeat.json")).beat("eval", 1, {})
        col = os.path.join(REPO, "estorch_tpu", "obs", "agg",
                           "collector.py")
        store_root = str(tmp_path / "store")
        probe = (
            "import importlib.util, sys, time\n"
            f"spec = importlib.util.spec_from_file_location('c', {col!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "assert 'jax' not in sys.modules, 'collector imported jax'\n"
            "assert 'estorch_tpu' not in sys.modules, 'package init ran'\n"
            f"store = m.SeriesStore({store_root!r})\n"
            "rules = m.RulesEngine([{'name': 'down', 'kind': 'absence',"
            " 'metric': 'estorch_up', 'for_s': 0}])\n"
            f"t = m.Target('run', run_dir={str(tmp_path)!r})\n"
            "c = m.Collector([t], store, rules, serve_http=False)\n"
            "tick = c.tick(time.time())\n"
            "assert tick['targets']['run']['ok'], tick\n"
            "assert tick['transitions'] == [], tick\n"
            "print('FILE_RUN_OK')\n"
        )
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        assert "FILE_RUN_OK" in r.stdout


# =====================================================================
# CLI round trip (module form)
# =====================================================================

class TestCollectCLI:
    def test_collect_once_against_run_dir(self, tmp_path, capsys):
        from estorch_tpu.obs.agg.collector import main as collect_main

        Heartbeat(str(tmp_path / "run" / "heartbeat.json")).beat(
            "eval", 3, {"env_steps": 5})
        targets = tmp_path / "targets.json"
        targets.write_text(json.dumps({
            "schema": 1, "interval_s": 0.1,
            "targets": [{"name": "run-1", "run_dir": "run"}]}))
        rules = tmp_path / "rules.json"
        rules.write_text(json.dumps({
            "schema": 1, "rules": [
                {"name": "down", "kind": "absence",
                 "metric": "estorch_up", "for_s": 0, "window_s": 30}]}))
        store_dir = str(tmp_path / "store")
        rc = collect_main(["--targets", str(targets), "--store", store_dir,
                           "--rules", str(rules), "--once"])
        assert rc == 0
        out = capsys.readouterr().out
        ready = json.loads(out.splitlines()[0])
        assert ready["ready"] and ready["targets"] == ["run-1"]
        s = SeriesStore(store_dir)
        got = s.latest("estorch_env_steps", {"target": "run-1"},
                       window_s=600, now=time.time())
        assert got and list(got.values())[0][2] == 5.0

    def test_bad_targets_file_is_exit_2_one_line(self, tmp_path, capsys):
        from estorch_tpu.obs.agg.collector import main as collect_main

        bad = tmp_path / "targets.json"
        bad.write_text(json.dumps({"schema": 1, "targets": [{"name": "x"}]}))
        rc = collect_main(["--targets", str(bad),
                           "--store", str(tmp_path / "s")])
        assert rc == 2
        err = capsys.readouterr().err
        assert "exactly one of url / run_dir" in err
