"""Unit tests for the core ES math ops (SURVEY.md §4 'Unit' bullet)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from estorch_tpu.ops import (
    NoiseTable,
    centered_rank,
    centered_rank_safe,
    compute_ranks,
    es_gradient,
    fold_mirrored_weights,
    make_noise_table,
    make_param_spec,
    member_noise,
    member_offsets,
    pair_signs,
    rank_weighted_noise_sum,
    sample_pair_offsets,
)


class TestRanks:
    def test_known_permutation(self):
        x = jnp.array([3.0, 1.0, 2.0])
        assert compute_ranks(x).tolist() == [2, 0, 1]
        cr = centered_rank(x)
        np.testing.assert_allclose(np.asarray(cr), [0.5, -0.5, 0.0], atol=1e-7)

    def test_centered_rank_sums_to_zero(self):
        x = jax.random.normal(jax.random.key(0), (101,))
        assert abs(float(centered_rank(x).sum())) < 1e-5

    def test_scale_invariance(self):
        x = jax.random.normal(jax.random.key(1), (64,))
        a = centered_rank(x)
        b = centered_rank(1000.0 * x + 5.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_matches_numpy_oracle(self):
        x = np.random.RandomState(7).randn(257).astype(np.float32)
        ranks_np = np.empty(len(x), dtype=np.int32)
        ranks_np[np.argsort(x)] = np.arange(len(x))
        expected = ranks_np.astype(np.float32) / (len(x) - 1) - 0.5
        np.testing.assert_allclose(np.asarray(centered_rank(jnp.array(x))), expected, atol=1e-7)

    def test_degenerate_sizes(self):
        assert centered_rank(jnp.array([5.0])).tolist() == [0.0]


class TestCenteredRankSafe:
    """Device twin of utils/fault.py::rank_weights_with_failures."""

    def test_all_finite_bit_identical_to_centered_rank(self):
        x = jax.random.normal(jax.random.key(2), (129,))
        w, n_valid = centered_rank_safe(x)
        np.testing.assert_array_equal(np.asarray(w), np.asarray(centered_rank(x)))
        assert int(n_valid) == 129

    def test_verdict_example_nan_not_promoted(self):
        """The exact round-1 bug: centered_rank([1, nan, 3, 2]) gave the NaN
        member weight +0.5 (argsort sorts NaN last)."""
        w, n_valid = centered_rank_safe(jnp.array([1.0, jnp.nan, 3.0, 2.0]))
        assert int(n_valid) == 3
        assert float(w[1]) == 0.0
        # survivors ranked among themselves, renormalized by n/n_valid = 4/3
        expected = np.array([-0.5, 0.0, 0.5, 0.0], np.float32) * (4.0 / 3.0)
        np.testing.assert_allclose(
            np.asarray(w), [expected[0], 0.0, expected[2], 0.0], atol=1e-6
        )

    def test_matches_host_oracle_random_failures(self):
        from estorch_tpu.utils.fault import rank_weights_with_failures

        rng = np.random.RandomState(11)
        for trial in range(5):
            x = rng.randn(64).astype(np.float32)
            bad = rng.rand(64) < 0.2
            x[bad] = [np.nan, np.inf, -np.inf][trial % 3]
            if np.isfinite(x).sum() < 2:
                continue
            w, n_valid = centered_rank_safe(jnp.asarray(x))
            np.testing.assert_allclose(
                np.asarray(w), rank_weights_with_failures(x), atol=1e-6,
                err_msg=f"trial {trial}",
            )
            assert int(n_valid) == int(np.isfinite(x).sum())

    def test_under_jit(self):
        x = jnp.array([np.nan, 2.0, 1.0, np.nan])
        w, n_valid = jax.jit(centered_rank_safe)(x)
        np.testing.assert_allclose(np.asarray(w), [0.0, 1.0, -1.0, 0.0], atol=1e-6)
        assert int(n_valid) == 2

    def test_fewer_than_two_valid_zeroes_update(self):
        w, n_valid = centered_rank_safe(jnp.array([jnp.nan, 5.0, jnp.nan]))
        assert int(n_valid) == 1
        np.testing.assert_array_equal(np.asarray(w), np.zeros(3, np.float32))


class TestNoiseTable:
    def test_determinism_same_seed(self):
        t1 = make_noise_table(4096, seed=3)
        t2 = make_noise_table(4096, seed=3)
        np.testing.assert_array_equal(np.asarray(t1.data), np.asarray(t2.data))

    def test_different_seed_differs(self):
        t1 = make_noise_table(1024, seed=0)
        t2 = make_noise_table(1024, seed=1)
        assert not np.array_equal(np.asarray(t1.data), np.asarray(t2.data))

    def test_slice_matches_direct_index(self):
        t = make_noise_table(1000, seed=0)
        sl = t.slice(jnp.int32(17), 5)
        np.testing.assert_array_equal(np.asarray(sl), np.asarray(t.data[17:22]))

    def test_offsets_in_bounds(self):
        key = jax.random.key(0)
        offs = sample_pair_offsets(key, 1000, table_size=5000, dim=300)
        assert int(offs.min()) >= 0
        assert int(offs.max()) <= 5000 - 300

    def test_offsets_reject_oversized_dim(self):
        with pytest.raises(ValueError):
            sample_pair_offsets(jax.random.key(0), 4, table_size=10, dim=11)

    def test_antithetic_signs(self):
        s = pair_signs(6)
        assert s.tolist() == [1.0, -1.0, 1.0, -1.0, 1.0, -1.0]
        with pytest.raises(ValueError):
            pair_signs(5)

    def test_member_offsets_repeat_pairs(self):
        m = member_offsets(jnp.array([10, 20], dtype=jnp.int32))
        assert m.tolist() == [10, 10, 20, 20]

    def test_mirrored_noise_cancels(self):
        """θ+σε and θ-σε reconstruct from one offset: signed rows sum to 0."""
        t = make_noise_table(2048, seed=0)
        pair_offs = sample_pair_offsets(jax.random.key(5), 4, t.size, 16)
        offs = member_offsets(pair_offs)
        signs = pair_signs(8)
        rows = member_noise(t, offs, signs, 16)
        np.testing.assert_allclose(np.asarray(rows.sum(0)), np.zeros(16), atol=1e-5)


class TestParamSpec:
    def test_roundtrip(self):
        tree = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3), "b": jnp.ones(3)}
        flat, spec = make_param_spec(tree)
        assert spec.dim == 9
        back = spec.unravel(flat)
        np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(tree["w"]))
        np.testing.assert_array_equal(np.asarray(back["b"]), np.asarray(tree["b"]))


class TestGradient:
    def test_weighted_sum_matches_dense(self):
        t = make_noise_table(8192, seed=2)
        dim = 37
        n = 10
        offs = sample_pair_offsets(jax.random.key(1), n, t.size, dim)
        w = jax.random.normal(jax.random.key(2), (n,))
        dense = np.asarray(member_noise(t, offs, jnp.ones(n), dim))
        expected = np.asarray(w) @ dense
        got = np.asarray(rank_weighted_noise_sum(t, offs, w, dim=dim, chunk=4))
        np.testing.assert_allclose(got, expected, rtol=2e-5, atol=1e-5)

    def test_chunking_invariance(self):
        t = make_noise_table(8192, seed=2)
        dim = 21
        n = 24
        offs = sample_pair_offsets(jax.random.key(3), n, t.size, dim)
        w = jax.random.normal(jax.random.key(4), (n,))
        a = rank_weighted_noise_sum(t, offs, w, dim=dim, chunk=24)
        b = rank_weighted_noise_sum(t, offs, w, dim=dim, chunk=8)
        c = rank_weighted_noise_sum(t, offs, w, dim=dim, chunk=7)  # non-divisor → pad
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-5, atol=1e-6)

    def test_pair_folding_matches_per_member_sum(self):
        """Folded Σ(w2k−w2k+1)εk must equal the naive per-member Σ wᵢsᵢεᵢ."""
        t = make_noise_table(4096, seed=5)
        dim, n_pairs = 19, 16
        pair_offs = sample_pair_offsets(jax.random.key(8), n_pairs, t.size, dim)
        offs = member_offsets(pair_offs)
        signs = pair_signs(2 * n_pairs)
        w = jax.random.normal(jax.random.key(9), (2 * n_pairs,))
        dense = np.asarray(member_noise(t, offs, signs, dim))  # signed rows
        expected = np.asarray(w) @ dense
        folded = rank_weighted_noise_sum(
            t, pair_offs, fold_mirrored_weights(w), dim=dim, chunk=8
        )
        np.testing.assert_allclose(np.asarray(folded), expected, rtol=2e-5, atol=1e-5)

    def test_gradient_estimator_on_quadratic_bowl(self):
        """E[f(θ+σε)ε]/σ ≈ ∇f: check the estimator points downhill on f(x)=-|x-c|²."""
        dim = 8
        center = jnp.arange(dim, dtype=jnp.float32) / 4.0
        theta = jnp.zeros(dim)
        sigma = 0.1
        n_pairs = 4096
        t = make_noise_table(1 << 20, seed=9)
        pair_offs = sample_pair_offsets(jax.random.key(11), n_pairs, t.size, dim)
        offs = member_offsets(pair_offs)
        signs = pair_signs(2 * n_pairs)
        eps = member_noise(t, offs, signs, dim)  # signed noise rows
        fitness = -jnp.sum((theta + sigma * eps - center) ** 2, axis=1)
        weights = centered_rank(fitness)
        grad = es_gradient(
            t, pair_offs, weights, sigma=sigma,
            population_size=2 * n_pairs, dim=dim, chunk=512,
        )
        true_grad = -2.0 * (theta - center)  # ascent direction of fitness
        cos = float(
            jnp.dot(grad, true_grad)
            / (jnp.linalg.norm(grad) * jnp.linalg.norm(true_grad))
        )
        assert cos > 0.95, f"estimator misaligned with true gradient: cos={cos}"
