"""Host (reference-parity) backend: a reference user's code runs unchanged.

This is the reference's README usage shape (SURVEY.md Appendix A): a torch
policy class, a duck-typed Agent with rollout(policy) -> reward (or
(reward, bc)), a torch optimizer class — ES(...).train(n_steps, n_proc).
"""

import numpy as np
import pytest
import torch

from estorch_tpu import ES, NS_ES, NSRA_ES


class TorchMLP(torch.nn.Module):
    def __init__(self, hidden=16):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(4, hidden),
            torch.nn.Tanh(),
            torch.nn.Linear(hidden, 2),
        )

    def forward(self, x):
        return self.net(x)


class QuadraticAgent:
    """Deterministic fitness: -(||W - target||²) over the policy's params.

    A rollout stand-in that needs no env: fast, exact, and lets tests check
    actual optimization through the full host path.
    """

    target = 0.1

    def rollout(self, policy):
        with torch.no_grad():
            vec = torch.nn.utils.parameters_to_vector(policy.parameters())
            reward = -float(((vec - self.target) ** 2).sum())
        self.last_episode_steps = 1
        return reward


class QuadraticBCAgent(QuadraticAgent):
    """Novelty flavor: returns (reward, bc) like the reference's NS agents."""

    def rollout(self, policy):
        r = super().rollout(policy)
        with torch.no_grad():
            vec = torch.nn.utils.parameters_to_vector(policy.parameters())
        return r, vec[:2].numpy()


def _make(agent_cls=QuadraticAgent, cls=ES, pop=32, **extra):
    return cls(
        policy=TorchMLP,
        agent=agent_cls,
        optimizer=torch.optim.Adam,
        population_size=pop,
        sigma=0.05,
        seed=0,
        policy_kwargs={"hidden": 8},
        optimizer_kwargs={"lr": 0.05},
        table_size=1 << 16,
        **extra,
    )


class GymStyleAgent(QuadraticAgent):
    """Reference-idiomatic shape: holds a (fake) `.env` AND rollout() —
    must dispatch to the host path, never the device path."""

    def __init__(self):
        class _FakeGymEnv:  # has reset/step like gym, but no JaxEnv markers
            def reset(self):
                return None

            def step(self, a):
                return None

        self.env = _FakeGymEnv()


class TestHostES:
    def test_backend_detected(self):
        es = _make()
        assert es.backend == "host"

    def test_agent_with_gym_env_attribute_routes_to_host(self):
        """Regression: reference Agents usually hold self.env = gym.make(...);
        the rollout() contract must win over the env attribute."""
        es = _make(agent_cls=GymStyleAgent)
        assert es.backend == "host"
        es.train(1, verbose=False)
        assert len(es.history) == 1

    def test_optimizes_quadratic(self):
        es = _make()
        es.train(40, verbose=False)
        first, last = es.history[0], es.history[-1]
        assert last["reward_mean"] > first["reward_mean"]
        # distance to target must have shrunk substantially
        assert last["reward_max"] > 0.5 * first["reward_max"]

    def test_n_proc_parallel_matches_serial(self):
        """Same seed: n_proc=4 must produce identical results to n_proc=1
        (deterministic fitness; layout is member-indexed, not worker-indexed)."""
        a = _make()
        a.train(3, n_proc=1, verbose=False)
        b = _make()
        b.train(3, n_proc=4, verbose=False)
        np.testing.assert_allclose(
            a.state.params_flat, b.state.params_flat, rtol=1e-6, atol=1e-7
        )

    def test_policy_is_torch_module(self):
        es = _make()
        es.train(1, verbose=False)
        assert isinstance(es.policy, torch.nn.Module)
        assert isinstance(es.best_policy, torch.nn.Module)
        out = es.predict(np.zeros(4, dtype=np.float32))
        assert tuple(out.shape) == (2,)

    def test_best_policy_params_match_best_flat(self):
        es = _make()
        es.train(3, verbose=False)
        vec = torch.nn.utils.parameters_to_vector(es.best_policy.parameters())
        np.testing.assert_allclose(
            vec.detach().numpy(), es._best_flat, rtol=1e-6, atol=1e-7
        )

    def test_shared_agent_instance_caps_n_proc(self):
        es = _make(agent_cls=QuadraticAgent)
        es._agent_arg = QuadraticAgent()  # simulate instance-passing
        es._agent_is_shared_instance = True
        with pytest.warns(UserWarning, match="n_proc=1"):
            es.train(1, n_proc=4, verbose=False)

    def test_determinism_same_seed(self):
        a = _make()
        a.train(3, verbose=False)
        b = _make()
        b.train(3, verbose=False)
        np.testing.assert_array_equal(a.state.params_flat, b.state.params_flat)

    def test_evaluate_policy_uses_best_params(self):
        """use_best must evaluate _best_flat, not the center (deterministic
        quadratic fitness makes the distinction exact)."""
        es = _make()
        es.train(5, verbose=False)
        center = es.evaluate_policy(n_episodes=1)["mean"]
        best = es.evaluate_policy(n_episodes=1, use_best=True)["mean"]
        # with the quadratic agent, reward is a pure function of params:
        # best-member reward must equal the recorded best_reward exactly
        assert best == pytest.approx(es.best_reward, rel=1e-6)
        assert center != best or es.best_reward == center

    def test_env_steps_from_agent_attribute(self):
        es = _make()
        es.train(1, verbose=False)
        assert es.history[0]["env_steps"] == 32  # 1 step per member


class TestHostSigmaAnnealing:
    """Round-1 VERDICT next-round #7: σ-decay was a device-only option that
    the host backend rejected; a reference user porting a σ-annealed run
    needs it on the parity backend too."""

    def test_sigma_decays_with_floor(self):
        es = _make(sigma_decay=0.5, sigma_min=0.01)  # sigma starts at 0.05
        sigmas = [es.state.sigma]
        for _ in range(4):
            es.train(1, verbose=False)
            sigmas.append(es.state.sigma)
        np.testing.assert_allclose(sigmas, [0.05, 0.025, 0.0125, 0.01, 0.01], rtol=1e-6)

    def test_record_reports_decaying_sigma(self):
        es = _make(sigma_decay=0.5)
        es.train(2, verbose=False)
        assert es.history[0]["sigma"] == pytest.approx(0.05)
        assert es.history[1]["sigma"] == pytest.approx(0.025)

    def test_decayed_sigma_survives_checkpoint(self, tmp_path):
        from estorch_tpu.utils import restore_checkpoint, save_checkpoint

        ref = _make(sigma_decay=0.5)
        ref.train(4, verbose=False)

        a = _make(sigma_decay=0.5)
        a.train(2, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))
        b = _make(sigma_decay=0.5)
        restore_checkpoint(b, str(tmp_path / "ck"))
        assert b.state.sigma == pytest.approx(0.0125)
        b.train(2, verbose=False)
        np.testing.assert_array_equal(ref.state.params_flat, b.state.params_flat)


class TestHostUnmirrored:
    """The reference's PLAIN per-member sampling (no antithetic pairs) on
    the parity backend — mirroring stays the default."""

    def test_learns_quadratic(self):
        es = _make(mirrored=False)
        es.train(40, verbose=False)
        assert es.history[-1]["reward_max"] > 0.5 * es.history[0]["reward_max"]

    def test_deterministic_same_seed(self):
        a = _make(mirrored=False)
        a.train(3, verbose=False)
        b = _make(mirrored=False)
        b.train(3, verbose=False)
        np.testing.assert_array_equal(a.state.params_flat, b.state.params_flat)

    def test_differs_from_mirrored(self):
        a = _make(mirrored=False)
        a.train(1, verbose=False)
        b = _make()
        b.train(1, verbose=False)
        assert not np.array_equal(a.state.params_flat, b.state.params_flat)

    def test_odd_population_allowed(self):
        es = _make(mirrored=False, pop=7)
        es.train(1, verbose=False)
        assert len(es.history) == 1

    def test_member_theta_matches_evaluated(self):
        """member_params(i) must be the exact θ whose fitness was recorded."""
        es = _make(mirrored=False, pop=8)
        st = es.state
        ev = es.engine.evaluate(st)
        theta3 = es.engine.member_params(st, 3)
        policy = es.engine.policy_factory()
        es.engine._load(policy, theta3)
        r = QuadraticAgent().rollout(policy)
        assert r == pytest.approx(float(ev.fitness[3]), rel=1e-6)

    def test_process_mode_matches_thread_mode(self):
        a = _make(mirrored=False)
        a.train(2, n_proc=2, verbose=False)
        b = _make(mirrored=False, worker_mode="process")
        b.train(2, n_proc=2, verbose=False)
        np.testing.assert_allclose(
            a.state.params_flat, b.state.params_flat, rtol=1e-6, atol=1e-7
        )
        b.engine.close()


class TestHostNovelty:
    def test_ns_es_on_host(self):
        es = _make(agent_cls=QuadraticBCAgent, cls=NS_ES,
                   meta_population_size=2, k=3)
        es.train(3, verbose=False)
        assert es.backend == "host"
        assert len(es.archive) == 2 + 3
        assert len(es.history) == 3

    def test_nsra_es_on_host(self):
        es = _make(agent_cls=QuadraticBCAgent, cls=NSRA_ES,
                   meta_population_size=2, k=3, weight=0.7)
        es.train(2, verbose=False)
        assert "nsra_weight" in es.history[-1]

    def test_meta_centers_distinct_on_host(self):
        es = _make(agent_cls=QuadraticBCAgent, cls=NS_ES,
                   meta_population_size=3, k=3)
        p0 = es.meta_states[0].params_flat
        p1 = es.meta_states[1].params_flat
        assert not np.array_equal(p0, p1)


class TestHostTorchVBN:
    def test_vbn_freezes_on_first_batch(self):
        from estorch_tpu.models import TorchVirtualBatchNorm

        vbn = TorchVirtualBatchNorm(4)
        ref = torch.randn(32, 4) * 5 + 2
        out1 = vbn(ref)
        # frozen: different input later, same stats
        mean_after_ref = vbn.ref_mean.clone()
        _ = vbn(torch.randn(8, 4) * 100)
        torch.testing.assert_close(vbn.ref_mean, mean_after_ref)
        # reference batch is normalized to ~zero mean / unit var
        assert abs(float(out1.mean())) < 0.1
        assert abs(float(out1.var()) - 1.0) < 0.2

    def test_gradient_flows_through_affine_only_params(self):
        from estorch_tpu.models import TorchVirtualBatchNorm

        vbn = TorchVirtualBatchNorm(4)
        params = list(vbn.parameters())
        assert len(params) == 2  # scale, bias — stats are buffers

    def test_uninitialized_single_obs_raises(self):
        """Freezing stats from one observation (var=0) must be refused."""
        from estorch_tpu.models import TorchVirtualBatchNorm

        vbn = TorchVirtualBatchNorm(4)
        with pytest.raises(RuntimeError, match="set_reference"):
            vbn(torch.randn(4))


class TestProcessWorkers:
    def test_process_mode_matches_thread_mode(self):
        """Same seed: fork-based workers must produce identical params to
        thread workers (deterministic fitness; layout member-indexed)."""
        a = _make()
        a.train(3, n_proc=2, verbose=False)
        b = _make(worker_mode="process")
        b.train(3, n_proc=2, verbose=False)
        np.testing.assert_allclose(
            a.state.params_flat, b.state.params_flat, rtol=1e-6, atol=1e-7
        )
        b.engine.close()

    def test_process_mode_survives_member_exception(self):
        class SometimesFails(QuadraticAgent):
            def rollout(self, policy):
                # deterministic: each worker's 3rd rollout fails
                self._n = getattr(self, "_n", 0) + 1
                if self._n == 3:
                    raise RuntimeError("boom")
                return super().rollout(policy)

        es = _make(agent_cls=SometimesFails, worker_mode="process")
        es.train(2, n_proc=2, verbose=False)  # must not raise
        assert len(es.history) == 2
        es.engine.close()

    def test_process_workers_carry_master_buffers(self):
        """Forked workers must inherit master BUFFERS (frozen VBN stats) —
        vector_to_parameters only syncs parameters (regression)."""
        from estorch_tpu.models import TorchVirtualBatchNorm

        class VBNPolicy(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.l1 = torch.nn.Linear(4, 8)
                self.vbn = TorchVirtualBatchNorm(8)
                self.l2 = torch.nn.Linear(8, 2)

            def forward(self, x):
                return self.l2(torch.tanh(self.vbn(self.l1(x))))

        class VBNAgent:
            def rollout(self, policy):
                with torch.no_grad():
                    out = policy(torch.zeros(3, 4))  # batched: VBN must be frozen
                    return -float((out**2).sum())

        es = ES(VBNPolicy, VBNAgent, torch.optim.Adam, population_size=8,
                sigma=0.05, seed=0, optimizer_kwargs={"lr": 1e-2},
                table_size=1 << 12, worker_mode="process")
        es.engine.freeze_vbn(torch.randn(32, 4).numpy())
        es.train(2, n_proc=2, verbose=False)
        # every member must have evaluated (no NaN-from-uninitialized-VBN)
        assert es.history[-1]["n_failed"] == 0
        es.engine.close()

    def test_straggler_timeout_nans_slice_without_desync(self, tmp_path):
        """EXACTLY one worker exceeds proc_timeout_s (file-claim makes it
        deterministic): its slice is NaN'd that generation, and its LATE
        reply must be discarded — the next evaluation's fitness must equal
        the analytic values for the CURRENT thetas (sequence tags)."""
        import time as _time

        flag = str(tmp_path / "slow_claim")
        open(flag, "w").close()

        class SlowOnceAgent(QuadraticAgent):
            def rollout(self, policy):
                import os

                try:  # atomic claim: exactly one process sleeps, exactly once
                    os.rename(flag, flag + ".claimed")
                    _time.sleep(1.5)
                except OSError:
                    pass
                return super().rollout(policy)

        es = _make(agent_cls=SlowOnceAgent, worker_mode="process", pop=8)
        es.engine.proc_timeout_s = 0.4  # shorter than the sleep
        es.train(1, n_proc=2, verbose=False)
        assert es.history[0]["n_failed"] == 4  # one worker's slice dropped

        # the straggler's stale gen-1 reply is (or soon will be) queued in
        # its pipe; the next evaluation's drain must discard it and return
        # fresh values for the CURRENT state — verified analytically
        es.engine.proc_timeout_s = 30.0
        ev = es.engine.evaluate(es.state)
        expected = np.array(
            [
                -float(((es.engine.member_theta(es.state, i) - 0.1) ** 2).sum())
                for i in range(8)
            ],
            np.float32,
        )
        np.testing.assert_allclose(ev.fitness, expected, rtol=1e-4, atol=1e-5)
        es.engine.close()

    def test_worker_mode_rejected_on_device_path(self):
        import optax

        from estorch_tpu import JaxAgent, MLPPolicy
        from estorch_tpu.envs import CartPole

        with pytest.raises(ValueError, match="worker_mode"):
            ES(MLPPolicy, JaxAgent, optax.adam, population_size=16,
               policy_kwargs={"action_dim": 2},
               agent_kwargs={"env": CartPole()},
               optimizer_kwargs={"learning_rate": 1e-2},
               table_size=1 << 14, worker_mode="process")


class TestHostOptimizerIsolation:
    def test_meta_centers_do_not_share_adam_moments(self):
        """Interleaving updates of two states must not change either's result
        (the reference's single-policy flow never hits this; the novelty
        meta-population does)."""
        es = _make()
        eng = es.engine
        sA = es.state
        sB = eng.init_state(sA.params_flat + 0.3, key=123)
        w = np.linspace(-0.5, 0.5, 32).astype(np.float32)

        # sequence 1: A updated twice in a row
        a1, _ = eng.apply_weights(sA, w)
        a2, _ = eng.apply_weights(a1, w)

        # sequence 2: B's update interleaved between A's two updates
        a1b, _ = eng.apply_weights(sA, w)
        _ = eng.apply_weights(sB, w)
        a2b, _ = eng.apply_weights(a1b, w)

        np.testing.assert_array_equal(a2.params_flat, a2b.params_flat)
