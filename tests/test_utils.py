"""Aux subsystems: checkpoint/resume exactness, metrics writers, fault
tolerance, profiler timing (SURVEY.md §5)."""

import os

import numpy as np
import optax
import pytest
import torch

from estorch_tpu import ES, NSRA_ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole
from estorch_tpu.utils import (
    JsonlWriter,
    MultiWriter,
    PeriodicCheckpointer,
    mask_and_renormalize,
    rank_weights_with_failures,
    restore_checkpoint,
    save_checkpoint,
    timed_generations,
    valid_mask,
)


def _device_es(**over):
    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=16,
        sigma=0.1,
        seed=3,
        policy_kwargs={"action_dim": 2, "hidden": (8,)},
        agent_kwargs={"env": CartPole(), "horizon": 50},
        optimizer_kwargs={"learning_rate": 1e-2},
        table_size=1 << 16,
    )
    kw.update(over)
    cls = kw.pop("cls", ES)
    return cls(**kw)


class TestCheckpointDevice:
    def test_resume_is_exact(self, tmp_path):
        """Train 4; checkpoint at 2; restore into a fresh object; resume 2
        more — params must be IDENTICAL to the uninterrupted run."""
        ref = _device_es()
        ref.train(4, verbose=False)

        a = _device_es()
        a.train(2, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))

        b = _device_es()
        restore_checkpoint(b, str(tmp_path / "ck"))
        assert b.generation == 2
        b.train(2, verbose=False)

        np.testing.assert_array_equal(
            np.asarray(ref.state.params_flat), np.asarray(b.state.params_flat)
        )
        assert int(b.state.generation) == 4

    def test_history_survives_resume(self, tmp_path):
        """Per-generation records must come back (ADVICE round 1): a resumed
        run's logs continue from the interruption point, not from scratch."""
        a = _device_es()
        a.train(3, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))
        b = _device_es()
        restore_checkpoint(b, str(tmp_path / "ck"))
        assert len(b.history) == 3
        assert [r["generation"] for r in b.history] == [0, 1, 2]
        assert b.history[2]["reward_max"] == a.history[2]["reward_max"]
        b.train(1, verbose=False)
        assert [r["generation"] for r in b.history] == [0, 1, 2, 3]

    def test_best_snapshot_restored(self, tmp_path):
        a = _device_es()
        a.train(3, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))
        b = _device_es()
        restore_checkpoint(b, str(tmp_path / "ck"))
        assert b.best_reward == a.best_reward
        np.testing.assert_array_equal(b._best_flat, a._best_flat)

    def test_nsra_archive_and_weight_restored(self, tmp_path):
        a = _device_es(cls=NSRA_ES, meta_population_size=2, k=3, weight=0.6)
        a.train(3, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))

        b = _device_es(cls=NSRA_ES, meta_population_size=2, k=3, weight=0.6)
        restore_checkpoint(b, str(tmp_path / "ck"))
        assert len(b.archive) == len(a.archive)
        np.testing.assert_allclose(b.archive.bcs, a.archive.bcs)
        assert b.weight == a.weight
        assert b._stagnation == a._stagnation
        for sa, sb in zip(a.meta_states, b.meta_states):
            np.testing.assert_array_equal(
                np.asarray(sa.params_flat), np.asarray(sb.params_flat)
            )

    @pytest.mark.slow
    def test_novelty_resume_is_exact(self, tmp_path):
        """Regression: the meta-selection RNG position must be checkpointed —
        without it the resumed run picks different meta-individuals."""
        def mk():
            return _device_es(cls=NSRA_ES, meta_population_size=2, k=3, weight=0.8)

        ref = mk()
        ref.train(5, verbose=False)

        a = mk()
        a.train(3, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))
        b = mk()
        restore_checkpoint(b, str(tmp_path / "ck"))
        b.train(2, verbose=False)

        np.testing.assert_array_equal(
            np.asarray(ref.state.params_flat), np.asarray(b.state.params_flat)
        )
        # history is restored too, so b's records 3: are the post-resume ones
        assert [r["meta_index"] for r in ref.history[3:]] == [
            r["meta_index"] for r in b.history[3:]
        ]

    def test_backend_mismatch_rejected(self, tmp_path):
        a = _device_es()
        a.train(1, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))

        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.l = torch.nn.Linear(4, 2)

            def forward(self, x):
                return self.l(x)

        class A:
            def rollout(self, policy):
                return 0.0

        host = ES(P, A, torch.optim.Adam, population_size=16,
                  optimizer_kwargs={"lr": 1e-2}, table_size=1 << 14)
        with pytest.raises(Exception):
            restore_checkpoint(host, str(tmp_path / "ck"))


class TestCheckpointHost:
    def _host_es(self):
        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.l = torch.nn.Linear(4, 2)

            def forward(self, x):
                return self.l(x)

        class A:
            def rollout(self, policy):
                with torch.no_grad():
                    v = torch.nn.utils.parameters_to_vector(policy.parameters())
                    return -float(((v - 0.1) ** 2).sum())

        return ES(P, A, torch.optim.Adam, population_size=16, sigma=0.05,
                  seed=1, optimizer_kwargs={"lr": 0.05}, table_size=1 << 14)

    def test_host_resume_is_exact(self, tmp_path):
        ref = self._host_es()
        ref.train(4, verbose=False)

        a = self._host_es()
        a.train(2, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))
        b = self._host_es()
        restore_checkpoint(b, str(tmp_path / "ck"))
        b.train(2, verbose=False)
        np.testing.assert_allclose(
            ref.state.params_flat, b.state.params_flat, rtol=1e-6, atol=1e-7
        )


class TestCheckpointPooled:
    def test_pooled_resume_is_exact(self, tmp_path):
        from estorch_tpu import PooledAgent

        def mk():
            return _device_es(
                agent=PooledAgent,
                agent_kwargs={"env_name": "cartpole", "horizon": 40},
                seed=2,
                table_size=1 << 14,
            )

        a = mk()
        a.train(2, verbose=False)
        save_checkpoint(a, str(tmp_path / "ck"))
        b = mk()
        restore_checkpoint(b, str(tmp_path / "ck"))
        assert b.generation == 2
        np.testing.assert_array_equal(
            np.asarray(a.state.params_flat), np.asarray(b.state.params_flat)
        )
        b.train(1, verbose=False)  # must run cleanly from the restored state
        assert b.generation == 3


class TestPeriodicCheckpointer:
    def test_every_k_and_gc(self, tmp_path):
        es = _device_es()
        ck = PeriodicCheckpointer(es, str(tmp_path / "cks"), every=2, max_to_keep=2)
        es.train(6, log_fn=ck.on_record)
        kept = sorted(os.listdir(tmp_path / "cks"))
        assert len(kept) == 2  # gens 1,3,5 saved; oldest GC'd
        assert ck.latest().endswith(kept[-1])


class TestMetricsWriters:
    def test_jsonl_roundtrip(self, tmp_path):
        path = str(tmp_path / "log.jsonl")
        w = JsonlWriter(path)
        es = _device_es()
        es.train(3, log_fn=w)
        w.close()
        recs = JsonlWriter.read(path)
        assert len(recs) == 3
        assert recs[0]["generation"] == 0
        assert "env_steps_per_sec" in recs[-1]

    def test_multi_writer_fans_out(self, tmp_path):
        seen = []
        w = MultiWriter([seen.append, JsonlWriter(str(tmp_path / "l.jsonl"))])
        w({"generation": 0, "reward_max": 1.0, "reward_mean": 0.5,
           "env_steps_per_sec": 100.0})
        assert len(seen) == 1
        w.close()


class TestFaultTolerance:
    def test_valid_mask(self):
        f = np.array([1.0, np.nan, 3.0, np.inf])
        np.testing.assert_array_equal(valid_mask(f), [True, False, True, False])

    def test_mask_and_renormalize_unbiased_scale(self):
        w = np.array([0.5, -0.5, 0.25, -0.25], np.float32)
        valid = np.array([True, True, True, False])
        out = mask_and_renormalize(w, valid)
        assert out[3] == 0.0
        np.testing.assert_allclose(out[:3], w[:3] * (4 / 3), rtol=1e-6)

    def test_too_few_survivors_raises(self):
        with pytest.raises(RuntimeError, match="valid fitness"):
            mask_and_renormalize(np.ones(4, np.float32), np.array([True] + [False] * 3))

    def test_rank_weights_with_failures(self):
        f = np.array([3.0, np.nan, 1.0, 2.0], np.float32)
        w = rank_weights_with_failures(f)
        assert w[1] == 0.0
        # valid members ranked among themselves, renormalized by 4/3
        from estorch_tpu.ops import centered_rank_np

        expected = np.zeros(4, np.float32)
        expected[[0, 2, 3]] = centered_rank_np(f[[0, 2, 3]]) * (4 / 3)
        np.testing.assert_allclose(w, expected, rtol=1e-6)

    def test_host_engine_survives_worker_exception(self):
        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.l = torch.nn.Linear(2, 1)

            def forward(self, x):
                return self.l(x)

        class FlakyAgent:
            calls = 0

            def rollout(self, policy):
                FlakyAgent.calls += 1
                if FlakyAgent.calls % 5 == 0:
                    raise RuntimeError("env crashed")
                with torch.no_grad():
                    v = torch.nn.utils.parameters_to_vector(policy.parameters())
                    return -float((v**2).sum())

        es = ES(P, FlakyAgent, torch.optim.Adam, population_size=16,
                optimizer_kwargs={"lr": 1e-2}, table_size=1 << 12)
        es.train(2, verbose=False)  # must not raise
        assert len(es.history) == 2
        # failed members are NaN-masked: stats stay finite, failures counted,
        # and best tracking still works
        rec = es.history[-1]
        assert np.isfinite(rec["reward_mean"])
        assert np.isfinite(rec["reward_max"])
        assert rec["n_failed"] > 0
        assert np.isfinite(es.best_reward)
        assert es._best_flat is not None

    def test_novelty_weights_drop_failed_members(self):
        """A NaN-fitness member must get zero weight, not the top rank."""
        from estorch_tpu import NS_ES, JaxAgent, MLPPolicy
        from estorch_tpu.envs import CartPole
        import optax

        es = NS_ES(
            MLPPolicy, JaxAgent, optax.adam, population_size=16, sigma=0.1,
            seed=0, meta_population_size=2, k=3,
            policy_kwargs={"action_dim": 2, "hidden": (8,)},
            agent_kwargs={"env": CartPole(), "horizon": 20},
            optimizer_kwargs={"learning_rate": 1e-2}, table_size=1 << 14,
        )
        fitness = np.array([1.0, np.nan, 3.0, 2.0] * 4, np.float32)
        novelty = np.linspace(0, 1, 16).astype(np.float32)
        w = es._weights_with_failures(fitness, novelty)
        failed = np.isnan(fitness)
        assert np.all(w[failed] == 0.0)
        assert np.isfinite(w).all()
        assert abs(float(w.sum())) < 1e-4  # renormalized centered ranks still ~sum 0


class TestProfiler:
    def test_timed_generations(self):
        es = _device_es()
        stats = timed_generations(es, n=2, warmup=1)
        assert stats["generations"] == 2
        assert stats["env_steps"] > 0
        assert stats["env_steps_per_sec"] > 0
        assert stats["compile_time_s"] is not None

    @pytest.mark.slow
    def test_trace_writes_profile(self, tmp_path):
        from estorch_tpu.utils import annotate, trace

        es = _device_es()
        es.train(1, verbose=False)  # compile outside the trace
        with trace(str(tmp_path / "prof")):
            with annotate("generation"):
                es.train(1, verbose=False)
        written = list((tmp_path / "prof").rglob("*"))
        assert any(p.is_file() for p in written), "no trace files emitted"


class TestCompilationCache:
    def test_enable_compilation_cache_persists_executables(self, tmp_path):
        """enable_compilation_cache points XLA's persistent cache at the
        directory and compiled programs actually land there (the 20-40s
        fresh-process compile is what the cache exists to kill)."""
        import jax
        import jax.numpy as jnp

        from estorch_tpu.utils import enable_compilation_cache

        cache_dir = str(tmp_path / "xla")
        got = enable_compilation_cache(cache_dir, min_compile_time_s=0.0)
        assert got == cache_dir
        try:
            @jax.jit
            def f(x):
                return (x @ x.T).sum()

            f(jnp.ones((64, 64))).block_until_ready()
            import os

            entries = os.listdir(cache_dir)
            assert entries, "no cache entries written"
        finally:
            # restore defaults so later tests don't write into tmp_path —
            # the config alone is not enough: JAX pins the cache object on
            # first use, so it must be reset too
            jax.config.update("jax_compilation_cache_dir", None)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
            from estorch_tpu.utils.backend import _reset_live_cache

            _reset_live_cache()

    def test_default_dir_created(self, monkeypatch, tmp_path):
        import jax

        from estorch_tpu.utils import enable_compilation_cache

        monkeypatch.setenv("HOME", str(tmp_path))
        try:
            d = enable_compilation_cache()
            assert d.startswith(str(tmp_path))
            import os

            assert os.path.isdir(d)
        finally:
            jax.config.update("jax_compilation_cache_dir", None)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
            from estorch_tpu.utils.backend import _reset_live_cache

            _reset_live_cache()


class TestAsyncCheckpoint:
    @pytest.mark.slow
    def test_async_save_restores_bit_exact(self, tmp_path):
        import optax

        from estorch_tpu import ES, JaxAgent, MLPPolicy
        from estorch_tpu.envs import CartPole
        from estorch_tpu.utils import restore_checkpoint, save_checkpoint

        def build():
            return ES(
                policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
                population_size=16, sigma=0.1,
                policy_kwargs={"action_dim": 2, "hidden": (8,),
                               "discrete": True},
                agent_kwargs={"env": CartPole(), "horizon": 32},
                optimizer_kwargs={"learning_rate": 1e-2}, seed=3,
            )

        es = build()
        es.train(2, verbose=False)
        handle = save_checkpoint(es, tmp_path / "ck", asynchronous=True)
        # training continues while the write drains in the background —
        # the save must snapshot the state AT save time, not pick up these
        # later updates
        es.train(2, verbose=False)
        handle.wait()
        handle.wait()  # idempotent

        es2 = build()
        restore_checkpoint(es2, tmp_path / "ck")
        assert es2.generation == 2
        es_ref = build()
        es_ref.train(2, verbose=False)
        np.testing.assert_array_equal(
            np.asarray(es2.state.params_flat),
            np.asarray(es_ref.state.params_flat),
        )

    def test_periodic_async_resume_exact(self, tmp_path):
        from estorch_tpu.utils import PeriodicCheckpointer, restore_checkpoint

        es = _device_es()
        ck = PeriodicCheckpointer(es, str(tmp_path / "cks"), every=2,
                                  max_to_keep=2, asynchronous=True)
        es.train(4, log_fn=ck.on_record)
        ck.wait()
        b = _device_es()
        restore_checkpoint(b, ck.latest())
        assert b.generation == 4
        np.testing.assert_array_equal(
            np.asarray(es.state.params_flat), np.asarray(b.state.params_flat)
        )

    def test_restore_unfinalized_dir_clear_error(self, tmp_path):
        """restore_checkpoint on an in-flight/crash-truncated async save
        (meta.json present, no finalized state/) must raise a clear
        'not finalized' error BEFORE handing the path to Orbax
        (round-3 ADVICE #2)."""
        import shutil

        import pytest

        from estorch_tpu.utils import restore_checkpoint, save_checkpoint

        es = _device_es()
        es.train(1, verbose=False)
        save_checkpoint(es, str(tmp_path / "ck"))
        # simulate the crash-truncated async save: meta/history written,
        # Orbax payload never finalized
        shutil.rmtree(tmp_path / "ck" / "state")
        b = _device_es()
        with pytest.raises(ValueError, match="no finalized state"):
            restore_checkpoint(b, str(tmp_path / "ck"))

    def test_latest_skips_unfinalized_dir(self, tmp_path):
        """A crash mid-async-drain leaves meta.json without a finalized
        Orbax state/ — latest() must fall back to the older restorable
        checkpoint instead of handing restore a partial one."""
        from estorch_tpu.utils import PeriodicCheckpointer

        es = _device_es()
        es.train(2, verbose=False)
        ck = PeriodicCheckpointer(es, str(tmp_path / "cks"), every=1)
        good = ck.save(1)
        # simulate the partial newer checkpoint
        partial = os.path.join(str(tmp_path / "cks"), "gen_00000099")
        os.makedirs(partial)
        open(os.path.join(partial, "meta.json"), "w").write("{}")
        assert ck.latest() == good

    def test_async_gc_deferred_until_durable(self, tmp_path):
        """With max_to_keep=1 the old checkpoint must survive until the
        new async save has drained (GC runs in wait(), not at launch)."""
        from estorch_tpu.utils import PeriodicCheckpointer

        es = _device_es()
        es.train(1, verbose=False)
        ck = PeriodicCheckpointer(es, str(tmp_path / "cks"), every=1,
                                  max_to_keep=1, asynchronous=True)
        ck.save(0)
        ck.wait()
        first = ck.latest()
        assert first is not None
        ck.save(1)
        # in-flight: the only durable checkpoint must still exist
        assert os.path.isdir(os.path.join(first, "state"))
        ck.close()
        kept = sorted(os.listdir(tmp_path / "cks"))
        assert kept == ["gen_00000001"]
