"""Low-rank perturbations (ops/lowrank.py + engine low_rank path).

Covers: noise statistics (zero-mean, unit variance of E entries), the
update reduction vs a direct dense oracle, forward equivalence vs a
materialized dense perturbation, mirrored-pair antithesis, 8-dev == 1-dev
invariance, member_params consistency, and end-to-end learnability.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole, Pendulum
from estorch_tpu.ops.lowrank import (
    lowrank_noise_tree,
    lowrank_weighted_sum,
    make_lowrank_spec,
)


def _mlp_params(key, dims=(6, 8, 3)):
    """MLPPolicy-shaped param tree {dense_0.., head: {kernel, bias}}."""
    names = [f"dense_{i}" for i in range(len(dims) - 2)] + ["head"]
    params = {}
    for i, name in enumerate(names):
        k1, key = jax.random.split(key)
        params[name] = {
            "kernel": jax.random.normal(k1, (dims[i], dims[i + 1])),
            "bias": jnp.zeros((dims[i + 1],)),
        }
    return params


class TestSpec:
    def test_layout_and_dims(self):
        params = _mlp_params(jax.random.key(0), dims=(6, 8, 3))
        spec = make_lowrank_spec(params, rank=2)
        # kernels: (6+8)*2 + (8+3)*2 = 50; biases: 8 + 3 = 11
        assert spec.noise_dim == 50 + 11
        unpacked = spec.unpack(jnp.arange(spec.noise_dim, dtype=jnp.float32))
        a, b, nb = unpacked["dense_0"]
        assert a.shape == (6, 2) and b.shape == (8, 2) and nb.shape == (8,)
        a, b, nb = unpacked["head"]
        assert a.shape == (8, 2) and b.shape == (3, 2) and nb.shape == (3,)

    def test_dense_fallback_when_rank_not_low(self):
        """rank ≥ min(m, n) layers get exact dense noise (same size, exact
        Gaussian) instead of a fake low-rank factorization."""
        params = _mlp_params(jax.random.key(0), dims=(6, 8, 3))
        spec = make_lowrank_spec(params, rank=3)  # head is 8x3 → dense
        assert [l[0] for l in spec.lr_layers] == ["dense_0"]
        assert [l[0] for l in spec.dense_layers] == ["head"]
        # dense_0: (6+8)*3 = 42; head dense: 8*3 = 24; biases: 8+3 = 11
        assert spec.noise_dim == 42 + 24 + 11
        unpacked = spec.unpack(jnp.arange(spec.noise_dim, dtype=jnp.float32))
        e, none_marker, nb = unpacked["head"]
        assert none_marker is None
        assert e.shape == (8, 3) and nb.shape == (3,)

    def test_unit_variance_entries(self):
        """Dense E entries must be ~N(0,1)-moment-matched for σ to keep its
        full-rank meaning."""
        params = _mlp_params(jax.random.key(0), dims=(32, 32, 16))
        spec = make_lowrank_spec(params, rank=4)
        vals = []
        for s in range(200):
            noise = jax.random.normal(jax.random.key(s), (spec.noise_dim,))
            dense = lowrank_noise_tree(spec, noise)
            vals.append(np.asarray(dense["dense_0"]["kernel"]).ravel())
        flat = np.concatenate(vals)
        assert abs(flat.mean()) < 0.01
        assert abs(flat.var() - 1.0) < 0.05


class TestUpdateReduction:
    def test_weighted_sum_matches_dense_oracle(self):
        params = _mlp_params(jax.random.key(1), dims=(5, 7, 2))
        spec = make_lowrank_spec(params, rank=1)
        k = 9
        noise = jax.random.normal(jax.random.key(2), (k, spec.noise_dim))
        w = jax.random.normal(jax.random.key(3), (k,))
        got = lowrank_weighted_sum(spec, noise, w)
        # oracle: materialize every member's dense tree and sum
        for name in ("dense_0", "head"):
            want_k = sum(
                float(w[i]) * np.asarray(lowrank_noise_tree(spec, noise[i])[name]["kernel"])
                for i in range(k)
            )
            np.testing.assert_allclose(
                np.asarray(got[name]["kernel"]), want_k, rtol=1e-5, atol=1e-5
            )
            want_b = sum(
                float(w[i]) * np.asarray(lowrank_noise_tree(spec, noise[i])[name]["bias"])
                for i in range(k)
            )
            np.testing.assert_allclose(
                np.asarray(got[name]["bias"]), want_b, rtol=1e-5, atol=1e-5
            )


class TestForward:
    def test_lowrank_apply_matches_materialized_dense(self):
        """mlp_lowrank_apply == MLPPolicy.apply with W + c·dense(E)."""
        from estorch_tpu.models.decomposed import mlp_lowrank_apply

        module = MLPPolicy(action_dim=3, hidden=(8,), discrete=True)
        obs = jax.random.normal(jax.random.key(0), (6,))
        variables = module.init(jax.random.key(1), obs)
        params = variables["params"]
        spec = make_lowrank_spec(params, rank=2)
        noise = jax.random.normal(jax.random.key(2), (spec.noise_dim,))
        c = 0.13

        got = mlp_lowrank_apply(module, params, spec.unpack(noise), c, obs)

        dense = lowrank_noise_tree(spec, noise)
        perturbed = jax.tree_util.tree_map(
            lambda p, e: p + c * e, params, dense
        )
        want = module.apply({"params": perturbed}, obs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def _make_es(n_pop=16, seed=7, rank=1, **kw):
    return ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=n_pop,
        sigma=0.1,
        seed=seed,
        policy_kwargs={"action_dim": 2, "hidden": (8,)},
        agent_kwargs={"env": CartPole(), "horizon": 50},
        optimizer_kwargs={"learning_rate": 1e-2},
        table_size=1 << 15,
        low_rank=rank,
        **kw,
    )


class TestEngineIntegration:
    def test_trains_and_history_sane(self):
        es = _make_es()
        es.train(2, verbose=False)
        assert len(es.history) == 2
        assert np.isfinite(es.history[-1]["reward_mean"])

    def test_mesh_invariance(self):
        """8 virtual devices must produce the identical update as 1."""
        from estorch_tpu.parallel.mesh import population_mesh

        es8 = _make_es()
        mesh1 = population_mesh(jax.devices()[:1])
        es1 = _make_es(mesh=mesh1)
        es8.train(2, verbose=False)
        es1.train(2, verbose=False)
        np.testing.assert_allclose(
            np.asarray(es8.state.params_flat),
            np.asarray(es1.state.params_flat),
            rtol=0, atol=1e-6,
        )

    def test_member_params_match_evaluated_member(self):
        """member_params(i) must rebuild exactly the θ_i the rollout saw:
        evaluate member i's reconstructed params and compare fitness."""
        es = _make_es(n_pop=16)
        res = es.engine.evaluate(es.state)
        fitness = np.asarray(res.fitness)
        i = int(np.argmax(fitness))
        theta = es.engine.member_params(es.state, i)

        from estorch_tpu.envs.rollout import make_rollout

        okey, rkey = jax.random.fold_in(
            jax.random.fold_in(es.state.key, es.state.generation), 0
        ), jax.random.fold_in(
            jax.random.fold_in(es.state.key, es.state.generation), 1
        )
        pair_keys = jax.random.split(rkey, 8)
        key_i = jnp.repeat(pair_keys, 2, axis=0)[i]
        rollout = make_rollout(es.env, es._policy_apply, 50)
        res_i = rollout(es._spec.unravel(theta), key_i)
        assert float(res_i.total_reward) == pytest.approx(fitness[i], abs=1e-4)

    def test_unmirrored_mode(self):
        es = _make_es(mirrored=False)
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])

    def test_rejected_on_host_and_pooled(self):
        import torch

        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = torch.nn.Linear(2, 2)

            def forward(self, x):
                return self.lin(x)

        class A:
            def rollout(self, policy):
                return 0.0

        with pytest.raises(ValueError, match="low_rank"):
            ES(P, A, torch.optim.Adam, population_size=4, low_rank=1)

        from estorch_tpu import PooledAgent

        with pytest.raises(ValueError, match="low_rank"):
            ES(
                policy=MLPPolicy,
                agent=PooledAgent,
                optimizer=optax.adam,
                population_size=16,
                policy_kwargs={"action_dim": 2, "hidden": (8,)},
                agent_kwargs={"env_name": "cartpole", "horizon": 20},
                optimizer_kwargs={"learning_rate": 1e-2},
                table_size=1 << 15,
                low_rank=1,
            )

    def test_mutually_exclusive_with_other_modes(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            _make_es(decomposed=True)

    def test_learnability_pendulum(self):
        """Rank-1 ES must still learn: Pendulum mean return improves."""
        env = Pendulum()
        es = ES(
            policy=MLPPolicy,
            agent=JaxAgent,
            optimizer=optax.adam,
            population_size=256,
            sigma=0.1,
            seed=0,
            policy_kwargs={"action_dim": 1, "hidden": (16, 16),
                           "discrete": False, "action_scale": 2.0},
            agent_kwargs={"env": env, "horizon": 100},
            optimizer_kwargs={"learning_rate": 3e-2},
            table_size=1 << 17,
            low_rank=1,
        )
        es.train(15, verbose=False)
        first = es.history[0]["reward_mean"]
        last = max(r["reward_mean"] for r in es.history)
        # calibration: full-rank ES on this exact budget reaches ~+60; the
        # hyperscale claim is rank-1 ≈ full-rank, not rank-1 ≫ full-rank
        assert last > first + 40.0, (first, last)


class TestTreeSpec:
    """Generic pytree low-rank form (recurrent policies, round-5)."""

    def _params(self):
        key = jax.random.key(0)
        k1, k2, k3, k4 = jax.random.split(key, 4)
        return {
            "dense": {"kernel": jax.random.normal(k1, (12, 16)),
                      "bias": jax.random.normal(k2, (16,))},
            "cell": {"gate": {"kernel": jax.random.normal(k3, (16, 16))}},
            "head": {"kernel": jax.random.normal(k4, (16, 2))},
        }

    def test_layout(self):
        from estorch_tpu.ops.lowrank import make_lowrank_tree_spec

        p = self._params()
        spec = make_lowrank_tree_spec(p, 1)
        # factored: (12,16) and (16,16); dense: bias (1-D) and head
        # ((16+2)·1 ≥ 16·2 is false → 18 < 32, so head factors too)
        assert len(spec.lr_leaves) == 3
        assert len(spec.dense_leaves) == 1
        assert spec.noise_dim == (12 + 16) + (16 + 16) + (16 + 2) + 16

    def test_noise_tree_matches_perturb(self):
        from estorch_tpu.ops.lowrank import (lowrank_tree_noise,
                                             lowrank_tree_perturb,
                                             make_lowrank_tree_spec)

        p = self._params()
        spec = make_lowrank_tree_spec(p, 2)
        vec = jax.random.normal(jax.random.key(1), (spec.noise_dim,))
        noise = lowrank_tree_noise(spec, vec)
        pert = lowrank_tree_perturb(spec, p, vec, 0.3)
        jax.tree_util.tree_map(
            lambda w, e, t: np.testing.assert_allclose(
                np.asarray(w + 0.3 * e), np.asarray(t), rtol=1e-6
            ),
            p, noise, pert,
        )
        # factored kernel really is rank-2
        assert np.linalg.matrix_rank(np.asarray(noise["cell"]["gate"]["kernel"]),
                                     tol=1e-5) <= 2

    def test_weighted_sum_matches_dense_oracle(self):
        from estorch_tpu.ops.lowrank import (lowrank_tree_noise,
                                             lowrank_tree_weighted_sum,
                                             make_lowrank_tree_spec)

        p = self._params()
        spec = make_lowrank_tree_spec(p, 1)
        k = 5
        mat = jax.random.normal(jax.random.key(2), (k, spec.noise_dim))
        w = jax.random.normal(jax.random.key(3), (k,))
        got = lowrank_tree_weighted_sum(spec, mat, w)
        want = None
        for i in range(k):
            dense = lowrank_tree_noise(spec, mat[i])
            scaled = jax.tree_util.tree_map(lambda e: w[i] * e, dense)
            want = scaled if want is None else jax.tree_util.tree_map(
                jnp.add, want, scaled
            )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-5
            ),
            got, want,
        )
