"""Serving fleet: front router + fleet supervisor (docs/serving.md
"Fleet").

THE acceptance demo is chaos-driven: a 2-replica fleet under concurrent
load takes a DECLARED ``kill_replica`` SIGKILL (ESTORCH_CHAOS — the
same once-semantics ledger as training chaos) and loses ZERO client
answers: in-flight and follow-on requests retry onto the survivor
within the budget, the dead replica's breaker opens and re-closes, the
fleet respawns the corpse WARM (PR-12 bundles: ``compiles_at_load ==
0``), and a canary rollout carrying a deliberately-different bundle is
auto-rolled-back with the bit-parity (or tail-band) evidence in the
structured abort reason — while a same-params re-export promotes
fleet-wide.

Around the demo: router unit mechanics over stdlib toy replicas
(failover, budgeted retry, breaker state machine, hedging, trace
headers, drain), fleet.json validation, the chaos plan's wall-clock
serve events, the loadgen capacity sweep, and the jax-free file-run
probes (the sidecar/collector discipline).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from estorch_tpu.resilience.chaos import CHAOS_ENV, ChaosPlan
from estorch_tpu.serve.fleet import (Fleet, FleetError, load_fleet_config,
                                     validate_fleet_config)
from estorch_tpu.serve.loadgen import capacity_sweep, run_load
from estorch_tpu.serve.router import (BREAKER_CLOSED, BREAKER_HALF_OPEN,
                                      BREAKER_OPEN, CircuitBreaker,
                                      Router, parse_replica_spec)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# =====================================================================
# toy replicas (stdlib): the /predict //healthz //stats shapes
# =====================================================================

def make_toy_replica(*, delay_s: float = 0.0, fail: bool = False,
                     scale: float = 2.0):
    state = {"delay_s": delay_s, "fail": fail, "scale": scale,
             "requests": 0, "traces": []}

    class Toy(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _j(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._j(200, {"ok": True, "draining": False,
                              "queue_depth": 0})
            else:
                self._j(200, {"queue_depth": 0,
                              "request_ms": {"p99": 1.0}})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            data = json.loads(self.rfile.read(n))
            state["requests"] += 1
            trace = self.headers.get("X-Trace-Id")
            if trace:
                state["traces"].append(trace)
            if state["delay_s"]:
                time.sleep(state["delay_s"])
            if state["fail"]:
                self._j(500, {"error": "injected"})
                return
            self._j(200, {"action": [v * state["scale"]
                                     for v in data["obs"]]})

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Toy)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, state


def _post(url, payload, timeout=15):
    req = urllib.request.Request(url, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read()), dict(r.headers)


# =====================================================================
# circuit breaker state machine
# =====================================================================

class TestCircuitBreaker:
    def test_opens_after_consecutive_failures(self):
        b = CircuitBreaker(fail_threshold=3, open_s=60.0)
        assert b.allow() and b.state == BREAKER_CLOSED
        assert not b.record_failure()
        assert not b.record_failure()
        assert b.record_failure()  # third opens
        assert b.state == BREAKER_OPEN
        assert not b.allow()

    def test_success_resets_failure_streak(self):
        b = CircuitBreaker(fail_threshold=2, open_s=60.0)
        b.record_failure()
        b.record_success()
        assert not b.record_failure()  # streak restarted
        assert b.state == BREAKER_CLOSED

    def test_half_open_admits_one_probe(self):
        b = CircuitBreaker(fail_threshold=1, open_s=0.05)
        b.record_failure()
        assert b.state == BREAKER_OPEN and not b.allow()
        time.sleep(0.08)
        assert b.allow()  # the probe
        assert b.state == BREAKER_HALF_OPEN
        assert not b.allow()  # only one in flight
        b.record_success()
        assert b.state == BREAKER_CLOSED and b.allow()

    def test_half_open_failure_reopens(self):
        b = CircuitBreaker(fail_threshold=1, open_s=0.05)
        b.record_failure()
        time.sleep(0.08)
        assert b.allow()
        assert b.record_failure()  # the probe failed: re-open
        assert b.state == BREAKER_OPEN
        assert b.opens_total == 2


# =====================================================================
# chaos plan: wall-clock serve events
# =====================================================================

class TestChaosServeEvents:
    def test_serve_events_need_at_s(self):
        with pytest.raises(ValueError, match="at_s"):
            ChaosPlan([{"kind": "kill_replica", "replica": 0}])

    def test_gen_events_still_need_gen(self):
        with pytest.raises(ValueError, match="gen"):
            ChaosPlan([{"kind": "die"}])

    def test_due_and_once_semantics(self):
        plan = ChaosPlan([
            {"kind": "kill_replica", "at_s": 1.0, "replica": 1},
            {"kind": "wedge_replica", "at_s": 5.0, "replica": 0},
            {"kind": "die", "gen": 3},
        ])
        assert plan.serve_events_due(0.5) == []
        due = plan.serve_events_due(2.0)
        assert [e["kind"] for e in due] == ["kill_replica"]
        assert plan.serve_events_due(2.0) == []  # fired once
        due = plan.serve_events_due(9.0)
        assert [e["kind"] for e in due] == ["wedge_replica"]
        # generation-keyed events are untouched by the serve clock
        assert [e["kind"] for e in plan.events_at(3)] == ["die"]

    def test_ledger_shared_across_plans(self, tmp_path):
        ledger = str(tmp_path / "ledger")
        spec = [{"kind": "kill_replica", "at_s": 0.1, "replica": 0}]
        p1 = ChaosPlan(spec, ledger=ledger)
        assert len(p1.serve_events_due(1.0)) == 1
        # a restarted fleet parsing the same plan skips the fired event
        p2 = ChaosPlan(spec, ledger=ledger)
        assert p2.serve_events_due(1.0) == []

    def test_to_json_round_trip(self):
        plan = ChaosPlan([{"kind": "wedge_replica", "at_s": 2.5,
                           "replica": 1}])
        again = ChaosPlan.parse(plan.to_json())
        assert [e["kind"] for e in again.serve_events_due(3.0)] == \
            ["wedge_replica"]


# =====================================================================
# router mechanics over toy replicas
# =====================================================================

class TestRouterUnit:
    def _router(self, replicas, **kw):
        kw.setdefault("port", 0)
        kw.setdefault("poll_interval_s", 0.1)
        r = Router(replicas, **kw)
        r.start_background()
        return r

    def test_routes_and_traces(self):
        srv, state = make_toy_replica()
        router = self._router([("ra",
                                f"127.0.0.1:{srv.server_address[1]}")])
        try:
            time.sleep(0.25)
            url = f"http://{router.host}:{router.port}"
            out, hdrs = _post(url + "/predict", {"obs": [1.0, 2.0]})
            assert out["action"] == [2.0, 4.0]
            assert hdrs["X-Upstream"] == "ra"
            # the router's trace id reached the replica
            assert hdrs["X-Trace-Id"] in state["traces"]
            # a client-supplied id is honored, not replaced
            req = urllib.request.Request(
                url + "/predict", json.dumps({"obs": [1.0]}).encode(),
                {"Content-Type": "application/json",
                 "X-Trace-Id": "r-mine"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.headers["X-Trace-Id"] == "r-mine"
            assert "r-mine" in state["traces"]
        finally:
            router.shutdown(drain=False)
            srv.shutdown(), srv.server_close()

    def test_retry_on_different_replica_and_breaker(self):
        a, _ = make_toy_replica()
        b, bstate = make_toy_replica(scale=2.0)
        # poll slowly: health is STALE when a dies, so requests must hit
        # the corpse and fail over via the retry budget
        router = self._router(
            [("ra", f"127.0.0.1:{a.server_address[1]}"),
             ("rb", f"127.0.0.1:{b.server_address[1]}")],
            poll_interval_s=30.0)
        try:
            time.sleep(0.4)  # one poll: both healthy
            a.shutdown(), a.server_close()
            url = f"http://{router.host}:{router.port}"
            for i in range(8):
                out, _h = _post(url + "/predict", {"obs": [float(i)]})
                assert out["action"] == [2.0 * i]
            st = router.stats()
            assert st["counters"]["router_retries_total"] >= 1
            assert st["counters"]["router_breaker_opens_total"] >= 1
            breakers = {r["name"]: r["breaker"]
                        for r in st["replicas"]}
            assert breakers["ra"] == BREAKER_OPEN
            assert breakers["rb"] == BREAKER_CLOSED
        finally:
            router.shutdown(drain=False)
            b.shutdown(), b.server_close()

    def test_5xx_retries_and_no_healthy_is_503(self):
        a, _ = make_toy_replica(fail=True)
        b, _ = make_toy_replica(fail=True)
        router = self._router(
            [("ra", f"127.0.0.1:{a.server_address[1]}"),
             ("rb", f"127.0.0.1:{b.server_address[1]}")],
            poll_interval_s=30.0, retry_budget=1)
        try:
            time.sleep(0.4)
            url = f"http://{router.host}:{router.port}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(url + "/predict", {"obs": [1.0]})
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert "no healthy upstream" in body["error"]
            assert router.counters.get("router_no_upstream_total") >= 1
        finally:
            router.shutdown(drain=False)
            for s in (a, b):
                s.shutdown(), s.server_close()

    def test_hedge_cuts_the_tail(self):
        slow, _ = make_toy_replica(delay_s=0.4)
        fast, _ = make_toy_replica()
        router = self._router(
            [("slow", f"127.0.0.1:{slow.server_address[1]}"),
             ("fast", f"127.0.0.1:{fast.server_address[1]}")],
            poll_interval_s=30.0, hedge=True, hedge_min_ms=60.0)
        try:
            time.sleep(0.4)
            url = f"http://{router.host}:{router.port}"
            hedged_upstreams = []
            for i in range(8):  # rr tiebreak: some land on the stall
                out, hdrs = _post(url + "/predict", {"obs": [float(i)]})
                assert out["action"] == [2.0 * i]
                hedged_upstreams.append(hdrs.get("X-Upstream"))
            c = router.counters
            assert c.get("router_hedged_total") >= 1
            assert c.get("router_hedge_wins_total") >= 1
            # the winner is attributed: a hedge win answers from 'fast'
            # even though the attempt STARTED on 'slow'
            assert hedged_upstreams.count("fast") > \
                hedged_upstreams.count("slow"), hedged_upstreams
            # a cancelled hedge loser is healthy-but-slow, NOT a death:
            # its breaker stays closed and it is charged no failures
            reps = {r.name: r for r in router.replicas()}
            assert reps["slow"].breaker.state == BREAKER_CLOSED
            assert reps["slow"].failures == 0, reps["slow"].snapshot()
        finally:
            router.shutdown(drain=False)
            for s in (slow, fast):
                s.shutdown(), s.server_close()

    def test_metrics_exposition_parses_with_replica_gauges(self):
        from estorch_tpu.obs.export.prometheus import parse_exposition

        srv, _ = make_toy_replica()
        router = self._router([("ra",
                                f"127.0.0.1:{srv.server_address[1]}")])
        try:
            time.sleep(0.25)
            url = f"http://{router.host}:{router.port}"
            _post(url + "/predict", {"obs": [1.0]})
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=10) as r:
                body = r.read().decode()
            parse_exposition(body)
            assert 'estorch_router_replica_up{replica="ra"} 1' in body
            assert 'estorch_router_breaker_state{replica="ra"} 0' in body
            assert "estorch_router_route_s_bucket" in body
            # the /stats collector-discovery stanza, like the server's
            with urllib.request.urlopen(url + "/stats", timeout=10) as r:
                st = json.loads(r.read())
            assert st["collector_target"]["url"].endswith("/metrics")
            assert str(router.port) in st["collector_target"]["url"]
        finally:
            router.shutdown(drain=False)
            srv.shutdown(), srv.server_close()

    def test_rollout_without_fleet_is_409(self):
        srv, _ = make_toy_replica()
        router = self._router([("ra",
                                f"127.0.0.1:{srv.server_address[1]}")])
        try:
            url = f"http://{router.host}:{router.port}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(url + "/rollout", {"path": "/x"})
            assert ei.value.code == 409
        finally:
            router.shutdown(drain=False)
            srv.shutdown(), srv.server_close()

    def test_replica_spec_parsing(self):
        assert parse_replica_spec("a=h:1,b=h:2") == [("a", "h:1"),
                                                     ("b", "h:2")]
        with pytest.raises(ValueError):
            parse_replica_spec("nonsense")
        with pytest.raises(ValueError):
            parse_replica_spec("")


# =====================================================================
# fleet config
# =====================================================================

class TestFleetConfig:
    def test_validate_catches_junk(self):
        assert validate_fleet_config([]) != []
        assert validate_fleet_config({"schema": 99}) != []
        p = validate_fleet_config({"schema": 1, "replicas": 0})
        assert any("bundle" in x for x in p)
        assert any("replicas" in x for x in p)
        p = validate_fleet_config(
            {"schema": 1, "bundle": "b", "replicas": 2,
             "rollout": {"shadow_fraction": 2.0}})
        assert any("shadow_fraction" in x for x in p)
        assert validate_fleet_config(
            {"schema": 1, "bundle": "b", "replicas": 2}) == []

    def test_load_resolves_relative_bundle(self, tmp_path):
        cfg = tmp_path / "fleet.json"
        cfg.write_text(json.dumps(
            {"schema": 1, "bundle": "bundle_dir", "replicas": 1}))
        loaded = load_fleet_config(str(cfg))
        assert loaded["bundle"] == str(tmp_path / "bundle_dir")
        with pytest.raises(FleetError):
            load_fleet_config(str(tmp_path / "missing.json"))


# =====================================================================
# capacity sweep (loadgen)
# =====================================================================

class TestCapacitySweep:
    def _echo(self):
        class Echo(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                data = json.loads(self.rfile.read(n))
                body = json.dumps({"action": data["obs"]}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), Echo)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def test_ladder_reports_max_rps_at_slo(self):
        srv = self._echo()
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            res = capacity_sweep(addr, slo_ms=1000.0,
                                 rps_ladder=[50, 100], conns=4,
                                 rung_duration_s=0.4)
            assert res["max_rps_at_slo"] == 100.0
            assert not res["saturated"]
            assert [r["ok"] for r in res["rungs"]] == [True, True]
        finally:
            srv.shutdown(), srv.server_close()

    def test_impossible_slo_reads_as_saturation(self):
        srv = self._echo()
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            res = capacity_sweep(addr, slo_ms=1e-6, rps_ladder=[50],
                                 conns=4, rung_duration_s=0.3)
            assert res["max_rps_at_slo"] is None
            assert res["saturated"]
        finally:
            srv.shutdown(), srv.server_close()

    def test_geometric_ladder_stops_at_saturation(self):
        srv = self._echo()
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            res = capacity_sweep(addr, slo_ms=1e-6, start_rps=10,
                                 growth=2.0, max_rungs=5, conns=2,
                                 rung_duration_s=0.3)
            # the first failing rung ends the auto ladder
            assert len(res["rungs"]) == 1
        finally:
            srv.shutdown(), srv.server_close()


# =====================================================================
# jax-free file-run probes (the sidecar/collector discipline)
# =====================================================================

class TestFileRun:
    def test_router_file_run_never_imports_package_or_jax(self):
        path = os.path.join(REPO, "estorch_tpu", "serve", "router.py")
        probe = (
            "import importlib.util, sys, json, threading, time\n"
            "import urllib.request\n"
            "from http.server import BaseHTTPRequestHandler, "
            "ThreadingHTTPServer\n"
            f"spec = importlib.util.spec_from_file_location('r', "
            f"{path!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "assert 'jax' not in sys.modules, 'router imported jax'\n"
            "assert 'estorch_tpu' not in sys.modules, 'package init "
            "ran'\n"
            "class Toy(BaseHTTPRequestHandler):\n"
            "    protocol_version = 'HTTP/1.1'\n"
            "    def log_message(self, *a): pass\n"
            "    def do_GET(self):\n"
            "        b = json.dumps({'ok': True, 'draining': False,"
            " 'queue_depth': 0}).encode()\n"
            "        self.send_response(200)\n"
            "        self.send_header('Content-Length', str(len(b)))\n"
            "        self.end_headers(); self.wfile.write(b)\n"
            "    def do_POST(self):\n"
            "        n = int(self.headers.get('Content-Length', 0))\n"
            "        d = json.loads(self.rfile.read(n))\n"
            "        b = json.dumps({'action': d['obs']}).encode()\n"
            "        self.send_response(200)\n"
            "        self.send_header('Content-Length', str(len(b)))\n"
            "        self.end_headers(); self.wfile.write(b)\n"
            "srv = ThreadingHTTPServer(('127.0.0.1', 0), Toy)\n"
            "threading.Thread(target=srv.serve_forever, "
            "daemon=True).start()\n"
            "router = m.Router([('ra', f'127.0.0.1:"
            "{srv.server_address[1]}')], port=0)\n"
            "router.start_background(); time.sleep(0.3)\n"
            "req = urllib.request.Request("
            "f'http://{router.host}:{router.port}/predict', "
            "json.dumps({'obs': [3.0]}).encode(), "
            "{'Content-Type': 'application/json'})\n"
            "out = json.loads(urllib.request.urlopen(req, "
            "timeout=10).read())\n"
            "assert out['action'] == [3.0], out\n"
            "assert 'jax' not in sys.modules\n"
            "router.shutdown(drain=False)\n"
            "print('ROUTER_FILE_RUN_OK')\n"
        )
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        assert "ROUTER_FILE_RUN_OK" in r.stdout

    def test_fleet_file_run_never_imports_package_or_jax(self, tmp_path):
        path = os.path.join(REPO, "estorch_tpu", "serve", "fleet.py")
        cfg = tmp_path / "fleet.json"
        cfg.write_text(json.dumps(
            {"schema": 1, "bundle": "b", "replicas": 2,
             "rollout": {"shadow_fraction": 0.5}}))
        probe = (
            "import importlib.util, sys\n"
            f"spec = importlib.util.spec_from_file_location('f', "
            f"{path!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "assert 'jax' not in sys.modules, 'fleet imported jax'\n"
            "assert 'estorch_tpu' not in sys.modules, 'package init "
            "ran'\n"
            f"cfg = m.load_fleet_config({str(cfg)!r})\n"
            "assert cfg['replicas'] == 2\n"
            "assert m.validate_fleet_config({'schema': 1}) != []\n"
            "assert 'jax' not in sys.modules\n"
            "print('FLEET_FILE_RUN_OK')\n"
        )
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        assert "FLEET_FILE_RUN_OK" in r.stdout


# =====================================================================
# THE acceptance demo: chaos kill + warm respawn + canary rollback
# =====================================================================

SMALL_PK = {"action_dim": 1, "hidden": (24, 24), "discrete": False,
            "action_scale": 2.0}


def _make_es(seed):
    import jax
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs.pendulum import Pendulum

    return ES(MLPPolicy, JaxAgent(Pendulum(), horizon=10), optax.adam,
              population_size=8, sigma=0.05, seed=seed,
              policy_kwargs=dict(SMALL_PK),
              optimizer_kwargs={"learning_rate": 1e-2},
              table_size=1 << 14, device=jax.devices()[0])


@pytest.fixture(scope="module")
def fleet_bundles(tmp_path_factory):
    """One warm incumbent bundle + a same-params re-export (good canary)
    + a different-seed bundle (bad canary: valid artifact, different
    parameters — the parity gate's target)."""
    root = tmp_path_factory.mktemp("fleet_bundles")
    es = _make_es(0)
    es.train(1, verbose=False)
    incumbent = es.export_bundle(str(root / "incumbent"), warm=True,
                                 warm_max_batch=4)
    good = es.export_bundle(str(root / "good"))
    es_bad = _make_es(1)
    es_bad.train(1, verbose=False)
    bad = es_bad.export_bundle(str(root / "bad"))
    ref = np.asarray(es.predict(
        np.array([0.1, 0.2, 0.3], np.float32))).tolist()
    return {"incumbent": incumbent, "good": good, "bad": bad,
            "ref": ref}


class TestFleetChaosDemo:
    def test_kill_under_load_then_bad_canary_rollback(
            self, fleet_bundles, tmp_path, monkeypatch):
        ledger = str(tmp_path / "chaos_ledger")
        fleet = Fleet(
            {"schema": 1, "bundle": fleet_bundles["incumbent"],
             "replicas": 2,
             "serve": {"max_batch": 4, "cpu_devices": 8},
             "router": {"retry_budget": 2, "breaker_open_s": 0.5},
             "respawn": {"backoff_s": 0.2},
             "rollout": {"shadow_fraction": 0.9, "min_shadow": 12,
                         "parity_samples": 4, "window_s": 30}},
            str(tmp_path / "run"), port=0)
        try:
            fleet.start()
            assert fleet.wait_ready(180), fleet.status()
            # declare the chaos once the fleet SERVES (at_s counts from
            # arm_chaos): a kill scheduled into the replicas' jax-import
            # window would murder a replica the router never met
            monkeypatch.setenv(CHAOS_ENV, json.dumps({
                "events": [{"kind": "kill_replica", "at_s": 1.5,
                            "replica": 1}],
                "ledger": ledger}))
            fleet.arm_chaos()  # kill_replica@1.5s of SERVING
            addr = f"{fleet.router.host}:{fleet.router.port}"

            # --- concurrent load across the declared SIGKILL: every
            # client request answers (retried to the survivor within
            # the budget), nothing shed
            load = run_load(addr, conns=6, duration_s=4.5,
                            obs=[0.1, 0.2, 0.3])
            assert load["errors"] == 0 and load["shed"] == 0, load
            assert load["requests"] > 100, load
            events = [e["event"] for e in fleet.events]
            assert "chaos_kill_replica" in events, events
            c = fleet.router.counters
            assert c.get("router_breaker_opens_total") >= 1
            assert c.get("router_retries_total") >= 1

            # --- the fleet respawns the corpse and the breaker closes
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                slot = fleet.slots[1]
                breakers = {r.name: r.breaker.state
                            for r in fleet.router.replicas()}
                if (slot.restarts >= 1 and slot.state == "up"
                        and breakers["r1"] == BREAKER_CLOSED):
                    break
                time.sleep(0.2)
            assert fleet.slots[1].restarts >= 1
            assert fleet.slots[1].state == "up", fleet.status()
            assert breakers["r1"] == BREAKER_CLOSED, breakers

            # --- warm respawn: zero fresh XLA builds (PR-12 warmth)
            with urllib.request.urlopen(
                    f"http://{fleet.slots[1].address}/stats",
                    timeout=15) as r:
                cold = json.loads(r.read())["cold_start"]
            assert cold["compiles_at_load"] == 0, cold
            assert cold["warm_cache_hits"] > 0, cold

            # --- bad-canary rollout auto-rolls-back with evidence
            bg: dict = {}

            def bg_load():
                bg["res"] = run_load(addr, conns=4, duration_s=18.0,
                                     obs=[0.1, 0.2, 0.3])

            th = threading.Thread(target=bg_load, daemon=True)
            th.start()
            time.sleep(0.5)
            out, _h = _post(f"http://{addr}/rollout",
                            {"path": fleet_bundles["bad"]})
            assert out["ok"] and out["state"] == "canary", out
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                ro = fleet.status()["rollout"]
                if ro["state"] == "idle" and ro["last"] is not None:
                    break
                time.sleep(0.2)
            last = ro["last"]
            assert last is not None and last["aborted"], ro
            # the structured abort cites the parity or tail evidence
            assert last["reason"] in ("parity", "tail_band"), last
            if last["reason"] == "parity":
                assert last["evidence"]["mismatched"] >= 1
                assert "example" in last["evidence"]
            else:
                assert "groups" in last["evidence"]

            # clients kept getting INCUMBENT answers bit-equal to the
            # exporting run throughout
            out, _h = _post(f"http://{addr}/predict",
                            {"obs": [0.1, 0.2, 0.3]})
            assert out["action"] == fleet_bundles["ref"], out

            # --- a same-params re-export PROMOTES fleet-wide
            out, _h = _post(f"http://{addr}/rollout",
                            {"path": fleet_bundles["good"],
                             "min_shadow": 12, "min_band_pct": 40.0})
            assert out["ok"], out
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                ro = fleet.status()["rollout"]
                if (ro["state"] == "idle" and ro["last"]
                        and ro["last"].get("path")
                        != fleet_bundles["bad"]):
                    break
                time.sleep(0.2)
            th.join(timeout=30)
            last = ro["last"]
            assert last and last.get("promoted"), last
            assert last["evidence"]["parity_samples"] >= 4
            assert fleet.bundle == fleet_bundles["good"]
            # the background load saw zero errors through BOTH rollouts
            assert bg["res"]["errors"] == 0 and bg["res"]["shed"] == 0, \
                bg["res"]
            # answers unchanged (same params, new artifact)
            out, _h = _post(f"http://{addr}/predict",
                            {"obs": [0.1, 0.2, 0.3]})
            assert out["action"] == fleet_bundles["ref"], out
        finally:
            final = fleet.shutdown()
        assert final["clean"], final


class TestFleetCLI:
    def test_route_fleet_end_to_end(self, fleet_bundles, tmp_path):
        """`python -m estorch_tpu.serve route --fleet fleet.json`: the
        whole stack from the operator's seat — ready line, routed
        predict, clean SIGTERM drain (exit 0)."""
        import signal as _signal

        cfg = tmp_path / "fleet.json"
        cfg.write_text(json.dumps({
            "schema": 1, "bundle": fleet_bundles["incumbent"],
            "replicas": 2,
            "serve": {"max_batch": 4, "cpu_devices": 8}}))
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        p = subprocess.Popen(
            [sys.executable, "-m", "estorch_tpu.serve", "route",
             "--fleet", str(cfg), "--port", "0",
             "--workdir", str(tmp_path / "run")],
            stdout=subprocess.PIPE, text=True, env=env, cwd=REPO)
        try:
            ready = json.loads(p.stdout.readline())
            assert ready["role"] == "fleet"
            assert ready["replicas"] == ["r0", "r1"]
            url = ready["url"]
            # wait for at least one replica to come up, then predict
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                try:
                    out, hdrs = _post(url + "/predict",
                                      {"obs": [0.1, 0.2, 0.3]},
                                      timeout=10)
                    break
                except (urllib.error.URLError, OSError):
                    time.sleep(0.3)
            assert out["action"] == fleet_bundles["ref"], out
            assert hdrs["X-Upstream"] in ("r0", "r1")
            p.send_signal(_signal.SIGTERM)
            rest, _ = p.communicate(timeout=60)
            final = json.loads(rest.strip().splitlines()[-1])
            assert final["clean"] and p.returncode == 0, (final,
                                                          p.returncode)
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)


@pytest.mark.slow
class TestFleetWedge:
    def test_wedge_replica_is_escalated_and_respawned(
            self, fleet_bundles, tmp_path, monkeypatch):
        """SIGSTOP (declared wedge_replica): alive process, silent
        socket — the breaker opens on poll timeouts and the fleet
        escalates to SIGKILL + warm respawn."""
        fleet = Fleet(
            {"schema": 1, "bundle": fleet_bundles["incumbent"],
             "replicas": 2,
             "serve": {"max_batch": 4, "cpu_devices": 8},
             "router": {"breaker_open_s": 0.5, "poll_timeout_s": 0.5,
                        "upstream_timeout_s": 3.0},
             "respawn": {"backoff_s": 0.2, "wedge_kill_s": 2.0}},
            str(tmp_path / "run"), port=0)
        try:
            fleet.start()
            assert fleet.wait_ready(180)
            monkeypatch.setenv(CHAOS_ENV, json.dumps({
                "events": [{"kind": "wedge_replica", "at_s": 0.5,
                            "replica": 0}],
                "ledger": str(tmp_path / "ledger")}))
            fleet.arm_chaos()
            addr = f"{fleet.router.host}:{fleet.router.port}"
            load = run_load(addr, conns=4, duration_s=5.0,
                            obs=[0.1, 0.2, 0.3])
            assert load["errors"] == 0 and load["shed"] == 0, load
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if (fleet.router.counters.get("fleet_wedge_kills_total")
                        and fleet.slots[0].state == "up"):
                    break
                time.sleep(0.2)
            assert fleet.router.counters.get(
                "fleet_wedge_kills_total") >= 1
            assert fleet.slots[0].state == "up", fleet.status()
            events = [e["event"] for e in fleet.events]
            assert "chaos_wedge_replica" in events
        finally:
            fleet.shutdown()
