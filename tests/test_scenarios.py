"""Scenario suite (estorch_tpu/scenarios, docs/scenarios.md): params
pytree + distribution determinism, step_p default-path bit-equality for
every parameterized family, ScenarioEnv semantics, the device/sharded
E2E acceptance (≥10 variants, one XLA program, per-variant fitness
surfaced), PBT exploit/explore with bit-exact event-log replay, and the
per-variant fitness helpers."""

import dataclasses
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from estorch_tpu import ES, JaxAgent, MLPPolicy, NS_ES
from estorch_tpu.envs import (Acrobot, CartPole, Hopper2D, MountainCar,
                              MountainCarContinuous, Pendulum)
from estorch_tpu.scenarios import (LogRange, PBTController, Range,
                                   ScenarioDistribution, ScenarioEnv,
                                   ScenarioParams, default_distribution,
                                   merge_scenario_blocks,
                                   scenario_fitness_block,
                                   tunable_optimizer, variant_of_bc,
                                   worst_variant_callout)

ALL_FAMILIES = [Pendulum(), CartPole(), Acrobot(), MountainCar(),
                MountainCarContinuous(), Hopper2D()]


def small_es(dist=None, optimizer=None, **over):
    kw = dict(
        population_size=16, sigma=0.05, seed=0,
        policy_kwargs={"action_dim": 1, "hidden": (8,),
                       "discrete": False, "action_scale": 2.0},
        table_size=1 << 14, telemetry=True, scenarios=dist,
    )
    if optimizer is None:
        optimizer = optax.adam
        kw["optimizer_kwargs"] = {"learning_rate": 0.01}
    kw.update(over)
    return ES(MLPPolicy, JaxAgent(Pendulum(), horizon=20), optimizer, **kw)


# ---------------------------------------------------------------------
# params + distribution
# ---------------------------------------------------------------------

class TestParamsAndDistribution:
    def test_params_pytree_round_trip(self):
        p = ScenarioParams({"g": jnp.float32(9.8), "m": jnp.float32(1.0)})
        leaves, treedef = jax.tree_util.tree_flatten(p)
        assert len(leaves) == 2
        q = jax.tree_util.tree_unflatten(treedef, leaves)
        assert q.names == ("g", "m") and float(q["g"]) == pytest.approx(9.8)
        assert "g" in q and q.get("absent") is None

    def test_range_validation(self):
        with pytest.raises(ValueError, match="lo <= hi"):
            Range(2.0, 1.0)
        with pytest.raises(ValueError, match="lo > 0"):
            LogRange(0.0, 1.0)
        with pytest.raises(ValueError, match="finite"):
            Range(0.0, float("inf"))
        with pytest.raises(ValueError, match="n_variants"):
            ScenarioDistribution({"g": (1.0, 2.0)}, n_variants=0)
        with pytest.raises(ValueError, match="at least one"):
            ScenarioDistribution({})

    def test_draws_deterministic_and_in_bounds(self):
        dist = ScenarioDistribution(
            {"g": (7.0, 13.0), "m": LogRange(0.5, 2.0)},
            n_variants=16, seed=3)
        a = dist.draw_concrete(5)
        assert a == dist.draw_concrete(5)  # same (seed, variant) stream
        assert a != dist.draw_concrete(6)
        for v in range(16):
            d = dist.draw_concrete(v)
            assert 7.0 <= d["g"] <= 13.0
            assert 0.5 <= d["m"] <= 2.0
        # seed changes every draw
        assert (ScenarioDistribution({"g": (7.0, 13.0)}, 4, seed=1)
                .draw_concrete(0)
                != ScenarioDistribution({"g": (7.0, 13.0)}, 4, seed=2)
                .draw_concrete(0))

    def test_traced_draw_matches_concrete(self):
        """The in-program (traced-variant) draw and the host concrete
        draw are the same stream — threefry is counter-based."""
        dist = ScenarioDistribution({"g": (7.0, 13.0)}, 8, seed=0)
        traced = jax.jit(lambda v: dist.draw(v)["g"])(jnp.int32(3))
        assert float(traced) == pytest.approx(dist.draw_concrete(3)["g"])

    def test_draw_all_stacks(self):
        dist = default_distribution(Pendulum(), n_variants=5, spread=0.2)
        stacked = dist.draw_all()
        for name in dist.names:
            assert np.asarray(stacked[name]).shape == (5,)

    def test_spec_json_round_trip(self):
        dist = ScenarioDistribution(
            {"g": (7.0, 13.0), "m": LogRange(0.5, 2.0)}, 12, seed=9)
        spec = json.loads(json.dumps(dist.spec_json()))
        clone = ScenarioDistribution.from_json(spec)
        assert clone.draw_concrete(7) == dist.draw_concrete(7)
        assert clone.n_variants == 12 and clone.seed == 9

    def test_validate_for_rejects_unknown_fields(self):
        dist = ScenarioDistribution({"warp_factor": (1.0, 9.0)}, 4)
        with pytest.raises(ValueError, match="warp_factor"):
            dist.validate_for(Pendulum())

    def test_unparameterized_env_named_in_error(self):
        class Boring:
            pass

        with pytest.raises(ValueError, match="SCENARIO_FIELDS"):
            default_distribution(Boring())


# ---------------------------------------------------------------------
# parameterized families: step_p contract
# ---------------------------------------------------------------------

class TestStepP:
    @pytest.mark.parametrize("env", ALL_FAMILIES,
                             ids=lambda e: type(e).__name__)
    def test_default_path_bit_equal(self, env):
        """step() delegates to step_p(None, ...) with Python-float
        constants — the un-randomized graph/values are IDENTICAL."""
        key = jax.random.PRNGKey(0)
        state, obs = env.reset(key)
        action = (jnp.int32(1) if env.discrete
                  else jnp.full((env.action_dim,), 0.3))
        a = env.step(state, action)
        b = env.step_p(None, state, action)
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    @pytest.mark.parametrize("env", ALL_FAMILIES,
                             ids=lambda e: type(e).__name__)
    def test_traced_defaults_match_static(self, env):
        """Feeding the family's own defaults as TRACED params reproduces
        the static dynamics (allclose: traced operands may reassociate
        constant folds)."""
        params = ScenarioParams({k: jnp.float32(v) for k, v in
                                 env.scenario_defaults().items()})
        key = jax.random.PRNGKey(1)
        state, obs = env.reset(key)
        action = (jnp.int32(0) if env.discrete
                  else jnp.full((env.action_dim,), -0.5))
        a = env.step(state, action)
        b = jax.jit(env.step_p)(params, state, action)
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-5, atol=1e-6)

    def test_perturbed_params_change_dynamics(self):
        env = Pendulum()
        params = ScenarioParams({"g": jnp.float32(2.0)})
        state = jnp.asarray([1.0, 0.5])
        a = env.step(state, jnp.asarray([0.0]))[0]
        b = env.step_p(params, state, jnp.asarray([0.0]))[0]
        assert not np.allclose(np.asarray(a), np.asarray(b))

    def test_locomotion_scales_change_dynamics(self):
        env = Hopper2D()
        state, _ = env.reset(jax.random.PRNGKey(0))
        act = jnp.full((env.action_dim,), 0.5)
        base = env.step(state, act)[0]
        scaled = env.step_p(
            ScenarioParams({"gravity_scale": jnp.float32(0.5)}),
            state, act)[0]
        assert not np.allclose(np.asarray(base["vel"]),
                               np.asarray(scaled["vel"]))


# ---------------------------------------------------------------------
# ScenarioEnv
# ---------------------------------------------------------------------

class TestScenarioEnv:
    def test_protocol_and_variant_column(self):
        dist = default_distribution(Pendulum(), n_variants=7, spread=0.2)
        env = ScenarioEnv(Pendulum(), dist)
        assert env.obs_dim == 3 and env.bc_dim == 3  # base 2 + variant
        assert env.action_bound == 2.0
        state, obs = env.reset(jax.random.PRNGKey(4))
        assert obs.shape == (3,)
        state, obs, reward, done = env.step(state, jnp.asarray([0.1]))
        bc = np.asarray(env.behavior(state, obs))
        assert bc.shape == (3,)
        assert 0 <= int(round(bc[-1])) < 7

    def test_variant_determines_params(self):
        """Same reset key → same variant → same drawn constants; the
        draw is keyed on (seed, variant), not on the episode."""
        dist = default_distribution(Pendulum(), n_variants=5, spread=0.3)
        env = ScenarioEnv(Pendulum(), dist)
        (_, p1, v1, _), _ = env.reset(jax.random.PRNGKey(8))
        (_, p2, v2, _), _ = env.reset(jax.random.PRNGKey(8))
        assert int(v1) == int(v2)
        assert float(p1["g"]) == float(p2["g"])
        assert float(p1["g"]) == pytest.approx(
            dist.draw_concrete(int(v1))["g"])

    def test_obs_noise_applied_when_configured(self):
        base = Pendulum()
        quiet = ScenarioEnv(base, ScenarioDistribution(
            {"g": (10.0, 10.0)}, 3, seed=0))
        noisy = ScenarioEnv(base, ScenarioDistribution(
            {"g": (10.0, 10.0), "obs_noise": (0.5, 0.5)}, 3, seed=0))
        key = jax.random.PRNGKey(2)
        (_, _, _, _), obs_q = quiet.reset(key)
        (_, _, _, _), obs_n = noisy.reset(key)
        assert not np.allclose(np.asarray(obs_q), np.asarray(obs_n))

    def test_rejects_unparameterized_env(self):
        class NoStepP:
            SCENARIO_FIELDS = ("x",)
            bc_dim = 1

        with pytest.raises(ValueError, match="step_p"):
            ScenarioEnv(NoStepP(), ScenarioDistribution({"x": (0, 1)}, 2))

    def test_gait_protocol_only_when_base_has_it(self):
        pend = ScenarioEnv(Pendulum(),
                           default_distribution(Pendulum(), 3))
        assert not hasattr(pend, "step_metrics")
        hop = ScenarioEnv(Hopper2D(),
                          default_distribution(Hopper2D(), 3))
        assert hasattr(hop, "step_metrics")
        state, _ = hop.reset(jax.random.PRNGKey(0))
        m = hop.step_metrics(state)
        assert np.asarray(m).shape == (len(hop.metric_names),)


# ---------------------------------------------------------------------
# fitness helpers
# ---------------------------------------------------------------------

class TestFitnessHelpers:
    def test_block_counts_and_nan_handling(self):
        fitness = np.asarray([1.0, 2.0, np.nan, 10.0])
        variants = np.asarray([0.0, 0.0, 1.0, 2.0])
        b = scenario_fitness_block(fitness, variants, 4)
        assert b["counts"] == [2, 1, 1, 0]
        assert b["mean"][0] == pytest.approx(1.5)
        assert math.isnan(b["mean"][1])  # failed rollout excluded
        assert b["best"][2] == 10.0 and math.isnan(b["mean"][3])

    def test_merge_weights_by_count(self):
        b1 = scenario_fitness_block([1.0, 3.0], [0, 0], 2)
        b2 = scenario_fitness_block([5.0, 7.0, 9.0], [0, 0, 1], 2)
        merged = merge_scenario_blocks([b1, b2])
        assert merged["counts"] == [4, 1]
        assert merged["mean"][0] == pytest.approx((1 + 3 + 5 + 7) / 4)
        assert merged["best"][0] == 7.0

    def test_worst_variant_callout_fires_and_stays_quiet(self):
        lag = {"n_variants": 6, "counts": [4] * 6,
               "mean": [-100.0, -102.0, -98.0, -101.0, -99.0, -400.0],
               "best": [0.0] * 6}
        hit = worst_variant_callout(lag)
        assert hit and hit["variant"] == 5 and hit["lag_in_mads"] > 2
        balanced = dict(lag, mean=[-100.0, -102.0, -98.0, -101.0,
                                   -99.0, -103.0])
        assert worst_variant_callout(balanced) is None


# ---------------------------------------------------------------------
# wiring refusals
# ---------------------------------------------------------------------

class TestWiringRefusals:
    def test_scenarios_must_be_a_distribution(self):
        with pytest.raises(TypeError, match="ScenarioDistribution"):
            small_es(dist={"g": (7.0, 13.0)})

    def test_host_backend_refused(self):
        class FakeHostAgent:
            def rollout(self, policy):
                return 0.0

        with pytest.raises(ValueError, match="device-path"):
            ES(object, FakeHostAgent(), optax.adam,
               scenarios=default_distribution(Pendulum(), 4))

    def test_novelty_family_refused(self):
        with pytest.raises(ValueError, match="novelty"):
            NS_ES(MLPPolicy, JaxAgent(Pendulum(), horizon=10), optax.adam,
                  scenarios=default_distribution(Pendulum(), 4))


# ---------------------------------------------------------------------
# E2E acceptance: device path
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def trained_10v():
    dist = default_distribution(Pendulum(), n_variants=10, spread=0.3,
                                seed=1)
    es = small_es(dist=dist, population_size=64)
    es.train(3, verbose=False)
    return es


class TestEndToEnd:
    def test_trains_across_ten_variants_with_fitness_block(self,
                                                          trained_10v):
        es = trained_10v
        seen = set()
        for r in es.history:
            blk = r["scenarios"]
            assert blk["n_variants"] == 10
            assert sum(blk["counts"]) == 64
            seen |= {v for v, c in enumerate(blk["counts"]) if c}
        assert seen == set(range(10))  # every variant trained on

    def test_program_count_independent_of_variant_count(self,
                                                        trained_10v):
        def compiles(es):
            return sum(len(r.get("compile_events", []))
                       for r in es.history)

        es3 = small_es(dist=default_distribution(
            Pendulum(), n_variants=3, spread=0.3, seed=1))
        es3.train(1, verbose=False)
        assert compiles(trained_10v) == compiles(es3) == 1

    def test_mirrored_pairs_share_variants(self, trained_10v):
        """Antithetic twins share a rollout key (common random numbers)
        — so ±ε are compared under IDENTICAL physics."""
        es = trained_10v
        es.compile_time_s = es.compile_time_s or 0.0
        es.engine.compile_split(es.state)
        ev = es.engine.evaluate(es.state)
        v = np.rint(variant_of_bc(ev.bc)).astype(int)
        np.testing.assert_array_equal(v[0::2], v[1::2])

    def test_manifest_and_bundle_name_the_scenarios(self, trained_10v,
                                                    tmp_path):
        es = trained_10v
        cfg = es.run_manifest()["config"]
        assert cfg["scenarios"]["n_variants"] == 10
        assert cfg["scenarios"]["seed"] == 1
        path = es.export_bundle(str(tmp_path / "bundle"))
        with open(os.path.join(path, "MANIFEST.json")) as f:
            manifest = json.load(f)
        spec = manifest["source"]["scenarios"]
        clone = ScenarioDistribution.from_json(spec)
        assert clone.draw_concrete(4) == es._scenarios.draw_concrete(4)

    def test_obs_summarize_scenarios_section(self, trained_10v,
                                             tmp_path):
        from estorch_tpu.obs.summarize import (format_summary,
                                               load_records, summarize)

        run = tmp_path / "run.jsonl"
        with open(run, "w") as f:
            for r in trained_10v.history:
                f.write(json.dumps(r, default=float) + "\n")
        s = summarize(load_records(str(run)))
        blk = s.get("scenarios")
        assert blk and blk["n_variants"] == 10
        assert blk["coverage"] == 1.0
        assert "scenarios" in s["diagnosis"]
        assert "scenarios" in format_summary(s)

    def test_overlap_scheduler_carries_the_block(self):
        """train_async(strategy="overlap") records get the same
        per-variant block as the sync loop (one shared attach)."""
        dist = default_distribution(Pendulum(), n_variants=5,
                                    spread=0.3, seed=1)
        es = small_es(dist=dist)
        es.train_async(2, strategy="overlap", verbose=False)
        blk = es.history[-1]["scenarios"]
        assert blk["n_variants"] == 5 and sum(blk["counts"]) == 16

    def test_sharded_engine_composes(self):
        dist = default_distribution(Pendulum(), n_variants=10,
                                    spread=0.3, seed=1)
        es = small_es(dist=dist, shard_params=True)
        es.train(1, verbose=False)
        blk = es.history[0]["scenarios"]
        assert blk["n_variants"] == 10 and sum(blk["counts"]) == 16


# ---------------------------------------------------------------------
# PBT
# ---------------------------------------------------------------------

class TestPBT:
    def _build(self):
        dist = default_distribution(Pendulum(), n_variants=6,
                                    spread=0.3, seed=1)
        return small_es(dist=dist,
                        optimizer=tunable_optimizer(learning_rate=0.01))

    def test_validation(self):
        es = small_es()
        with pytest.raises(ValueError, match="n_centers"):
            PBTController(es, n_centers=1)
        with pytest.raises(ValueError, match="explore_every"):
            PBTController(es, explore_every=0)

    def test_run_logs_and_replays_bit_exactly(self):
        es = self._build()
        ctl = PBTController(es, n_centers=3, explore_every=2, seed=7)
        assert ctl.lr_tunable
        log = ctl.run(5, verbose=False)
        live = np.asarray(es.state.params_flat)
        kinds = [e["type"] for e in log["events"]]
        assert kinds.count("init") == 3
        assert "exploit" in kinds
        for ev in log["events"]:
            if ev["type"] == "exploit":
                assert ev["lr"] is not None and ev["sigma"] > 0
        assert len(es.meta_states) == 3
        # the deterministic log re-drives the schedule to the SAME bits
        es2 = self._build()
        PBTController(es2, n_centers=3, explore_every=2, seed=7).run(
            5, verbose=False, replay=log)
        np.testing.assert_array_equal(live,
                                      np.asarray(es2.state.params_flat))

    def test_replay_rejects_foreign_log(self):
        es = self._build()
        ctl = PBTController(es, n_centers=3, explore_every=2, seed=7)
        log = ctl.run(3, verbose=False)
        es2 = self._build()
        bad = PBTController(es2, n_centers=3, explore_every=3, seed=7)
        with pytest.raises(ValueError, match="different PBT"):
            bad.run(3, verbose=False, replay=log)

    def test_exploit_actually_copies_top_params(self):
        es = self._build()
        ctl = PBTController(es, n_centers=3, explore_every=1, seed=0)
        log = ctl.run(2, verbose=False)
        exploits = [e for e in log["events"] if e["type"] == "exploit"]
        assert exploits, "explore_every=1 must exploit after round 1"
        ev = exploits[0]
        assert ev["score_src"] >= ev["score_dst"]
