"""End-to-end convergence: BASELINE config 1's acceptance criterion.

Runs the actual cartpole_smoke recipe (configs.py) at population 128 (the
only deviation, for CI speed): the trained CENTER policy must clear
gymnasium's 'solved' bar (mean return ≥ 475) on held-out evaluation
episodes.  ~18s on the 8-virtual-device CPU mesh.
"""

import pytest

from estorch_tpu.configs import cartpole_smoke


@pytest.mark.slow
def test_cartpole_solved():
    es = cartpole_smoke(population_size=128, seed=0)
    es.train(25, verbose=False)
    ev = es.evaluate_policy(n_episodes=50)
    assert ev["mean"] >= 475.0, f"not solved: {ev}"
