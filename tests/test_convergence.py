"""End-to-end convergence: BASELINE config 1's acceptance criterion.

CartPole-v1, 2-layer MLP, vanilla ES (the CPU smoke config): the trained
CENTER policy must clear gymnasium's 'solved' bar (mean return ≥ 475) on
held-out evaluation episodes.  ~18s on the 8-virtual-device CPU mesh.
"""

import optax

from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole


def test_cartpole_solved():
    es = ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=128,
        sigma=0.1,
        seed=0,
        policy_kwargs={"action_dim": 2, "hidden": (32, 32)},
        agent_kwargs={"env": CartPole()},
        optimizer_kwargs={"learning_rate": 3e-2},
    )
    es.train(25, verbose=False)
    ev = es.evaluate_policy(n_episodes=50)
    assert ev["mean"] >= 475.0, f"not solved: {ev}"