"""Multi-host helpers (single-process degenerate checks; real multi-process
runs are exercised on pods — the engine program is identical either way)."""

import jax
import numpy as np
import optax

from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole
from estorch_tpu.parallel import (
    global_population_mesh,
    initialize_distributed,
    leader_only,
    process_info,
)


class TestMultihost:
    def test_initialize_single_process_fallback(self):
        # off-cluster the argless auto-discovery attempt fails -> False,
        # and the run proceeds single-process without raising
        assert initialize_distributed() is False

# NOTE: explicit-argument failure passthrough is not tested here — with a
# real coordinator address jax.distributed BLOCKS waiting for the cluster
# (its own contract), so any such test would hang a single-machine CI.

    def test_process_info(self):
        info = process_info()
        assert info["process_count"] == 1
        assert info["is_leader"]
        assert info["global_devices"] == 8

    def test_global_mesh_spans_all_devices(self):
        mesh = global_population_mesh()
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("pop",)

    def test_leader_only_runs_on_leader(self):
        calls = []

        @leader_only
        def record(x):
            calls.append(x)
            return x

        assert record(5) == 5  # single process IS the leader
        assert calls == [5]

    def test_es_trains_on_global_mesh(self):
        es = ES(
            MLPPolicy, JaxAgent, optax.adam,
            population_size=32, sigma=0.1, seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (8,)},
            agent_kwargs={"env": CartPole(), "horizon": 50},
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 16,
            mesh=global_population_mesh(),
        )
        es.train(2, verbose=False)
        assert len(es.history) == 2
        assert np.isfinite(es.history[-1]["reward_mean"])