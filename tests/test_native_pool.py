"""Native C++ envpool: 3-way dynamics parity (C++ vs NumPy fallback vs JAX
envs) and the pooled ES backend end-to-end."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from estorch_tpu import ES, NS_ES, MLPPolicy, PooledAgent
from estorch_tpu.parallel import single_device_mesh
from estorch_tpu.envs import CartPole, Pendulum
from estorch_tpu.envs.native_pool import NativeEnvPool, _NumpyPool


@pytest.fixture(scope="module")
def native_available():
    pool = NativeEnvPool("cartpole", 1)
    ok = pool.is_native
    pool.close()
    if not ok:
        pytest.skip("C++ envpool unavailable (no compiler)")


class TestPoolParity:
    def test_cartpole_cpp_matches_jax_env(self, native_available):
        """Same start state + actions → identical trajectories (C++ vs JAX)."""
        pool = NativeEnvPool("cartpole", 4, n_threads=2, seed=0)
        obs = pool.reset()
        env = CartPole()
        jstate = jnp.asarray(obs)  # state == obs for cartpole
        rng = np.random.default_rng(3)
        for t in range(30):
            acts = rng.integers(0, 2, (4, 1)).astype(np.float32)
            cobs, crew, cdone = pool.step(acts)
            for i in range(4):
                js, jobs_, jrew, jdone = env.step(jstate[i], jnp.int32(int(acts[i, 0])))
                if cdone[i]:
                    # C++ auto-resets; just check the done flag agreed
                    assert bool(jdone)
                else:
                    np.testing.assert_allclose(
                        cobs[i], np.asarray(jobs_), rtol=1e-4, atol=1e-5,
                        err_msg=f"step {t} env {i}",
                    )
                jstate = jstate.at[i].set(js if not cdone[i] else jnp.asarray(cobs[i]))
        pool.close()

    def test_pendulum_cpp_matches_jax_env(self, native_available):
        pool = NativeEnvPool("pendulum", 2, seed=5)
        obs = pool.reset()
        env = Pendulum()
        # recover (th, thdot) from obs
        states = [jnp.array([np.arctan2(o[1], o[0]), o[2]]) for o in obs]
        rng = np.random.default_rng(1)
        for t in range(25):
            acts = rng.uniform(-2, 2, (2, 1)).astype(np.float32)
            cobs, crew, _ = pool.step(acts)
            for i in range(2):
                s, o, r, _ = env.step(states[i], jnp.asarray(acts[i]))
                states[i] = s
                np.testing.assert_allclose(cobs[i], np.asarray(o), rtol=1e-3, atol=1e-4)
                np.testing.assert_allclose(crew[i], float(r), rtol=1e-3, atol=1e-4)
        pool.close()

    def test_numpy_fallback_matches_cpp_dynamics(self, native_available):
        """C++ and the NumPy fallback step identically from the same state."""
        cpp = NativeEnvPool("cartpole", 8, seed=0)
        npy = _NumpyPool(0, 8, seed=0)
        obs_c = cpp.reset()
        npy.reset()
        npy.state = obs_c.copy()  # align states (reset RNGs differ)
        acts = np.ones((8, 1), np.float32)
        oc, rc, dc = cpp.step(acts)
        on, rn, dn = npy.step(acts)
        live = ~dc
        np.testing.assert_allclose(oc[live], on[live], rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(dc, dn)
        cpp.close()

    def test_auto_reset_keeps_envs_alive(self, native_available):
        pool = NativeEnvPool("cartpole", 16, seed=2)
        pool.reset()
        done_seen = False
        for _ in range(300):
            obs, rew, done = pool.step(np.zeros((16, 1), np.float32))
            done_seen = done_seen or bool(done.any())
            # auto-reset: post-done observations are fresh (within bounds)
            assert np.all(np.abs(obs[done, 0]) <= 0.05 + 1e-6)
        assert done_seen
        pool.close()

    def test_unknown_env_rejected(self):
        with pytest.raises(ValueError, match="unknown env"):
            NativeEnvPool("humanoid", 4)

    def test_thread_count_invariance(self, native_available):
        """1-thread and 8-thread pools produce identical trajectories."""
        a = NativeEnvPool("pendulum", 32, n_threads=1, seed=9)
        b = NativeEnvPool("pendulum", 32, n_threads=8, seed=9)
        oa, ob = a.reset(), b.reset()
        np.testing.assert_array_equal(oa, ob)
        for _ in range(10):
            acts = np.full((32, 1), 0.5, np.float32)
            oa, ra, _ = a.step(acts)
            ob, rb, _ = b.step(acts)
            np.testing.assert_array_equal(oa, ob)
            np.testing.assert_array_equal(ra, rb)
        a.close()
        b.close()


class TestSanitizers:
    """SURVEY §5 race detection: the pool's thread team under TSan/ASan."""

    @staticmethod
    def _sanitizer_supported(flag: str) -> bool:
        """Probe the toolchain, NOT our code: skip only when the sanitizer
        runtime itself is unavailable; a compile error in our sources must
        FAIL the test, not skip it."""
        import subprocess
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".cpp") as f:
            f.write("int main(){return 0;}\n")
            f.flush()
            probe = subprocess.run(
                ["g++", flag, "-o", "/dev/null", f.name],
                capture_output=True, timeout=60,
            )
        return probe.returncode == 0

    @pytest.mark.parametrize("target,binary,flag", [
        ("tsan", "stress_tsan", "-fsanitize=thread"),
        ("asan", "stress_asan", "-fsanitize=address"),
    ])
    def test_sanitizer_stress_clean(self, target, binary, flag):
        import os
        import subprocess

        if not self._sanitizer_supported(flag):
            pytest.skip(f"toolchain lacks {flag}")
        native = os.path.join(os.path.dirname(__file__), "..", "estorch_tpu", "native")
        build = subprocess.run(
            ["make", "-C", native, target], capture_output=True, timeout=180
        )
        assert build.returncode == 0, (
            f"{target} build failed:\n{build.stderr.decode(errors='replace')[-2000:]}"
        )
        run = subprocess.run(
            [os.path.join(native, binary)], capture_output=True, timeout=600
        )
        assert run.returncode == 0, (
            f"{target} stress failed:\n{run.stderr.decode(errors='replace')[-2000:]}"
        )
        assert b"stress: OK" in run.stdout


class TestPooledBackend:
    def _make(self, cls=ES, **extra):
        kw = dict(
            policy=MLPPolicy,
            agent=PooledAgent,
            optimizer=optax.adam,
            population_size=32,
            sigma=0.1,
            seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (16,)},
            agent_kwargs={"env_name": "cartpole", "horizon": 100},
            optimizer_kwargs={"learning_rate": 3e-2},
            table_size=1 << 16,
        )
        kw.update(extra)
        return cls(**kw)

    def test_backend_detected_and_trains(self):
        es = self._make()
        assert es.backend == "pooled"
        es.train(5, verbose=False)
        assert len(es.history) == 5
        assert es.history[-1]["env_steps"] > 0

    def test_learning_on_pooled_cartpole(self):
        es = self._make()
        es.train(10, verbose=False)
        first = es.history[0]["reward_mean"]
        last = es.history[-1]["reward_mean"]
        assert last > first, (first, last)

    def test_pooled_update_matches_device_offsets(self):
        """The pooled path must use the exact offsets the update regenerates:
        member_params(i) equals the i-th row of the materialized thetas."""
        es = self._make()
        pair_offs = es.engine.core.all_pair_offsets(es.state)
        thetas = es.engine._materialize(
            es.state.params_flat, es.state.sigma, pair_offs
        )
        for i in (0, 1, 7):
            np.testing.assert_allclose(
                np.asarray(es.engine.member_params(es.state, i)),
                np.asarray(thetas[i]),
                rtol=1e-6, atol=1e-7,
            )

    def test_double_buffer_learns_and_counts_steps(self):
        """The overlapped path must behave like a working evaluator: learning
        happens, step accounting is sane, shapes match."""
        es = self._make(agent_kwargs={"env_name": "cartpole", "horizon": 100,
                                      "double_buffer": True})
        es.train(8, verbose=False)
        first, last = es.history[0], es.history[-1]
        assert last["reward_mean"] > first["reward_mean"], (first, last)
        assert 0 < last["env_steps"] <= 32 * 100

    def test_double_buffer_matches_sync_given_same_pools(self):
        """With identical env streams, DB evaluation must equal the sync
        path member-for-member (same thetas, same pools, same seeds)."""
        import jax.numpy as jnp

        a = self._make(agent_kwargs={"env_name": "cartpole", "horizon": 60,
                                     "double_buffer": True})
        pair_offs = a.engine.core.all_pair_offsets(a.state)
        thetas = a.engine._materialize(a.state.params_flat, a.state.sigma, pair_offs)
        db = a.engine._evaluate_double_buffered(thetas)

        # rebuild the same half-pools and replay through the sync algorithm
        from estorch_tpu.envs.native_pool import NativeEnvPool

        ref_fit = np.zeros(32, np.float32)
        for lo, seed in ((0, 0), (16, 10_007)):
            pool = NativeEnvPool("cartpole", 16, seed=seed)
            obs = pool.reset()
            alive = np.ones(16, bool)
            for _ in range(60):
                acts = np.asarray(
                    a.engine._batch_actions(thetas[lo:lo + 16], jnp.asarray(obs))
                )
                obs, rew, done = pool.step(acts)
                ref_fit[lo:lo + 16] += rew * alive
                alive &= ~done
                if not alive.any():
                    break
            pool.close()
        np.testing.assert_allclose(db.fitness, ref_fit, rtol=1e-5, atol=1e-6)

    def test_ns_es_on_pooled(self):
        es = self._make(cls=NS_ES, meta_population_size=2, k=3)
        es.train(2, verbose=False)
        assert len(es.archive) == 2 + 2
        assert es.history[-1]["archive_size"] == 4

    def test_vbn_on_pooled(self):
        es = self._make(
            policy_kwargs={"action_dim": 2, "hidden": (16,), "use_vbn": True},
        )
        es.train(1, verbose=False)
        assert "vbn_stats" in es._frozen


class TestGymVecPool:
    """Arbitrary gymnasium envs on the pooled path via the gym: prefix —
    device-batched inference for MuJoCo-class envs without MJX."""

    def test_pool_interface_over_gym_env(self):
        from estorch_tpu.envs.gym_vec_pool import make_pool

        pool = make_pool("gym:CartPole-v1", 6, seed=0)
        assert pool.obs_shape == (4,) and pool.discrete and pool.n_actions == 2
        obs = pool.reset()
        assert obs.shape == (6, 4)
        obs, rew, done = pool.step(np.ones((6, 1), np.float32))
        assert rew.shape == (6,) and done.shape == (6,)
        pool.close()

    def test_resets_draw_fresh_initial_states(self):
        """Regression: reseeding every reset would evaluate identical starts
        each generation; only the FIRST reset pins the seed."""
        from estorch_tpu.envs.gym_vec_pool import make_pool

        pool = make_pool("gym:CartPole-v1", 4, seed=0)
        a = pool.reset()
        b = pool.reset()
        assert not np.array_equal(a, b)
        pool.close()
        # determinism across pools still holds (same seed, same sequence)
        p1 = make_pool("gym:CartPole-v1", 4, seed=0)
        c = p1.reset()
        np.testing.assert_array_equal(a, c)
        p1.close()

    def test_pooled_es_on_gym_env(self):
        """Full pooled training over a gymnasium env (device-batched
        forwards, gym.vector stepping, psum update)."""
        es = self._mk_gym_es()
        es.train(4, verbose=False)
        assert es.backend == "pooled"
        first = es.history[0]["reward_mean"]
        last = es.history[-1]["reward_mean"]
        assert last > first, (first, last)

    def test_pooled_es_on_gym_mujoco(self):
        """MuJoCo (HalfCheetah) through the pooled path — BASELINE config 2's
        env with device-batched inference."""
        es = ES(
            policy=MLPPolicy, agent=PooledAgent, optimizer=optax.adam,
            population_size=8, sigma=0.05, seed=0,
            policy_kwargs={"action_dim": 6, "hidden": (16,), "discrete": False},
            agent_kwargs={"env_name": "gym:HalfCheetah-v5", "horizon": 30},
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 14,
            mesh=single_device_mesh(),
        )
        es.train(1, verbose=False)
        assert np.isfinite(es.history[0]["reward_mean"])
        assert es.history[0]["env_steps"] == 8 * 30  # cheetah never terminates

    @staticmethod
    def _mk_gym_es():
        return ES(
            policy=MLPPolicy, agent=PooledAgent, optimizer=optax.adam,
            population_size=16, sigma=0.1, seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (16,)},
            agent_kwargs={"env_name": "gym:CartPole-v1", "horizon": 100},
            optimizer_kwargs={"learning_rate": 3e-2},
            table_size=1 << 16,
        )

    def test_env_kwargs_reach_gym_make(self):
        """env_kwargs forward to gym.make — HalfCheetah with x-position in
        the observation (the BC the novelty locomotion family needs) grows
        obs_dim 17 → 18, consistently in spec probe AND pool."""
        from estorch_tpu.envs.gym_vec_pool import make_pool, pool_env_spec

        kw = {"exclude_current_positions_from_observation": False}
        spec = pool_env_spec("gym:HalfCheetah-v5", kw)
        assert spec["obs_dim"] == 18
        pool = make_pool("gym:HalfCheetah-v5", 2, seed=0, env_kwargs=kw)
        assert pool.obs_dim == 18
        pool.close()

    def test_env_kwargs_rejected_for_native(self):
        from estorch_tpu.envs.gym_vec_pool import make_pool

        with pytest.raises(ValueError, match="native"):
            make_pool("cartpole", 2, env_kwargs={"x": 1})

    def test_bc_indices_slice_the_final_obs(self):
        """bc_indices=(0,) → 1-dim BC everywhere the pooled path reports
        one: member evaluation, center evaluation, batched held-out eval."""
        es = ES(
            policy=MLPPolicy, agent=PooledAgent, optimizer=optax.adam,
            population_size=8, sigma=0.1, seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (8,)},
            agent_kwargs={"env_name": "cartpole", "horizon": 30,
                          "bc_indices": (0,)},
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 14,
            mesh=single_device_mesh(),
        )
        assert es.engine.bc_dim == 1
        ev = es.engine.evaluate(es.state)
        assert np.asarray(ev.bc).shape == (8, 1)
        c = es.engine.evaluate_center(es.state)
        assert np.asarray(c.bc).shape == (1,)
        det = es.evaluate_policy(n_episodes=3, return_details=True)
        assert det["bc"].shape == (3, 1)
        es.engine.pool.close()
        es.engine.center_pool.close()


class TestPong84ConvPath:
    """The Atari-config machinery (conv policy + pooled pixel env) end to
    end, using the bundled pong84 C++ env in place of ALE (BASELINE config 5
    stand-in)."""

    def test_pong84_env_semantics(self, native_available):
        pool = NativeEnvPool("pong84", 4, n_threads=2, seed=0)
        obs = pool.reset()
        assert obs.shape == (4, 84 * 84)
        assert pool.obs_shape == (84, 84, 1)
        assert pool.discrete and pool.n_actions == 3
        # pixels are binary {0, 1}
        assert set(np.unique(obs)).issubset({0.0, 1.0})
        # a still agent eventually concedes points (negative rewards), and
        # play CONTINUES past a point (multi-rally episodes, ALE-style)
        conceded = np.zeros(4)
        won = np.zeros(4)
        dones = np.zeros(4, bool)
        for _ in range(2000):
            _, r, d = pool.step(np.zeros((4, 1), np.float32))
            conceded += (r < 0)
            won += (r > 0)
            dones |= d
        # structural (not statistical): a still agent concedes far more than
        # the tracker does, and play continues past single points
        assert conceded.sum() > won.sum()
        assert np.any(conceded > 1)
        # first-to-21 match: no env may report done before conceding 21
        # (a still agent can still WIN points off tracker spin, so count
        # conceded, not net)
        for i in range(4):
            if dones[i]:
                assert conceded[i] >= 21
        pool.close()

    def test_pong84_match_runs_to_21(self, native_available):
        """done fires exactly at the 21st CONCEDED point (the still agent
        may also score a few off tracker spin — those don't end matches)."""
        pool = NativeEnvPool("pong84", 1, n_threads=1, seed=3)
        pool.reset()
        conceded, steps = 0, 0
        done = False
        while not done and steps < 60_000:
            _, r, d = pool.step(np.zeros((1, 1), np.float32))
            conceded += int(r[0] < 0.0)
            done = bool(d[0])
            steps += 1
        assert done, "match never ended"
        assert conceded == 21
        pool.close()

    def test_naturecnn_es_on_pong84(self, native_available):
        """Full conv rollout: NatureCNN population through the pooled path."""
        from estorch_tpu import NatureCNN
        from estorch_tpu.parallel import single_device_mesh

        es = ES(
            policy=NatureCNN,
            agent=PooledAgent,
            optimizer=optax.adam,
            population_size=4,
            sigma=0.05,
            seed=0,
            policy_kwargs={"action_dim": 3, "use_vbn": False},
            agent_kwargs={"env_name": "pong84", "horizon": 40},
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 21,  # NatureCNN ~1.7M params needs a larger table
            mesh=single_device_mesh(),  # pop 4 need not divide the 8-dev mesh
        )
        es.train(2, verbose=False)
        assert es.backend == "pooled"
        assert len(es.history) == 2
        assert es.history[-1]["env_steps"] > 0
        assert np.isfinite(es.history[-1]["reward_mean"])
