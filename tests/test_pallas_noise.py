"""Pallas streamed-noise kernels vs their pure-JAX twins (interpret mode).

The kernels must be bit-compatible REORDERINGS of existing math:
- weighted_noise_sum ≡ ops/gradient.py::rank_weighted_noise_sum
- population_noise_matvec ≡ the c·(x@E) noise term of models/decomposed.py
- mlp_streamed_apply ≡ mlp_decomposed_apply over a population batch

On CPU they run in interpret mode; the SAME code compiles to Mosaic on TPU
(bench.py A/Bs it on-chip when the chip is reachable).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from estorch_tpu.ops import make_noise_table, make_param_spec, rank_weighted_noise_sum
from estorch_tpu.ops.pallas_noise import (
    flat_layer_offsets,
    mlp_streamed_apply,
    population_noise_matvec,
    weighted_noise_sum,
)

TABLE = make_noise_table(1 << 16, seed=3)


class TestWeightedNoiseSum:
    @pytest.mark.parametrize("n,dim", [(1, 8), (7, 33), (64, 128), (33, 257)])
    def test_matches_pure_jax(self, n, dim):
        key = jax.random.key(n * 1000 + dim)
        offs = jax.random.randint(key, (n,), 0, TABLE.size - dim, dtype=jnp.int32)
        w = jax.random.normal(jax.random.fold_in(key, 1), (n,))
        got = weighted_noise_sum(TABLE.data, offs, w, dim=dim, interpret=True)
        want = rank_weighted_noise_sum(TABLE, offs, w, dim=dim)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_zero_weights_zero_sum(self):
        offs = jnp.array([5, 10, 15], jnp.int32)
        got = weighted_noise_sum(TABLE.data, offs, jnp.zeros(3), dim=16, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.zeros(16, np.float32))

    def test_single_row_is_scaled_slice(self):
        got = weighted_noise_sum(
            TABLE.data, jnp.array([42], jnp.int32), jnp.array([2.5]), dim=64,
            interpret=True,
        )
        want = 2.5 * np.asarray(TABLE.data[42:106])
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)

    def test_empty_input(self):
        got = weighted_noise_sum(
            TABLE.data, jnp.zeros((0,), jnp.int32), jnp.zeros((0,)), dim=8,
            interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got), np.zeros(8, np.float32))


class TestPopulationNoiseMatvec:
    @pytest.mark.parametrize("n,d,h", [(4, 8, 16), (6, 17, 5), (16, 32, 32), (3, 64, 7)])
    def test_matches_einsum_oracle(self, n, d, h):
        key = jax.random.key(n + 10 * d + 100 * h)
        offs = jax.random.randint(key, (n,), 0, TABLE.size - d * h - 64, dtype=jnp.int32)
        c = jax.random.normal(jax.random.fold_in(key, 1), (n,))
        x = jax.random.normal(jax.random.fold_in(key, 2), (n, d))
        layer_off = 32

        got = population_noise_matvec(
            TABLE.data, offs, c, x, layer_offset=layer_off, d=d, h=h, interpret=True
        )
        # oracle: materialize each member's E and einsum
        E = jax.vmap(
            lambda o: jax.lax.dynamic_slice(TABLE.data, (o + layer_off,), (d * h,))
        )(offs).reshape(n, d, h)
        want = c[:, None] * jnp.einsum("nd,ndh->nh", x, E)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )

    def test_explicit_block_rows(self):
        """A forced non-trivial row blocking must not change the result."""
        key = jax.random.key(0)
        n, d, h = 4, 12, 6
        offs = jax.random.randint(key, (n,), 0, TABLE.size - d * h, dtype=jnp.int32)
        c = jnp.ones((n,))
        x = jax.random.normal(jax.random.fold_in(key, 1), (n, d))
        a = population_noise_matvec(
            TABLE.data, offs, c, x, layer_offset=0, d=d, h=h,
            interpret=True, block_rows=3,
        )
        b = population_noise_matvec(
            TABLE.data, offs, c, x, layer_offset=0, d=d, h=h,
            interpret=True, block_rows=12,
        )
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    def test_indivisible_block_rows_rejected(self):
        with pytest.raises(ValueError, match="divide"):
            population_noise_matvec(
                TABLE.data, jnp.zeros((2,), jnp.int32), jnp.ones((2,)),
                jnp.ones((2, 10)), layer_offset=0, d=10, h=4,
                interpret=True, block_rows=3,
            )


class TestStreamedMLPForward:
    def _setup(self, n=6, obs_dim=5, hidden=(8, 8), act=3):
        from estorch_tpu.models import MLPPolicy

        module = MLPPolicy(action_dim=act, hidden=hidden, discrete=False)
        obs0 = jnp.zeros(obs_dim)
        params = module.init(jax.random.PRNGKey(0), obs0)["params"]
        flat, spec = make_param_spec(params)
        key = jax.random.key(9)
        offs = jax.random.randint(
            key, (n,), 0, TABLE.size - spec.dim, dtype=jnp.int32
        )
        c = 0.1 * jax.random.normal(jax.random.fold_in(key, 1), (n,))
        obs = jax.random.normal(jax.random.fold_in(key, 2), (n, obs_dim))
        return module, params, spec, offs, c, obs

    def test_matches_decomposed_apply(self):
        """Streamed forward ≡ pure-JAX decomposed forward, member by member."""
        from estorch_tpu.models.decomposed import mlp_decomposed_apply

        module, params, spec, offs, c, obs = self._setup()
        lo = flat_layer_offsets(params)
        got = mlp_streamed_apply(
            module, params, TABLE.data, offs, c, obs, lo, interpret=True
        )
        for i in range(obs.shape[0]):
            eps_tree = spec.unravel(TABLE.slice(offs[i], spec.dim))
            want_i = mlp_decomposed_apply(module, params, eps_tree, c[i], obs[i])
            np.testing.assert_allclose(
                np.asarray(got[i]), np.asarray(want_i), rtol=1e-4, atol=1e-5,
                err_msg=f"member {i}",
            )

    def test_matches_materialized_perturbation(self):
        """…and ≡ the STANDARD engine path: apply(θ + c·ε) directly."""
        module, params, spec, offs, c, obs = self._setup(hidden=(16,))
        lo = flat_layer_offsets(params)
        flat = spec.flatten(params)
        got = mlp_streamed_apply(
            module, params, TABLE.data, offs, c, obs, lo, interpret=True
        )
        for i in range(obs.shape[0]):
            theta = flat + c[i] * TABLE.slice(offs[i], spec.dim)
            want_i = module.apply({"params": spec.unravel(theta)}, obs[i])
            np.testing.assert_allclose(
                np.asarray(got[i]), np.asarray(want_i), rtol=1e-4, atol=1e-5,
                err_msg=f"member {i}",
            )

    def test_layer_offsets_cover_flat_vector(self):
        _, params, spec, *_ = self._setup()
        lo = flat_layer_offsets(params)
        total = sum(
            int(np.prod(leaf.shape))
            for leaf in jax.tree_util.tree_leaves(params)
        )
        assert total == spec.dim
        all_offs = sorted(o for layer in lo.values() for o in layer.values())
        assert all_offs[0] == 0
        assert all(b > a for a, b in zip(all_offs, all_offs[1:]))


class TestEngineNoiseKernel:
    """noise_kernel=True must reproduce the chunked pure-JAX update inside
    the real sharded generation program (8 virtual devices, interpret mode)."""

    def _engines(self, mirrored):
        import optax

        from estorch_tpu.envs import CartPole
        from estorch_tpu.parallel import EngineConfig, ESEngine, population_mesh

        def apply(p, obs):
            return jnp.tanh(obs @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]

        params = {
            "w1": jax.random.normal(jax.random.key(0), (4, 16)) * 0.5,
            "b1": jnp.zeros(16),
            "w2": jax.random.normal(jax.random.key(1), (16, 2)) * 0.5,
            "b2": jnp.zeros(2),
        }
        flat, spec = make_param_spec(params)
        out = []
        for nk in (False, True):
            cfg = EngineConfig(
                population_size=32, sigma=0.1, horizon=30,
                mirrored=mirrored, noise_kernel=nk,
            )
            out.append(
                ESEngine(CartPole(), apply, spec, TABLE,
                         optax.adam(1e-2), cfg, population_mesh())
            )
        return out, flat

    @pytest.mark.parametrize("mirrored", [True, False])
    def test_kernel_update_matches_pure_jax(self, mirrored, devices8):
        (ref, kern), flat = self._engines(mirrored)
        s_ref = ref.init_state(flat, jax.random.PRNGKey(5))
        s_k = kern.init_state(flat, jax.random.PRNGKey(5))
        for gen in range(2):
            s_ref, m_ref = ref.generation_step(s_ref)
            s_k, m_k = kern.generation_step(s_k)
            np.testing.assert_array_equal(
                np.asarray(m_ref["fitness"]), np.asarray(m_k["fitness"]),
                err_msg=f"gen {gen}",
            )
            np.testing.assert_allclose(
                np.asarray(s_ref.params_flat), np.asarray(s_k.params_flat),
                rtol=1e-5, atol=1e-6, err_msg=f"gen {gen}",
            )

    def test_streamed_engine_matches_standard(self, devices8):
        """The FULL streamed path (batched rollout + Pallas forward) must
        reproduce the standard engine's fitness and update on the mesh."""
        import optax

        from estorch_tpu import ES, JaxAgent, MLPPolicy
        from estorch_tpu.envs import CartPole

        def mk(**over):
            return ES(
                MLPPolicy, JaxAgent, optax.adam,
                population_size=32, sigma=0.1, seed=0,
                policy_kwargs={"action_dim": 2, "hidden": (16,)},
                agent_kwargs={"env": CartPole(), "horizon": 60},
                optimizer_kwargs={"learning_rate": 3e-2},
                table_size=1 << 16, **over,
            )

        std, stream = mk(), mk(streamed=True)
        for gen in range(2):
            std.train(1, verbose=False)
            stream.train(1, verbose=False)
            np.testing.assert_allclose(
                np.asarray(stream.state.params_flat),
                np.asarray(std.state.params_flat),
                rtol=2e-5, atol=1e-6, err_msg=f"gen {gen}",
            )
        # fitness recorded identically (CartPole argmax actions: float-
        # associativity can only flip near-ties, so allow tiny disagreement)
        f_std = [r["reward_mean"] for r in std.history]
        f_str = [r["reward_mean"] for r in stream.history]
        np.testing.assert_allclose(f_str, f_std, rtol=0.1)

    def test_streamed_learns(self, devices8):
        import optax

        from estorch_tpu import ES, JaxAgent, MLPPolicy
        from estorch_tpu.envs import CartPole

        es = ES(
            MLPPolicy, JaxAgent, optax.adam,
            population_size=32, sigma=0.1, seed=0,
            policy_kwargs={"action_dim": 2, "hidden": (16,)},
            agent_kwargs={"env": CartPole(), "horizon": 100},
            optimizer_kwargs={"learning_rate": 3e-2},
            table_size=1 << 16, streamed=True, noise_kernel=True,
        )
        es.train(8, verbose=False)
        first = es.history[0]["reward_mean"]
        last = es.history[-1]["reward_mean"]
        assert last > first + 15, (first, last)

    def test_streamed_rejected_on_pooled(self):
        """streamed must fail LOUDLY on the pooled path, not silently run
        the standard materialized forward."""
        import optax

        from estorch_tpu import ES, MLPPolicy, PooledAgent

        with pytest.raises(ValueError, match="streamed"):
            ES(
                MLPPolicy, PooledAgent, optax.adam,
                population_size=8, sigma=0.1,
                policy_kwargs={"action_dim": 2, "hidden": (8,)},
                agent_kwargs={"env_name": "cartpole", "horizon": 10},
                optimizer_kwargs={"learning_rate": 1e-2},
                table_size=1 << 14, streamed=True,
            )

    def test_rejected_on_host_backend(self):
        import torch

        from estorch_tpu import ES

        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = torch.nn.Linear(2, 2)

            def forward(self, x):
                return self.lin(x)

        class A:
            def rollout(self, policy):
                return 0.0

        with pytest.raises(ValueError, match="noise_kernel"):
            ES(P, A, torch.optim.Adam, population_size=4, noise_kernel=True)


def test_noise_kernel_rejects_dims_past_vmem_budget():
    """>1M params with noise_kernel=True must fail loudly at construction
    (3·dim f32 VMEM cost, parallel/engine.py::NOISE_KERNEL_MAX_DIM), not as
    an opaque Mosaic compile error inside the generation step."""
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import SyntheticEnv

    env = SyntheticEnv()  # obs 376: hidden 1024x1024 → ~1.45M params
    with pytest.raises(ValueError, match="noise_kernel.*1,000,000"):
        ES(
            policy=MLPPolicy,
            agent=JaxAgent,
            optimizer=optax.adam,
            population_size=8,
            policy_kwargs={"action_dim": env.action_dim,
                           "hidden": (1024, 1024), "discrete": False},
            agent_kwargs={"env": env, "horizon": 10},
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 21,
            noise_kernel=True,
        )
