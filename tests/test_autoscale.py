"""Autoscaler: the serving fleet closes its own control loop
(obs/agg/autoscale.py + serve/fleet.py, docs/serving.md "Autoscaling").

THE acceptance demo: an autoscaled 2-replica fleet under open-loop load
that TRIPLES mid-run scales up (warm — ``compiles_at_load == 0``),
keeps p99 inside the SLO with zero client errors/shed, survives a
declared ``kill_replica`` chaos event during the scale-up, scales back
down after the sustained low-watermark window with a DRAINED
retirement, and the append-only decision log replays bit-exactly from
its recorded inputs.

Around the demo: the pure policy step (:func:`decide` — demand
formula, per-direction cooldowns, burn-rate bypass/step, low-watermark
hysteresis), the capacity-artifact contract (loadgen writes what the
autoscaler validates; a bundle/platform mismatch is refused naming
both sides), decision-log replay + tamper detection + restart
adoption, the dash's desired-vs-actual columns, the fleet admin
``POST /scale`` surface, and drain-then-retire semantics pinned under
concurrent load against stdlib toy replicas.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from estorch_tpu.obs.agg import autoscale as azmod
from estorch_tpu.obs.agg.autoscale import (AutoscaleError, Autoscaler,
                                           POLICY_DEFAULTS, decide,
                                           read_decisions, replay,
                                           validate_capacity)
from estorch_tpu.obs.agg.dash import fleet_snapshot, render
from estorch_tpu.obs.agg.store import SeriesStore
from estorch_tpu.resilience.chaos import CHAOS_ENV
from estorch_tpu.serve.fleet import Fleet
from estorch_tpu.serve.loadgen import (CAPACITY_SCHEMA, capacity_sweep,
                                       run_load, write_capacity_artifact)
from estorch_tpu.serve.router import Router

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _policy(**kw) -> dict:
    p = {**POLICY_DEFAULTS, "max_rps_at_slo": 10.0, "min_replicas": 1,
         "max_replicas": 8, "headroom": 1.3, "window_s": 10.0,
         "up_cooldown_s": 5.0, "down_cooldown_s": 5.0,
         "low_watermark": 0.5, "low_hold_s": 4.0}
    p.update(kw)
    return p


def _inputs(ts=1000.0, offered=None, actual=2, burn=(), **kw) -> dict:
    d = {"ts": ts, "target": "t", "window_s": 10.0,
         "offered_rps": offered, "p99_ms": None, "queue_depth": 0.0,
         "actual_replicas": actual, "replicas_known": actual,
         "reported_desired": None, "alerts_active": list(burn),
         "burn_firing": list(burn)}
    d.update(kw)
    return d


def _fresh():
    return dict(azmod.FRESH_STATE)


# =====================================================================
# the pure policy step
# =====================================================================

class TestDecide:
    def test_demand_formula_scales_to_target(self):
        # 30 rps, 10 rps/replica, headroom 1.3 -> ceil(3.9) = 4
        v, s = decide(_inputs(offered=30.0, actual=2), _policy(),
                      _fresh())
        assert (v["action"], v["desired"], v["target"]) == ("up", 4, 4)
        assert v["reason"] == "demand"
        assert s["desired"] == 4 and s["last_up_ts"] == 1000.0

    def test_clamped_to_max_and_min(self):
        v, _ = decide(_inputs(offered=1000.0, actual=2),
                      _policy(max_replicas=5), _fresh())
        assert v["desired"] == 5
        v, _ = decide(_inputs(offered=0.1, actual=4),
                      _policy(min_replicas=3, low_hold_s=0.0),
                      {**_fresh(), "low_since": 900.0,
                       "desired": 4})
        assert v["desired"] >= 3

    def test_no_signal_holds(self):
        # offered None = the counter never reported in the window: a
        # controller with no signal must not move the fleet
        v, s = decide(_inputs(offered=None, actual=3), _policy(),
                      _fresh())
        assert v["action"] == "hold" and v["desired"] == 3
        assert v["utilization"] is None

    def test_up_cooldown_suppresses_but_state_remembers(self):
        st = {**_fresh(), "desired": 2, "last_up_ts": 998.0}
        v, s = decide(_inputs(offered=30.0, actual=2), _policy(), st)
        assert (v["action"], v["reason"]) == ("hold", "up_cooldown")
        assert s["desired"] == 2  # no phantom progress

    def test_burn_bypasses_up_cooldown_when_demand_agrees(self):
        st = {**_fresh(), "desired": 2, "last_up_ts": 999.5}
        v, _ = decide(_inputs(offered=30.0, actual=2,
                              burn=["p99-burn"]), _policy(), st)
        assert v["action"] == "up" and v["desired"] == 4
        assert v["reason"] == "demand+burn:p99-burn"

    def test_pure_burn_steps_one_per_cooldown_window(self):
        # demand satisfied (target <= cur) but the SLO burns: +1
        pol = _policy()
        v, s = decide(_inputs(offered=30.0, actual=6,
                              burn=["p99-burn"]),
                      pol, {**_fresh(), "desired": 6})
        assert (v["action"], v["desired"]) == ("up", 7)
        assert v["reason"] == "burn:p99-burn"
        # within the cooldown the next breach cannot add another
        v2, _ = decide(_inputs(ts=1002.0, offered=30.0, actual=7,
                               burn=["p99-burn"]), pol, s)
        assert (v2["action"], v2["reason"]) == ("hold", "burn_cooldown")
        # and at the ceiling it must hold, loudly
        v3, _ = decide(_inputs(offered=30.0, actual=8,
                               burn=["p99-burn"]),
                       pol, {**_fresh(), "desired": 8})
        assert (v3["action"], v3["reason"]) == ("hold", "burn_at_max")

    def test_low_watermark_needs_a_sustained_window(self):
        pol = _policy()
        st = {**_fresh(), "desired": 4}
        # tick 1: low utilization arms the timer, nothing moves
        v, st = decide(_inputs(ts=1000.0, offered=2.0, actual=4), pol,
                       st)
        assert (v["action"], v["reason"]) == ("hold",
                                              "low_watermark_arming")
        # tick 2: still inside low_hold_s -> holding
        v, st = decide(_inputs(ts=1002.0, offered=2.0, actual=4), pol,
                       st)
        assert (v["action"], v["reason"]) == ("hold",
                                              "low_watermark_holding")
        # tick 3: sustained past low_hold_s -> ONE step down
        v, st = decide(_inputs(ts=1005.0, offered=2.0, actual=4), pol,
                       st)
        assert (v["action"], v["desired"]) == ("down", 3)
        assert st["last_down_ts"] == 1005.0
        # the step re-armed the window: an immediate repeat must hold
        v, st = decide(_inputs(ts=1006.0, offered=2.0, actual=3), pol,
                       st)
        assert v["action"] == "hold"

    def test_utilization_blip_resets_the_low_window(self):
        pol = _policy()
        st = {**_fresh(), "desired": 4}
        _, st = decide(_inputs(ts=1000.0, offered=2.0, actual=4), pol,
                       st)
        assert st["low_since"] == 1000.0
        # a burst above the watermark clears the armed timer
        _, st = decide(_inputs(ts=1002.0, offered=25.0, actual=4), pol,
                       st)
        assert st["low_since"] is None
        v, st = decide(_inputs(ts=1006.0, offered=2.0, actual=4), pol,
                       st)
        assert v["reason"] == "low_watermark_arming"  # from scratch

    def test_hysteresis_dead_band_holds(self):
        # target says 3 < cur 4, but utilization (0.55) sits ABOVE the
        # low watermark: inside the dead band nothing moves — this gap
        # is what keeps flapping from thrashing the fleet
        v, s = decide(_inputs(offered=22.0, actual=4), _policy(),
                      {**_fresh(), "desired": 4})
        assert (v["action"], v["reason"]) == ("hold", "steady")
        assert s["low_since"] is None

    def test_down_cooldown_gates_consecutive_steps(self):
        pol = _policy()
        st = {**_fresh(), "desired": 4, "last_down_ts": 1001.0,
              "low_since": 990.0}
        v, _ = decide(_inputs(ts=1003.0, offered=2.0, actual=4), pol,
                      st)
        assert (v["action"], v["reason"]) == ("hold", "down_cooldown")

    def test_decide_is_pure_and_json_stable(self):
        inp, pol, st = _inputs(offered=30.0), _policy(), _fresh()
        a = decide(inp, pol, st)
        b = decide(json.loads(json.dumps(inp)),
                   json.loads(json.dumps(pol)),
                   json.loads(json.dumps(st)))
        assert json.dumps(a, sort_keys=True) == \
            json.dumps(b, sort_keys=True)


# =====================================================================
# capacity artifact: loadgen writes, autoscale validates
# =====================================================================

def _sweep(max_rps=40.0):
    return {"slo_ms": 50.0, "quantile": "p99", "max_rps_at_slo": max_rps,
            "saturated": False,
            "rungs": [{"offered_rps": max_rps, "requests": 10,
                       "ok": True}]}


class TestCapacityArtifact:
    def test_schema_constants_locked(self):
        # the writer (serve/loadgen.py) and the validator
        # (obs/agg/autoscale.py) must move their schema together
        assert CAPACITY_SCHEMA == azmod.CAPACITY_SCHEMA

    def test_writer_output_passes_the_validator(self, tmp_path):
        path = str(tmp_path / "capacity.json")
        art = write_capacity_artifact(_sweep(), path)
        assert validate_capacity(art) == []
        with open(path) as f:
            on_disk = json.load(f)
        assert validate_capacity(on_disk) == []
        assert on_disk["max_rps_at_slo"] == 40.0
        assert azmod.load_capacity(path)["kind"] == "capacity"

    def test_bundle_identity_stamped_from_manifest(self, tmp_path):
        bundle = tmp_path / "bundle"
        bundle.mkdir()
        (bundle / "MANIFEST.json").write_text(json.dumps({
            "version": 3, "sha256": {"arrays.npz": "ab" * 32},
            "warm": {"platform": "cpu"}}))
        art = write_capacity_artifact(_sweep(),
                                      str(tmp_path / "c.json"),
                                      bundle=str(bundle))
        assert art["bundle_sha"] == "ab" * 32
        assert art["bundle_version"] == 3
        assert art["platform"] == "cpu"

    def test_unreadable_bundle_manifest_is_an_error(self, tmp_path):
        with pytest.raises(ValueError, match="MANIFEST"):
            write_capacity_artifact(_sweep(), str(tmp_path / "c.json"),
                                    bundle=str(tmp_path / "nope"))

    def test_saturated_model_is_refused(self, tmp_path):
        path = str(tmp_path / "capacity.json")
        write_capacity_artifact(_sweep(max_rps=None), path)
        with pytest.raises(AutoscaleError, match="saturated"):
            azmod.load_capacity(path)

    def test_mismatch_refusal_names_both_sides(self, tmp_path):
        store = str(tmp_path / "store")
        SeriesStore(store).append(
            [{"name": "estorch_up", "labels": {"target": "t"},
              "value": 1.0}], ts=1000.0)
        cap = {"schema": CAPACITY_SCHEMA, "kind": "capacity",
               "created_ts": 0.0, "slo_ms": 50.0, "quantile": "p99",
               "max_rps_at_slo": 40.0, "saturated": False,
               "rungs": [{}], "bundle_sha": "ab" * 32,
               "bundle_version": 1, "platform": "cpu"}
        cap_path = tmp_path / "capacity.json"
        cap_path.write_text(json.dumps(cap))
        with pytest.raises(AutoscaleError) as ei:
            Autoscaler(store, capacity=str(cap_path),
                       fleet_identity={"bundle_sha": "cd" * 32,
                                       "platform": "cpu",
                                       "bundle": "/f"}, dry_run=True)
        msg = str(ei.value)
        assert ("ab" * 6)[:12] in msg and ("cd" * 6)[:12] in msg
        # platform mismatch names both platforms
        with pytest.raises(AutoscaleError) as ei:
            Autoscaler(store, capacity=str(cap_path),
                       fleet_identity={"bundle_sha": "ab" * 32,
                                       "platform": "tpu"},
                       dry_run=True)
        assert "'cpu'" in str(ei.value) and "'tpu'" in str(ei.value)
        # a matching identity constructs cleanly
        Autoscaler(store, capacity=str(cap_path),
                   fleet_identity={"bundle_sha": "ab" * 32,
                                   "platform": "cpu"}, dry_run=True)


# =====================================================================
# decision log: replay, tamper, restart adoption
# =====================================================================

def _seed(store, ts, total, replicas, target="t"):
    rows = [{"name": "estorch_router_requests_total",
             "labels": {"target": target}, "value": float(total)}]
    for i in range(replicas):
        rows.append({"name": "estorch_router_replica_up",
                     "labels": {"target": target, "replica": f"r{i}"},
                     "value": 1.0})
    store.append(rows, ts=ts)


def _cap_file(tmp_path, max_rps=10.0):
    path = tmp_path / "capacity.json"
    path.write_text(json.dumps({
        "schema": CAPACITY_SCHEMA, "kind": "capacity", "created_ts": 0.0,
        "slo_ms": 50.0, "quantile": "p99",
        "max_rps_at_slo": float(max_rps), "saturated": False,
        "rungs": [{}]}))
    return str(path)


class TestDecisionLog:
    def test_replay_is_bit_exact_and_detects_tampering(self, tmp_path):
        store = SeriesStore(str(tmp_path / "store"))
        t0 = 1000.0
        _seed(store, t0, 0.0, 2)
        _seed(store, t0 + 10, 300.0, 2)
        acts = []
        az = Autoscaler(str(tmp_path / "store"),
                        capacity=_cap_file(tmp_path),
                        actuate=lambda n, r: acts.append((n, r))
                        or {"ok": True},
                        policy={"window_s": 10.0, "min_replicas": 2})
        ev = az.tick(now=t0 + 10)
        assert ev["verdict"]["action"] == "up"
        assert acts == [(4, "demand")]
        rep = replay(az.log_path)
        assert rep["ok"] and rep["decisions"] == 1
        # flip one recorded verdict: replay must flag exactly it
        rows = [json.loads(ln) for ln in open(az.log_path)]
        rows[0]["verdict"]["desired"] = 99
        bad = tmp_path / "tampered.jsonl"
        bad.write_text("".join(json.dumps(r) + "\n" for r in rows))
        rep = replay(str(bad))
        assert not rep["ok"]
        assert rep["mismatches"][0]["kind"] == "verdict"

    def test_restart_adopts_logged_state(self, tmp_path):
        store = SeriesStore(str(tmp_path / "store"))
        t0 = 1000.0
        _seed(store, t0, 0.0, 2)
        _seed(store, t0 + 10, 300.0, 2)
        az = Autoscaler(str(tmp_path / "store"),
                        capacity=_cap_file(tmp_path),
                        actuate=lambda n, r: {"ok": True},
                        policy={"window_s": 10.0, "min_replicas": 2})
        az.tick(now=t0 + 10)
        state = dict(az.state)
        assert state["last_up_ts"] == t0 + 10
        # a fresh daemon over the same log resumes the SAME controller:
        # cooldowns survive the restart, and the replayed state chain
        # stays unbroken
        az2 = Autoscaler(str(tmp_path / "store"),
                         capacity=_cap_file(tmp_path),
                         actuate=lambda n, r: {"ok": True},
                         policy={"window_s": 10.0, "min_replicas": 2})
        assert az2.state == state
        _seed(store, t0 + 12, 700.0, 4)
        ev = az2.tick(now=t0 + 12)
        assert ev["verdict"]["reason"] == "up_cooldown"
        rep = replay(az2.log_path)
        assert rep["ok"] and rep["decisions"] == 2

    def test_torn_tail_line_is_skipped(self, tmp_path):
        log = tmp_path / "autoscale_decisions.jsonl"
        ev = {"schema": 1, "ts": 1.0, "event": "decision", "target": "t",
              "inputs": _inputs(), "policy": _policy(),
              "state_before": _fresh(),
              "verdict": decide(_inputs(), _policy(), _fresh())[0],
              "state_after": decide(_inputs(), _policy(), _fresh())[1]}
        log.write_text(json.dumps(ev) + "\n" + '{"schema": 1, "ev')
        assert len(read_decisions(str(log))) == 1
        assert replay(str(log))["ok"]


# =====================================================================
# dash columns from the store + decision log alone
# =====================================================================

class TestDashColumns:
    def _store_with_router(self, root, desired=5.0, up=3):
        s = SeriesStore(root)
        rows = [{"name": "estorch_up", "labels": {"target": "fleet"},
                 "value": 1.0},
                {"name": "estorch_router_desired_replicas",
                 "labels": {"target": "fleet"}, "value": desired}]
        for i in range(up):
            rows.append({"name": "estorch_router_replica_up",
                         "labels": {"target": "fleet",
                                    "replica": f"r{i}"}, "value": 1.0})
        s.append(rows, ts=1000.0)
        s.append([{"name": "estorch_up", "labels": {"target": "plain"},
                   "value": 1.0}], ts=1000.0)
        return s

    def test_desired_vs_actual_and_decision_age(self, tmp_path):
        root = str(tmp_path / "store")
        self._store_with_router(root)
        with open(os.path.join(root, azmod.DECISIONS_FILENAME),
                  "a") as f:
            f.write(json.dumps({
                "schema": 1, "ts": 997.0, "event": "decision",
                "target": "fleet", "verdict": {"action": "up",
                                               "desired": 5}}) + "\n")
        snap = fleet_snapshot(root, window_s=60.0, now=1001.0)
        rows = {r["target"]: r for r in snap["targets"]}
        assert rows["fleet"]["autoscale"] == {
            "desired": 5, "actual": 3, "last_decision_ts": 997.0,
            "decision_age_s": 4.0, "last_action": "up"}
        # a target with no router gauges and no decisions: honest None
        assert rows["plain"]["autoscale"] is None
        out = render(root, window_s=60.0, now=1001.0)
        assert "3→5" in out and "4s" in out
        plain_line = next(ln for ln in out.splitlines()
                          if ln.startswith("plain"))
        assert "→" not in plain_line

    def test_converged_fleet_shows_bare_count(self, tmp_path):
        root = str(tmp_path / "store")
        self._store_with_router(root, desired=3.0, up=3)
        snap = fleet_snapshot(root, window_s=60.0, now=1001.0)
        row = next(r for r in snap["targets"]
                   if r["target"] == "fleet")
        assert row["autoscale"]["desired"] == 3
        assert row["autoscale"]["actual"] == 3
        # no decision log at all: age honestly unknown
        assert row["autoscale"]["decision_age_s"] is None
        assert "→" not in render(root, window_s=60.0, now=1001.0)


# =====================================================================
# toy replicas: /scale surface + drain-then-retire under load
# =====================================================================

def make_toy_replica(*, delay_s: float = 0.0):
    state = {"requests": 0}

    class Toy(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _j(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._j(200, {"ok": True, "draining": False,
                              "queue_depth": 0})
            else:
                self._j(200, {"queue_depth": 0,
                              "request_ms": {"p99": 1.0}})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            data = json.loads(self.rfile.read(n))
            state["requests"] += 1
            if delay_s:
                time.sleep(delay_s)
            self._j(200, {"action": [v * 2.0 for v in data["obs"]]})

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Toy)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, state


def _post(url, payload, timeout=15):
    req = urllib.request.Request(url, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read()), dict(r.headers)


class TestScaleSurface:
    def _router(self, replicas, **kw):
        kw.setdefault("port", 0)
        kw.setdefault("poll_interval_s", 0.1)
        r = Router(replicas, **kw)
        r.start_background()
        return r

    def test_scale_without_a_fleet_is_409(self):
        srv, _ = make_toy_replica()
        router = self._router(
            [("ra", f"127.0.0.1:{srv.server_address[1]}")])
        try:
            url = f"http://{router.host}:{router.port}"
            with urllib.request.urlopen(url + "/scale",
                                        timeout=10) as r:
                assert json.loads(r.read()) == {"supported": False}
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(url + "/scale", {"replicas": 3})
            assert ei.value.code == 409
            assert "fleet" in json.loads(ei.value.read())["error"]
        finally:
            router.shutdown(drain=False)
            srv.shutdown()

    def test_scale_payload_validation(self):
        srv, _ = make_toy_replica()
        calls = []
        router = self._router(
            [("ra", f"127.0.0.1:{srv.server_address[1]}")],
            scale_cb=lambda op, data: calls.append((op, data))
            or {"ok": True, "accepted": True})
        try:
            url = f"http://{router.host}:{router.port}"
            for bad in ({"replicas": "three"}, {"replicas": 0},
                        {"replicas": True}, {}):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post(url + "/scale", bad)
                assert ei.value.code == 400, bad
            assert calls == []  # junk never reached the fleet
            out, _ = _post(url + "/scale", {"replicas": 3})
            assert out["ok"] and calls[-1][0] == "set"
        finally:
            router.shutdown(drain=False)
            srv.shutdown()

    def test_retire_deselects_before_kill_under_load(self):
        """Satellite: the router stops selecting a retiring replica
        BEFORE the kill, everything in flight is answered, and the
        concurrent load sees zero errors."""
        srv_a, state_a = make_toy_replica(delay_s=0.02)
        srv_b, state_b = make_toy_replica(delay_s=0.02)
        router = self._router(
            [("ra", f"127.0.0.1:{srv_a.server_address[1]}"),
             ("rb", f"127.0.0.1:{srv_b.server_address[1]}")])
        errors = []
        stop = threading.Event()

        def loader():
            url = f"http://{router.host}:{router.port}/predict"
            while not stop.is_set():
                try:
                    out, _ = _post(url, {"obs": [1.0]})
                    if out.get("action") != [2.0]:
                        errors.append(out)
                except Exception as e:  # noqa: BLE001 — the assertion
                    errors.append(repr(e))
        threads = [threading.Thread(target=loader) for _ in range(6)]
        try:
            time.sleep(0.25)  # health poll marks both replicas up
            for t in threads:
                t.start()
            time.sleep(0.5)
            assert state_b["requests"] > 0  # rb carries load pre-retire
            assert router.retire_replica("rb")
            # wait for rb's in-flight to drain, then freeze its count
            rep = {r.name: r for r in router.replicas()}["rb"]
            deadline = time.monotonic() + 10
            while rep.inflight > 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert rep.inflight == 0
            drained_count = state_b["requests"]
            time.sleep(0.6)  # load keeps hammering the router
            # deselected: NOTHING new reached the retiring replica,
            # while the survivor kept answering
            assert state_b["requests"] == drained_count
            before_a = state_a["requests"]
            time.sleep(0.3)
            assert state_a["requests"] > before_a
            # only now would the fleet kill the process; forget it
            assert router.remove_replica("rb")
            assert "rb" not in {r.name for r in router.replicas()}
            time.sleep(0.3)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            router.shutdown(drain=False)
            srv_a.shutdown()
            srv_b.shutdown()
        assert not errors, errors[:5]
        snap = router.stats()
        assert snap["counters"].get("router_replicas_retired_total") == 1


# =====================================================================
# file-run probe: the autoscaler is stdlib-only and jax-free
# =====================================================================

class TestFileRun:
    def test_autoscale_file_run_never_imports_package_or_jax(self):
        path = os.path.join(REPO, "estorch_tpu", "obs", "agg",
                            "autoscale.py")
        probe = (
            "import importlib.util, sys\n"
            f"spec = importlib.util.spec_from_file_location('a', "
            f"{path!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "assert 'jax' not in sys.modules, 'autoscale imported jax'\n"
            "assert 'estorch_tpu' not in sys.modules, 'package init "
            "ran'\n"
            "assert m.selfcheck() == 0\n"
            "assert 'jax' not in sys.modules\n"
        )
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=120,
                           cwd=REPO)
        assert r.returncode == 0, r.stdout + r.stderr


# =====================================================================
# THE acceptance demo: the loop closes end to end
# =====================================================================

SMALL_PK = {"action_dim": 1, "hidden": (24, 24), "discrete": False,
            "action_scale": 2.0}


@pytest.fixture(scope="module")
def warm_bundle(tmp_path_factory):
    import jax
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs.pendulum import Pendulum

    root = tmp_path_factory.mktemp("autoscale_bundle")
    es = ES(MLPPolicy, JaxAgent(Pendulum(), horizon=10), optax.adam,
            population_size=8, sigma=0.05, seed=0,
            policy_kwargs=dict(SMALL_PK),
            optimizer_kwargs={"learning_rate": 1e-2},
            table_size=1 << 14, device=jax.devices()[0])
    es.train(1, verbose=False)
    return es.export_bundle(str(root / "bundle"), warm=True,
                            warm_max_batch=4)


class TestAutoscaleDemo:
    def test_load_triples_fleet_tracks_and_log_replays(
            self, warm_bundle, tmp_path, monkeypatch):
        from estorch_tpu.obs.agg.collector import Collector, Target

        slo_ms = 2000.0
        fleet = Fleet(
            {"schema": 1, "bundle": warm_bundle, "replicas": 2,
             "serve": {"max_batch": 4, "cpu_devices": 8},
             "router": {"retry_budget": 2, "breaker_open_s": 0.5},
             "respawn": {"backoff_s": 0.2},
             "autoscale": {"min_replicas": 2, "max_replicas": 4}},
            str(tmp_path / "run"), port=0)
        store_dir = str(tmp_path / "store")
        col_stop = threading.Event()
        col_thread = scaler = None
        try:
            fleet.start()
            assert fleet.wait_ready(180), fleet.status()
            # INITIAL spawns carry the warmth proof (satellite: the
            # same bar the respawn path is held to)
            for slot in fleet.slots:
                assert (slot.cold_start or {}).get(
                    "compiles_at_load") == 0, fleet.status()
            addr = f"{fleet.router.host}:{fleet.router.port}"

            # capacity model from a REAL sweep against one replica
            sweep = capacity_sweep(fleet.slots[0].address,
                                   slo_ms=slo_ms, rps_ladder=[40.0],
                                   conns=8, rung_duration_s=1.0,
                                   obs=[0.1, 0.2, 0.3])
            assert sweep["max_rps_at_slo"] == 40.0, sweep
            cap_path = str(tmp_path / "capacity.json")
            write_capacity_artifact(sweep, cap_path,
                                    bundle=warm_bundle)

            # in-process collector: the autoscaler reads the STORE,
            # never the fleet
            col = Collector(
                [Target("fleet", url=f"http://{addr}/metrics",
                        timeout_s=5.0)],
                SeriesStore(store_dir), None, serve_http=False)

            def scrape():
                while not col_stop.is_set():
                    col.tick()
                    col_stop.wait(0.3)
            col_thread = threading.Thread(target=scrape, daemon=True)
            col_thread.start()

            scaler = Autoscaler(
                store_dir, capacity=cap_path, fleet_admin=addr,
                interval_s=0.4,
                policy={"min_replicas": 2, "max_replicas": 4,
                        "headroom": 1.2, "window_s": 5.0,
                        "up_cooldown_s": 3.0, "down_cooldown_s": 4.0,
                        "low_watermark": 0.5, "low_hold_s": 3.0})
            scaler.start_background()

            # chaos declared once the fleet serves: the kill lands in
            # the spike phase, i.e. during/just after the scale-up
            monkeypatch.setenv(CHAOS_ENV, json.dumps({
                "events": [{"kind": "kill_replica", "at_s": 6.5,
                            "replica": 1}],
                "ledger": str(tmp_path / "chaos_ledger")}))
            fleet.arm_chaos()

            # baseline the floor absorbs -> load TRIPLES -> trickle
            base = run_load(addr, mode="open", target_rps=25.0,
                            duration_s=4.0, conns=8,
                            obs=[0.1, 0.2, 0.3])
            spike = run_load(addr, mode="open", target_rps=75.0,
                             duration_s=9.0, conns=16,
                             obs=[0.1, 0.2, 0.3])
            scaled_up = False
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                sc = fleet.status()["scale"]
                if sc["desired"] > 2 and sc["actual"] >= sc["desired"]:
                    scaled_up = True
                    break
                time.sleep(0.2)
            assert scaled_up, fleet.status()["scale"]
            trickle = run_load(addr, mode="open", target_rps=4.0,
                               duration_s=12.0, conns=4,
                               obs=[0.1, 0.2, 0.3])
            scaled_down = False
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                sc = fleet.status()["scale"]
                if sc["desired"] == 2 and sc["actual"] == 2:
                    scaled_down = True
                    break
                time.sleep(0.2)
            assert scaled_down, fleet.status()["scale"]
            scaler.stop()

            # zero client errors/shed and p99 inside the SLO through
            # every phase — including across the kill and the retire
            for name, load in (("base", base), ("spike", spike),
                               ("trickle", trickle)):
                assert load["errors"] == 0, (name, load)
                assert load["shed"] == 0, (name, load)
                assert load["latency_ms"]["p99"] <= slo_ms, (name, load)
            assert spike["requests"] >= 300

            events = [e["event"] for e in fleet.events]
            assert "chaos_kill_replica" in events  # the kill DID land
            assert "scale_up_warm" in events
            assert "scale_up_cold" not in events
            retired = [e for e in fleet.events
                       if e["event"] == "replica_retired"]
            assert retired and retired[-1]["drained"], retired
            assert retired[-1]["exitcode"] == 0

            # the decision log replays bit-exactly from its inputs
            rep = replay(scaler.log_path)
            assert rep["ok"], rep["mismatches"][:3]
            assert rep["decisions"] >= 10

            # and the dash sees it all from the store + log alone
            snap = fleet_snapshot(store_dir, window_s=60.0)
            row = next(r for r in snap["targets"]
                       if r["target"] == "fleet")
            assert row["autoscale"] is not None
            assert row["autoscale"]["desired"] == 2
            assert row["autoscale"]["decision_age_s"] is not None
        finally:
            if scaler is not None:
                scaler.stop()
            col_stop.set()
            if col_thread is not None:
                col_thread.join(timeout=10)
            fleet.shutdown()


# =====================================================================
# fleet config: the autoscale block validates
# =====================================================================

class TestAutoscaleConfig:
    def test_autoscale_block_validates(self, tmp_path):
        from estorch_tpu.serve.fleet import validate_fleet_config

        base = {"schema": 1, "bundle": str(tmp_path), "replicas": 2}
        assert validate_fleet_config(
            {**base, "autoscale": {"min_replicas": 2,
                                   "max_replicas": 4}}) == []
        assert any("min_replicas" in p for p in validate_fleet_config(
            {**base, "autoscale": {"min_replicas": 0}}))
        assert any("max_replicas" in p for p in validate_fleet_config(
            {**base, "autoscale": {"min_replicas": 3,
                                   "max_replicas": 2}}))

    def test_cli_autoscale_flag_requires_store_and_capacity(
            self, tmp_path):
        cfg = tmp_path / "fleet.json"
        cfg.write_text(json.dumps(
            {"schema": 1, "bundle": str(tmp_path), "replicas": 2}))
        r = subprocess.run(
            [sys.executable, "-m", "estorch_tpu.serve.fleet",
             "--fleet", str(cfg), "--autoscale"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 2
        assert "autoscale block" in r.stderr, r.stdout + r.stderr
