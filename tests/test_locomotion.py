"""Pure-JAX planar locomotion envs (envs/locomotion.py).

Covers: the JaxEnv contract under jit/scan, geometric consistency of the
solved init pose, integration stability under random torques, termination
semantics, and end-to-end ES learnability on the swimmer (the device-native
MuJoCo-class path the round-1 verdict called for).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from estorch_tpu.envs import (Cheetah2D, DeceptiveValley, Hopper2D,
                              Humanoid2D, Swimmer2D, Walker2D, make_rollout)
from estorch_tpu.envs.locomotion import _anchor_world

ENVS = [Swimmer2D, Hopper2D, Walker2D, Humanoid2D, Cheetah2D]


@pytest.mark.parametrize("Env", ENVS)
class TestContract:
    def test_reset_and_obs_shape(self, Env):
        env = Env()
        state, obs = env.reset(jax.random.key(0))
        assert obs.shape == (env.obs_dim,)
        assert np.all(np.isfinite(np.asarray(obs)))

    def test_step_jits_and_shapes(self, Env):
        env = Env()
        state, obs = env.reset(jax.random.key(0))
        step = jax.jit(env.step)
        state, obs, r, d = step(state, jnp.zeros(env.action_dim))
        assert obs.shape == (env.obs_dim,)
        assert r.shape == () and d.shape == ()
        assert d.dtype == jnp.bool_

    def test_rollout_scan_compiles(self, Env):
        env = Env()

        def policy(params, obs):
            return jnp.tanh(params["w"] @ obs)

        rollout = make_rollout(env, policy, horizon=25)
        params = {"w": 0.1 * jax.random.normal(jax.random.key(0),
                                               (env.action_dim, env.obs_dim))}
        res = jax.jit(rollout)(params, jax.random.key(1))
        assert np.isfinite(float(res.total_reward))
        assert res.bc.shape == (env.bc_dim,)

    def test_determinism(self, Env):
        env = Env()
        s1, o1 = env.reset(jax.random.key(7))
        s2, o2 = env.reset(jax.random.key(7))
        a = jnp.full((env.action_dim,), 0.3)
        _, o1b, r1, _ = env.step(s1, a)
        _, o2b, r2, _ = env.step(s2, a)
        np.testing.assert_array_equal(np.asarray(o1b), np.asarray(o2b))
        assert float(r1) == float(r2)

    def test_init_joint_anchors_coincide(self, Env):
        """_solve_init_positions must leave zero anchor gap at every joint
        (gaps become huge t=0 spring forces)."""
        env = Env()
        ch = env.chain
        pos = jnp.asarray(ch.init_pos, jnp.float32)
        theta = jnp.asarray(ch.init_angle, jnp.float32)
        half = jnp.asarray(ch.half_len)
        pj = jnp.asarray(ch.parent, jnp.int32)
        cj = jnp.asarray(ch.child, jnp.int32)
        a, _ = _anchor_world(pos[pj], theta[pj], half[pj], jnp.asarray(ch.parent_end))
        b, _ = _anchor_world(pos[cj], theta[cj], half[cj], jnp.asarray(ch.child_end))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_stable_under_random_torques(self, Env):
        """300 control steps of uniform random actions: finite and bounded
        (the explicit-integration stability criterion, empirically)."""
        env = Env()
        state, obs = env.reset(jax.random.key(0))

        def body(carry, key):
            state, _ = carry
            a = jax.random.uniform(key, (env.action_dim,), minval=-1.0, maxval=1.0)
            state, obs, r, d = env.step(state, a)
            return (state, obs), obs

        keys = jax.random.split(jax.random.key(1), 300)
        (_, obs), all_obs = jax.lax.scan(body, (state, obs), keys)
        assert np.all(np.isfinite(np.asarray(all_obs)))
        assert float(jnp.max(jnp.abs(all_obs))) < 50.0


class TestSemantics:
    def test_hopper_terminates_on_fall(self):
        env = Hopper2D()
        state, _ = env.reset(jax.random.key(0))
        # drop the torso below the height threshold
        state = dict(state, pos=state["pos"].at[0, 1].set(0.3))
        _, _, _, done = env.step(state, jnp.zeros(env.action_dim))
        assert bool(done)

    def test_swimmer_needs_actuation_to_move(self):
        """No gravity, no contact: with zero torques the swimmer must stay
        essentially where it started (drag kills the reset-noise drift)."""
        env = Swimmer2D()
        state, _ = env.reset(jax.random.key(0))
        step = jax.jit(env.step)
        for _ in range(100):
            state, obs, r, d = step(state, jnp.zeros(env.action_dim))
        assert abs(float(state["pos"][0, 0])) < 0.15

    def test_swimmer_undulation_propels(self):
        """A hand-written traveling-wave gait must produce net displacement
        an order of magnitude beyond the passive case — the anisotropic
        drag actually converts undulation into thrust."""
        env = Swimmer2D()
        state, _ = env.reset(jax.random.key(0))
        step = jax.jit(env.step)
        for t in range(150):
            phase = 2 * jnp.pi * t / 25.0
            a = 0.9 * jnp.sin(phase + jnp.arange(env.action_dim) * 2.0)
            state, obs, r, d = step(state, a)
        assert abs(float(state["pos"][0, 0])) > 0.5

    def test_walker_terminates_on_fall_and_lean(self):
        env = Walker2D()
        state, _ = env.reset(jax.random.key(0))
        dropped = dict(state, pos=state["pos"].at[0, 1].set(0.4))
        _, _, _, done = env.step(dropped, jnp.zeros(env.action_dim))
        assert bool(done)
        # the stiff joints pull a teleported torso back toward the legs
        # within one control step, so overshoot the 1.0 threshold
        leaned = dict(state, theta=state["theta"].at[0].add(1.6))
        _, _, _, done = env.step(leaned, jnp.zeros(env.action_dim))
        assert bool(done)

    def test_walker_stands_briefly_unactuated(self):
        """The asymmetric-but-planted init must not fall within the first
        few control steps with zero torque — a policy gets a fair chance to
        act before gravity decides (falling WILL happen eventually; the
        alive bonus exists because standing is nontrivial)."""
        env = Walker2D()
        state, _ = env.reset(jax.random.key(0))
        step = jax.jit(env.step)
        for _ in range(5):
            state, obs, r, done = step(state, jnp.zeros(env.action_dim))
            assert np.all(np.isfinite(np.asarray(obs)))
        assert not bool(done)

    def test_humanoid_stands_briefly_and_terminates_on_fall(self):
        """Same fair-chance contract as the walker, plus the drop check —
        the tallest chain must still start planted and upright."""
        env = Humanoid2D()
        state, _ = env.reset(jax.random.key(0))
        step = jax.jit(env.step)
        s = state
        for _ in range(5):
            s, obs, r, done = step(s, jnp.zeros(env.action_dim))
            assert np.all(np.isfinite(np.asarray(obs)))
        assert not bool(done)
        dropped = dict(state, pos=state["pos"].at[0, 1].set(0.4))
        _, _, _, done = env.step(dropped, jnp.zeros(env.action_dim))
        assert bool(done)

    def test_cheetah_settles_without_penetration(self):
        """Zero action: an unactuated torque-controlled cheetah slumps (as
        in MuJoCo) — but it must come to REST on the ground plane, not sink
        through it or jitter forever on the contact springs."""
        env = Cheetah2D()
        state, _ = env.reset(jax.random.key(0))
        step = jax.jit(env.step)
        for _ in range(200):
            state, obs, r, d = step(state, jnp.zeros(env.action_dim))
        ys = np.asarray(state["pos"][:, 1])
        assert np.all(ys > -0.05), ys  # nothing through the floor
        ke = float(jnp.sum(state["vel"] ** 2))
        assert ke < 0.1, ke  # settled, no contact chatter


class TestLearnability:
    @pytest.mark.slow
    def test_swimmer_es_improves(self):
        """ES on the device path must lift the swimmer's mean return well
        above the passive score within a small generation budget."""
        import optax

        from estorch_tpu import ES, JaxAgent, MLPPolicy

        env = Swimmer2D()
        es = ES(
            policy=MLPPolicy,
            agent=JaxAgent,
            optimizer=optax.adam,
            population_size=384,
            sigma=0.08,
            policy_kwargs={"action_dim": env.action_dim, "hidden": (32,),
                           "discrete": False, "action_scale": 1.0},
            agent_kwargs={"env": env, "horizon": 200},
            optimizer_kwargs={"learning_rate": 3e-2},
            seed=3,
        )
        es.train(15, verbose=False)
        first = es.history[0]["reward_mean"]
        last = es.history[-1]["reward_mean"]
        assert last > first + 30.0, (first, last)


class TestPositionOnly:
    """POMDP wrapper: velocity channels zeroed, everything else untouched."""

    def test_velocity_channels_zeroed_positions_kept(self):
        import jax

        from estorch_tpu.envs import PositionOnly, Walker2D

        base = Walker2D()
        env = PositionOnly(base)
        assert env.obs_dim == base.obs_dim
        key = jax.random.PRNGKey(0)
        s0b, ob = base.reset(key)
        s0w, ow = env.reset(key)
        n_pos = 2 + len(base.chain.parent)
        np.testing.assert_array_equal(np.asarray(ow[:n_pos]),
                                      np.asarray(ob[:n_pos]))
        assert (np.asarray(ow[n_pos:]) == 0).all()

    def test_dynamics_and_reward_unchanged(self):
        import jax
        import jax.numpy as jnp

        from estorch_tpu.envs import PositionOnly, Walker2D

        base = Walker2D()
        env = PositionOnly(base)
        key = jax.random.PRNGKey(1)
        sb, _ = base.reset(key)
        sw, _ = env.reset(key)
        a = jnp.full((base.action_dim,), 0.3)
        for _ in range(3):
            sb, ob, rb, db = base.step(sb, a)
            sw, ow, rw, dw = env.step(sw, a)
            assert float(rb) == float(rw)
            assert bool(db) == bool(dw)
        np.testing.assert_array_equal(np.asarray(env.behavior(sw, ow)),
                                      np.asarray(base.behavior(sb, ob)))

    def test_swimmer_layout_rejected(self):
        from estorch_tpu.envs import PositionOnly, Swimmer2D

        with pytest.raises(ValueError, match="_obs"):
            PositionOnly(Swimmer2D())

    def test_construction_does_not_touch_jax(self):
        """Envs are static Python data built BEFORE any backend choice —
        the mask must be NumPy, not a device array."""
        from estorch_tpu.envs import PositionOnly, Walker2D

        env = PositionOnly(Walker2D())
        assert type(env._mask).__module__ == "numpy"


class TestGaitMetrics:
    """Gait-metric channel (round-4 verdict weak #4): 'walks' must be a
    measured claim — m/s and upright fraction — not a reward-scale one."""

    def test_rollout_env_metrics_channel(self):
        env = Humanoid2D()

        def apply(params, obs):
            return jnp.tanh(obs[: env.action_dim] * params)

        ro = make_rollout(env, apply, 40, with_env_metrics=True)
        res, sums = jax.jit(ro)(jnp.float32(0.1), jax.random.key(0))
        assert sums.shape == (len(env.metric_names),)
        # upright steps can never exceed alive steps
        assert 0.0 <= float(sums[0]) <= float(res.steps)
        m = env.episode_metrics(np.asarray(res.bc), int(res.steps),
                                np.asarray(sums))
        assert set(m) == {"upright_fraction", "forward_velocity_mps"}
        assert 0.0 <= m["upright_fraction"] <= 1.0
        # displacement-based: velocity * time == distance traveled
        t = int(res.steps) * float(env.control_dt)
        x0 = float(env.chain.init_pos[0][0])
        assert m["forward_velocity_mps"] * t == pytest.approx(
            float(res.bc[0]) - x0, rel=1e-5
        )

    def test_horizontal_runner_upright_is_na(self):
        """Cheetah/swimmer have no upright posture to lose: the indicator
        is constant 1, so the fraction reads 1.0 (n/a-upright)."""
        env = Cheetah2D()
        state, _ = env.reset(jax.random.key(0))
        assert float(env.step_metrics(state)[0]) == 1.0

    def test_evaluate_policy_reports_gait(self):
        import optax

        from estorch_tpu import ES, JaxAgent, MLPPolicy

        env = Walker2D()
        es = ES(
            policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
            population_size=16, sigma=0.1,
            policy_kwargs={"action_dim": env.action_dim, "hidden": (8,),
                           "discrete": False, "action_scale": 1.0},
            agent_kwargs={"env": env, "horizon": 24},
            optimizer_kwargs={"learning_rate": 1e-2}, seed=0,
        )
        ev = es.evaluate_policy(n_episodes=3, return_details=True)
        assert ev["steps"].shape == (3,)
        assert ev["gait"]["upright_fraction"].shape == (3,)
        assert ev["gait"]["forward_velocity_mps"].shape == (3,)
        assert np.all(ev["gait"]["upright_fraction"] >= 0.0)
        assert np.all(ev["gait"]["upright_fraction"] <= 1.0)
        # the plain (detail-free) eval still works and agrees on the mean
        assert es.evaluate_policy(n_episodes=3)["mean"] == pytest.approx(
            ev["mean"]
        )

    def test_obs_moments_and_env_metrics_exclusive(self):
        env = Walker2D()
        with pytest.raises(ValueError, match="one aux channel"):
            make_rollout(env, lambda p, o: o[: env.action_dim], 8,
                         with_obs_moments=True, with_env_metrics=True)


class TestDeceptiveValley:
    """Deceptive-reward wrapper (round-4 verdict next #5): the fitness
    landscape must actually be deceptive — a local optimum at the bait
    whose basin covers the greedy path — while dynamics/BC stay the
    base env's."""

    def test_phi_shape_is_deceptive(self):
        env = DeceptiveValley(Cheetah2D(), x_bait=1.0, x_valley=3.0,
                              valley_slope=1.5, rise_slope=4.0)
        phi = lambda x: float(env._phi(jnp.float32(x)))
        assert phi(1.0) > phi(0.5) > phi(0.0)        # bait attracts
        assert phi(1.0) > phi(2.0) > phi(3.0)        # valley repels
        assert phi(5.0) > phi(1.0)                   # prize dominates bait
        # continuity at the two knees
        assert phi(1.0) == pytest.approx(phi(1.0 + 1e-6), abs=1e-4)
        assert phi(3.0) == pytest.approx(phi(3.0 - 1e-6), abs=1e-4)

    def test_shaped_return_telescopes(self):
        """Summed shaped reward equals reward_scale·(φ(x_T) − φ(x_0)) plus
        alive/control terms — potential-based shaping, exactly."""
        base = Cheetah2D()  # never terminates, alive_bonus 0
        env = DeceptiveValley(base, reward_scale=2.0)
        state, _ = env.reset(jax.random.key(0))
        x0 = float(state["pos"][0, 0])
        total, ctrl = 0.0, 0.0
        a = jnp.full((base.action_dim,), 0.4)
        step = jax.jit(env.step)
        for _ in range(20):
            state, _, r, _ = step(state, a)
            total += float(r)
            ctrl += float(base.ctrl_cost * jnp.sum(jnp.clip(a, -1, 1) ** 2))
        xT = float(state["pos"][0, 0])
        want = 2.0 * (float(env._phi(jnp.float32(xT)))
                      - float(env._phi(jnp.float32(x0)))) - ctrl
        assert total == pytest.approx(want, abs=1e-3)

    def test_dynamics_bc_and_termination_untouched(self):
        base = Walker2D()
        env = DeceptiveValley(base)
        sb, ob = base.reset(jax.random.key(3))
        sw, ow = env.reset(jax.random.key(3))
        np.testing.assert_array_equal(np.asarray(ob), np.asarray(ow))
        a = jnp.full((base.action_dim,), 0.3)
        for _ in range(5):
            sb, ob, _, db = base.step(sb, a)
            sw, ow, _, dw = env.step(sw, a)
        np.testing.assert_array_equal(np.asarray(ob), np.asarray(ow))
        assert bool(db) == bool(dw)
        np.testing.assert_array_equal(np.asarray(env.behavior(sw, ow)),
                                      np.asarray(base.behavior(sb, ob)))

    def test_bad_geometry_rejected(self):
        with pytest.raises(ValueError, match="x_bait"):
            DeceptiveValley(Cheetah2D(), x_bait=3.0, x_valley=1.0)
        with pytest.raises(ValueError, match="slope"):
            DeceptiveValley(Cheetah2D(), valley_slope=-1.0)

    @pytest.mark.slow
    def test_trains_under_es_and_gait_metrics_pass_through(self):
        import optax

        from estorch_tpu import ES, JaxAgent, MLPPolicy

        env = DeceptiveValley(Walker2D())
        es = ES(
            policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
            population_size=16, sigma=0.1,
            policy_kwargs={"action_dim": env.action_dim, "hidden": (8,),
                           "discrete": False, "action_scale": 1.0},
            agent_kwargs={"env": env, "horizon": 16},
            optimizer_kwargs={"learning_rate": 1e-2}, seed=0,
        )
        es.train(1, verbose=False)
        ev = es.evaluate_policy(n_episodes=2, return_details=True)
        assert "gait" in ev and "forward_velocity_mps" in ev["gait"]
