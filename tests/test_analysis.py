"""Per-rule esguard tests: every shipped rule gets at least one
true-positive snippet and one clean snippet, plus engine/config/baseline
mechanics (including the add → suppress → fix → stale round trip)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

from estorch_tpu.analysis import (Finding, all_rules, analyze_source,
                                  load_baseline, load_config, save_baseline)
from estorch_tpu.analysis.config import parse_esguard_table


def findings(src: str, rule: str | None = None) -> list[Finding]:
    out = analyze_source("snippet.py", textwrap.dedent(src))
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def rule_ids(src: str) -> set[str]:
    return {f.rule for f in findings(src)}


# ---------------------------------------------------------------------
# R01 prng-key-reuse
# ---------------------------------------------------------------------

class TestR01:
    def test_double_consumption_flagged(self):
        found = findings("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """, "R01")
        assert len(found) == 1
        assert found[0].line == 6
        assert "key" in found[0].message

    def test_split_then_consume_clean(self):
        assert not findings("""
            import jax

            def sample(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1, (3,))
                b = jax.random.uniform(k2, (3,))
                return a + b
        """, "R01")

    def test_split_result_reuse_flagged(self):
        found = findings("""
            import jax

            def sample(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1, (3,))
                b = jax.random.uniform(k1, (3,))
                return a + b
        """, "R01")
        assert [f.line for f in found] == [7]

    def test_loop_reuse_without_resplit_flagged(self):
        found = findings("""
            import jax

            def sample(key):
                outs = []
                for i in range(4):
                    outs.append(jax.random.normal(key, (3,)))
                return outs
        """, "R01")
        assert found, "key consumed every iteration must be flagged"

    def test_loop_with_resplit_clean(self):
        assert not findings("""
            import jax

            def sample(key):
                outs = []
                for i in range(4):
                    key, sub = jax.random.split(key)
                    outs.append(jax.random.normal(sub, (3,)))
                return outs
        """, "R01")

    def test_fold_in_stream_clean(self):
        # fold_in derives a new key per iteration — the idiomatic stream
        assert not findings("""
            import jax

            def sample(key):
                outs = []
                for i in range(4):
                    outs.append(jax.random.normal(
                        jax.random.fold_in(key, i), (3,)))
                return outs
        """, "R01")

    def test_alias_import_detected(self):
        found = findings("""
            from jax import random as jr

            def sample(rng):
                a = jr.normal(rng, (3,))
                b = jr.normal(rng, (3,))
                return a + b
        """, "R01")
        assert len(found) == 1

    def test_handoff_to_helper_clean(self):
        # passing the key to a helper forfeits tracking, no false positive
        assert not findings("""
            import jax

            def sample(key, helper):
                helper(key)
                return jax.random.normal(key, (3,))
        """, "R01")

    def test_reassignment_resets(self):
        assert not findings("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                key = jax.random.fold_in(key, 1)
                b = jax.random.normal(key, (3,))
                return a + b
        """, "R01")


# ---------------------------------------------------------------------
# R02 host-sync-in-hot-path
# ---------------------------------------------------------------------

class TestR02:
    def test_item_in_jit_flagged(self):
        found = findings("""
            import jax

            @jax.jit
            def step(x):
                return x.sum().item()
        """, "R02")
        assert len(found) == 1
        assert ".item()" in found[0].message

    def test_np_asarray_in_scanned_fn_flagged(self):
        found = findings("""
            import jax
            import numpy as np

            def outer(xs):
                def body(carry, x):
                    return carry + np.asarray(x), None
                return jax.lax.scan(body, 0.0, xs)
        """, "R02")
        assert len(found) == 1

    def test_host_code_clean(self):
        # same calls OUTSIDE traced code are fine
        assert not findings("""
            import numpy as np

            def log_stats(x):
                return float(np.asarray(x).mean())
        """, "R02")

    def test_static_shape_cast_clean(self):
        assert not findings("""
            import jax

            @jax.jit
            def step(x):
                n = int(x.shape[0])
                return x * n
        """, "R02")

    def test_float_on_traced_value_flagged(self):
        found = findings("""
            import jax

            @jax.jit
            def step(x):
                return float(x)
        """, "R02")
        assert len(found) == 1
        assert found[0].severity == "warning"

    def test_block_until_ready_under_vmap_flagged(self):
        found = findings("""
            import jax

            def outer(xs):
                def one(x):
                    return x.block_until_ready()
                return jax.vmap(one)(xs)
        """, "R02")
        assert len(found) == 1


# ---------------------------------------------------------------------
# R03 impure-jit
# ---------------------------------------------------------------------

class TestR03:
    def test_print_and_time_flagged(self):
        found = findings("""
            import time
            import jax

            @jax.jit
            def step(x):
                print(x)
                t = time.time()
                return x + t
        """, "R03")
        assert len(found) == 2

    def test_np_random_flagged(self):
        found = findings("""
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return x + np.random.randn(3)
        """, "R03")
        assert len(found) == 1

    def test_closure_mutation_flagged(self):
        found = findings("""
            import jax

            stats = {}

            def outer():
                @jax.jit
                def step(x):
                    stats["last"] = x
                    return x
                return step
        """, "R03")
        assert len(found) == 1
        assert "stats" in found[0].message

    def test_pure_jit_clean(self):
        assert not findings("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x, key):
                noise = jax.random.normal(key, x.shape)
                local = {}
                local["scratch"] = x  # local dict: fine
                return x + noise
        """, "R03")

    def test_host_print_clean(self):
        assert not findings("""
            def report(x):
                print(x)
        """, "R03")

    def test_compat_shim_shard_map_is_seen_through(self):
        """The repo's version-portable shard_map shim
        (utils/backend.py) must NOT blind the rules to the hot bodies it
        wraps — distinctive tails (jit/vmap/pmap/shard_map) count from
        any import, including relative ones."""
        found = findings("""
            import time

            from ..utils.backend import shard_map

            def build(mesh):
                def body(state):
                    t = time.time()
                    return state + t
                return shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None)
        """, "R03")
        assert len(found) == 1

    def test_name_collision_does_not_mark_host_fn_traced(self):
        """A host-side function sharing a closure's name (`body`) must
        not inherit traced status from another scope's lax.scan call."""
        assert not findings("""
            import jax

            def run(xs):
                def body(carry, x):
                    return carry + x, None
                return jax.lax.scan(body, 0.0, xs)

            def body(metrics):
                # module-level host helper, same name, NOT traced
                return float(metrics.mean())
        """)

    def test_local_helper_named_like_entry_point_clean(self):
        """A module-local `checkpoint`/`scan` helper must not mark its
        callable arguments traced — only provably-jax heads count."""
        assert not findings("""
            import time

            def checkpoint(fn):
                return fn

            def save_state(state):
                t = time.time()
                print(state, t)
                return t

            saver = checkpoint(save_state)
        """)


# ---------------------------------------------------------------------
# R04 missing-donation
# ---------------------------------------------------------------------

class TestR04:
    def test_update_without_donation_flagged(self):
        found = findings("""
            import jax

            @jax.jit
            def update(params, grads):
                new_params = jax.tree_util.tree_map(
                    lambda p, g: p - 0.01 * g, params, grads)
                return new_params
        """, "R04")
        assert len(found) == 1
        assert "params" in found[0].message

    def test_partial_jit_without_donation_flagged(self):
        found = findings("""
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("lr",))
            def update(opt_state, grads, lr):
                new_opt_state = opt_state
                return new_opt_state, grads
        """, "R04")
        assert len(found) == 1

    def test_donated_clean(self):
        assert not findings("""
            from functools import partial
            import jax

            @partial(jax.jit, donate_argnums=(0,))
            def update(params, grads):
                new_params = params
                return new_params
        """, "R04")

    def test_call_form_detected(self):
        found = findings("""
            import jax

            def update(state, grads):
                new_state = state
                return new_state

            update_jit = jax.jit(update)
        """, "R04")
        assert len(found) == 1

    def test_non_state_jit_clean(self):
        assert not findings("""
            import jax

            @jax.jit
            def forward(x, y):
                return x @ y
        """, "R04")


# ---------------------------------------------------------------------
# R05 untimed-subprocess-wait
# ---------------------------------------------------------------------

class TestR05:
    def test_untimed_wait_flagged(self):
        found = findings("""
            import subprocess

            def launch(cmd):
                proc = subprocess.Popen(cmd)
                proc.wait()
        """, "R05")
        assert len(found) == 1

    def test_untimed_communicate_flagged(self):
        found = findings("""
            import subprocess

            def launch(cmd):
                p = subprocess.Popen(cmd)
                out, err = p.communicate()
                return out
        """, "R05")
        assert len(found) == 1

    def test_timed_wait_clean(self):
        assert not findings("""
            import subprocess

            def launch(cmd):
                proc = subprocess.Popen(cmd)
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
        """, "R05")

    def test_subprocess_run_without_timeout_flagged(self):
        found = findings("""
            import subprocess

            def build():
                subprocess.run(["make"], check=True)
        """, "R05")
        assert len(found) == 1

    def test_timeout_none_still_flagged(self):
        # explicit timeout=None is the unbounded wait spelled loudly
        found = findings("""
            import subprocess

            def build(cmd):
                subprocess.run(cmd, timeout=None)
                proc = subprocess.Popen(cmd)
                proc.wait(timeout=None)
        """, "R05")
        assert len(found) == 2

    def test_unrelated_wait_clean(self):
        # DMA semaphores / thread events named outside the proc family
        assert not findings("""
            def drain(sem, handle):
                sem.wait()
                handle.wait()
        """, "R05")

    def test_procish_attribute_receiver_flagged(self):
        found = findings("""
            class Pool:
                def close(self):
                    self.proc.wait()
        """, "R05")
        assert len(found) == 1


# ---------------------------------------------------------------------
# R06 signature-probe-default
# ---------------------------------------------------------------------

class TestR06:
    def test_guessed_default_flagged(self):
        found = findings("""
            import inspect

            def detect(fn):
                try:
                    takes_params = bool(inspect.signature(fn).parameters)
                except (TypeError, ValueError):
                    takes_params = True
                return takes_params
        """, "R06")
        assert len(found) == 1
        assert "GUESS" in found[0].message

    def test_probing_fallback_clean(self):
        # what rollout.carry_init_takes_params does now: probe, not guess
        assert not findings("""
            import inspect

            def detect(fn):
                try:
                    return bool(inspect.signature(fn).parameters)
                except (TypeError, ValueError):
                    pass
                try:
                    fn()
                    return False
                except TypeError:
                    return True
        """, "R06")

    def test_unrelated_try_clean(self):
        assert not findings("""
            def read(path):
                try:
                    with open(path) as fh:
                        data = fh.read()
                except OSError:
                    data = ""
                return data
        """, "R06")


# ---------------------------------------------------------------------
# R07 unfenced-device-timing
# ---------------------------------------------------------------------

class TestR07:
    def test_unfenced_jitted_call_flagged(self):
        found = findings("""
            import time
            import jax

            step = jax.jit(lambda x: x * 2)

            def bench(x):
                t0 = time.perf_counter()
                y = step(x)
                return time.perf_counter() - t0
        """, "R07")
        assert len(found) == 1
        assert "dispatch" in found[0].message

    def test_fenced_call_clean(self):
        assert not findings("""
            import time
            import jax

            step = jax.jit(lambda x: x * 2)

            def bench(x):
                t0 = time.perf_counter()
                y = step(x)
                jax.block_until_ready(y)
                return time.perf_counter() - t0
        """, "R07")

    def test_method_fence_clean(self):
        assert not findings("""
            import time
            import jax

            step = jax.jit(lambda x: x * 2)

            def bench(x):
                t0 = time.perf_counter()
                y = step(x)
                y.block_until_ready()
                return time.perf_counter() - t0
        """, "R07")

    def test_same_line_fence_wrap_clean(self):
        """`jitted(...).block_until_ready()` — the fence wraps the
        dispatch on one line and must count as fenced."""
        assert not findings("""
            import time
            import jax

            step = jax.jit(lambda x: x * 2)

            def bench(x):
                t0 = time.perf_counter()
                step(x).block_until_ready()
                return time.perf_counter() - t0
        """, "R07")

    def test_self_attr_dispatch_flagged(self):
        """The engine idiom: self._step bound to jax.jit in __init__,
        dispatched (and timed) in another method."""
        found = findings("""
            import time
            import jax

            class Engine:
                def __init__(self, fn):
                    self._step = jax.jit(fn)

                def bench(self, x):
                    t0 = time.perf_counter()
                    y = self._step(x)
                    dt = time.perf_counter() - t0
                    return y, dt
        """, "R07")
        assert len(found) == 1
        assert found[0].symbol == "Engine.bench"

    def test_lower_compile_is_not_dispatch(self):
        """AOT .lower().compile() on a jitted object is synchronous —
        timing it is exactly how compile time SHOULD be measured."""
        assert not findings("""
            import time
            import jax

            class Engine:
                def __init__(self, fn):
                    self._step = jax.jit(fn)

                def compile(self, x):
                    t0 = time.perf_counter()
                    self._step.lower(x).compile()
                    return time.perf_counter() - t0
        """, "R07")

    def test_materialization_fence_clean(self):
        """np.asarray of the outputs forces completion — honest timing."""
        assert not findings("""
            import time
            import jax
            import numpy as np

            step = jax.jit(lambda x: x * 2)

            def bench(x):
                t0 = time.perf_counter()
                y = step(x)
                out = np.asarray(y)
                return out, time.perf_counter() - t0
        """, "R07")

    def test_plain_host_call_clean(self):
        """Timing a non-jitted call is ordinary profiling, not a hazard."""
        assert not findings("""
            import time

            def work(x):
                return x * 2

            def bench(x):
                t0 = time.perf_counter()
                y = work(x)
                return time.perf_counter() - t0
        """, "R07")

    def test_jit_wrapped_shard_map_attr_flagged(self):
        """jax.jit(shard_map(...)) nesting still marks the bound attr."""
        found = findings("""
            import time
            import jax
            from jax.experimental.shard_map import shard_map

            class Engine:
                def __init__(self, body, mesh):
                    self._gen = jax.jit(shard_map(body, mesh=mesh))

                def bench(self, state):
                    t0 = time.perf_counter()
                    out = self._gen(state)
                    dt = time.perf_counter() - t0
                    return out, dt
        """, "R07")
        assert len(found) == 1


# ---------------------------------------------------------------------
# R08 swallowed-fault
# ---------------------------------------------------------------------

class TestR08:
    def test_pass_only_handler_in_recovery_path_flagged(self):
        found = findings("""
            def send_all(conns, msg):
                for c in conns:
                    try:
                        c.send(msg)
                    except OSError:
                        pass
        """, "R08")
        assert len(found) == 1
        assert found[0].symbol == "send_all"
        assert "swallowed" in found[0].message

    def test_counter_bump_is_evidence(self):
        assert not findings("""
            def send_all(self, conns, msg):
                for c in conns:
                    try:
                        c.send(msg)
                    except OSError:
                        self.telemetry.counters.inc("worker_send_failures")
        """, "R08")

    def test_flag_assignment_is_evidence(self):
        assert not findings("""
            def reap(proc):
                unreapable = False
                try:
                    proc.wait(timeout=5)
                except TimeoutError:
                    unreapable = True
                return unreapable
        """, "R08")

    def test_reraise_is_clean(self):
        assert not findings("""
            def step(env):
                try:
                    return env.step()
                except RuntimeError:
                    raise
        """, "R08")

    def test_teardown_paths_exempt(self):
        assert not findings("""
            class Pool:
                def close(self):
                    try:
                        self.conn.send(None)
                    except OSError:
                        pass

                def __del__(self):
                    try:
                        self.close()
                    except Exception:
                        pass

                def __exit__(self, *exc):
                    try:
                        self.close()
                    except Exception:
                        pass
        """, "R08")

    def test_fall_through_probe_exempt(self):
        # the R06-prescribed probe idiom: try the introspection fast path,
        # fall through to the behavioral probe — the pass IS the dispatch
        assert not findings("""
            import inspect

            def takes_params(fn):
                try:
                    return bool(inspect.signature(fn).parameters)
                except (TypeError, ValueError):
                    pass
                try:
                    fn()
                    return False
                except TypeError:
                    return True
        """, "R08")

    def test_pass_only_at_module_level_flagged(self):
        found = findings("""
            try:
                import optional_dep
            except ImportError:
                pass
        """, "R08")
        assert len(found) == 1
        assert found[0].symbol == "<module>"

    def test_multi_handler_try_flags_only_the_silent_one(self):
        found = findings("""
            def fetch(conn):
                try:
                    return conn.recv()
                except EOFError:
                    raise
                except OSError:
                    pass
        """, "R08")
        # the try body ends in `return` — fall-through shape, both exempt
        assert not found
        found = findings("""
            def fetch(conn):
                try:
                    data = conn.recv()
                except EOFError:
                    raise
                except OSError:
                    pass
        """, "R08")
        assert len(found) == 1
        assert found[0].snippet.strip() == "except OSError:"


# ---------------------------------------------------------------------
# R09 nonmonotonic-span-clock
# ---------------------------------------------------------------------

class TestR09:
    def test_local_wall_clock_span_flagged(self):
        found = findings("""
            import time

            def span():
                t0 = time.time()
                work()
                return time.time() - t0
        """, "R09")
        assert len(found) == 1
        assert "wall clock" in found[0].message

    def test_self_attr_wall_clock_span_flagged(self):
        """The serving idiom: start stamped in __init__, delta taken in
        another method — the uptime bug this rule's self-apply fixed in
        serve/server.py."""
        found = findings("""
            import time

            class Server:
                def __init__(self):
                    self.started = time.time()

                def uptime(self):
                    return time.time() - self.started
        """, "R09")
        assert len(found) == 1
        assert found[0].symbol == "Server.uptime"

    def test_perf_counter_span_clean(self):
        assert not findings("""
            import time

            def span():
                t0 = time.perf_counter()
                work()
                return time.perf_counter() - t0
        """, "R09")

    def test_monotonic_deadline_clean(self):
        assert not findings("""
            import time

            def wait(deadline_s):
                t0 = time.monotonic()
                while time.monotonic() - t0 < deadline_s:
                    poll()
        """, "R09")

    def test_cross_process_age_from_file_clean(self):
        """The heartbeat reader: the start timestamp crosses a process
        boundary (written by another pid), so wall clock is REQUIRED —
        an untyped start read from a dict must stay silent."""
        assert not findings("""
            import json
            import time

            def heartbeat_age(path):
                with open(path) as f:
                    hb = json.load(f)
                return time.time() - hb["ts"]
        """, "R09")

    def test_wall_timestamp_without_delta_clean(self):
        assert not findings("""
            import time

            def stamp(record):
                record["ts"] = time.time()
                return record
        """, "R09")



# ---------------------------------------------------------------------
# R10 unsharded-capture
# ---------------------------------------------------------------------

class TestR10:
    def test_np_random_closure_flagged(self):
        found = findings("""
            import numpy as np
            import jax

            TABLE = np.random.randn(1 << 20)

            def body(x):
                return x + TABLE[:3].sum()

            step = jax.jit(body, in_shardings=(None,), out_shardings=None)
        """, "R10")
        assert len(found) == 1
        assert "TABLE" in found[0].message
        assert "replicated" in found[0].message

    def test_large_constant_closure_flagged(self):
        found = findings("""
            import numpy as np
            import jax

            MASK = np.zeros((4096, 4096))

            def body(x):
                return x * MASK

            step = jax.jit(body, out_shardings=None)
        """, "R10")
        assert len(found) == 1
        assert "16,777,216 elements" in found[0].message

    def test_method_and_lambda_forms_flagged(self):
        """The engine idiom: jit(self._body, in_shardings=...) and the
        lambda wrapper both count as sharded programs."""
        found = findings("""
            import numpy as np
            import jax

            SEEDS = np.random.randint(0, 100, (8,))

            class Engine:
                def _body(self, state):
                    return state + SEEDS[0]

                def __init__(self, sh):
                    self.step = jax.jit(self._body, in_shardings=(sh,))
                    self.step2 = jax.jit(lambda s: s * SEEDS[1],
                                         out_shardings=sh)
        """, "R10")
        assert len(found) == 2

    def test_partial_decorator_form_flagged(self):
        found = findings("""
            from functools import partial

            import numpy as np
            import jax

            BIG = np.arange(1 << 20)

            @partial(jax.jit, in_shardings=(None,))
            def body(x):
                return x + BIG[0]
        """, "R10")
        assert len(found) == 1

    def test_operand_passing_clean(self):
        """The fix shape: the host array reaches the program as an
        argument, placed by in_shardings — no capture."""
        assert not findings("""
            import numpy as np
            import jax

            TABLE = np.random.randn(1 << 20)

            def body(x, table):
                return x + table[:3].sum()

            step = jax.jit(body, in_shardings=(None, None))
            out = step(1.0, TABLE)
        """, "R10")

    def test_small_constant_clean(self):
        assert not findings("""
            import numpy as np
            import jax

            SMALL = np.zeros((4,))

            def body(x):
                return x + SMALL[0]

            step = jax.jit(body, in_shardings=(None,))
        """, "R10")

    def test_unsharded_jit_clean(self):
        """Plain jit (no sharding kwargs) is R03/R04 territory, not R10:
        a replicated program replicates by definition."""
        assert not findings("""
            import numpy as np
            import jax

            TABLE = np.random.randn(1 << 20)

            def body(x):
                return x + TABLE[0]

            step = jax.jit(body)
        """, "R10")

    def test_unrelated_local_name_collision_clean(self):
        """A helper's own local `table = np.random...` must not poison a
        legitimately-passed operand PARAMETER of the same bare name in
        another function — host bindings are module-level only."""
        assert not findings("""
            import numpy as np
            import jax

            def setup():
                table = np.random.randn(1 << 20)
                return table

            def make(sh, table):
                return jax.jit(lambda s: s + table, in_shardings=(sh,))
        """, "R10")

    def test_local_rebinding_clean(self):
        """A name the body binds itself is not a capture."""
        assert not findings("""
            import numpy as np
            import jax

            TABLE = np.random.randn(1 << 20)

            def body(x):
                TABLE = x * 2
                return TABLE

            step = jax.jit(body, in_shardings=(None,))
        """, "R10")


# ---------------------------------------------------------------------
# R11 blocking-wait-in-scheduler
# ---------------------------------------------------------------------

class TestR11:
    def test_untimed_queue_get_flagged(self):
        found = findings("""
            def pump(events):
                while True:
                    ev = events.get()
                    handle(ev)
        """, "R11")
        assert len(found) == 1
        assert "get" in found[0].message

    def test_timed_queue_get_clean(self):
        assert not findings("""
            import queue

            def pump(events):
                while True:
                    try:
                        ev = events.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    handle(ev)
        """, "R11")

    def test_nonblocking_get_and_dict_get_clean(self):
        assert not findings("""
            def drain(q, cfg):
                v = cfg.get("mode")
                try:
                    item = q.get(block=False)
                except Exception:
                    item = None
                return v, item
        """, "R11")

    def test_untimed_thread_join_flagged(self):
        found = findings("""
            def close(self):
                for t in self._threads:
                    t.join()
        """, "R11")
        # receiver `t` isn't thread-ish by name; the attr-receiver form is
        assert not found
        found = findings("""
            def close(self):
                self.worker.join()
        """, "R11")
        assert len(found) == 1
        assert "join" in found[0].message

    def test_timed_join_and_str_join_clean(self):
        assert not findings("""
            def close(self, parts):
                self.worker.join(timeout=5.0)
                return ", ".join(parts)
        """, "R11")

    def test_unguarded_conn_recv_flagged(self):
        found = findings("""
            def serve(conn):
                while True:
                    msg = conn.recv()
                    if msg is None:
                        return
        """, "R11")
        assert len(found) == 1
        assert "recv" in found[0].message

    def test_poll_guarded_recv_clean(self):
        assert not findings("""
            def serve(conn):
                while True:
                    if not conn.poll(1.0):
                        continue
                    msg = conn.recv()
                    if msg is None:
                        return
        """, "R11")

    def test_wait_select_guarded_recv_clean(self):
        assert not findings("""
            import multiprocessing.connection as mpc

            def collect(pending, deadline):
                ready = mpc.wait(list(pending.values()), timeout=0.1)
                for conn in ready:
                    got = conn.recv()
                    keep(got)
        """, "R11")

    def test_scheduler_and_procpool_self_clean(self):
        """The rule's own motivating modules must pass it (self-apply)."""
        import estorch_tpu.algo.scheduler as sched
        import estorch_tpu.host.procpool as pp

        for mod in (sched, pp):
            with open(mod.__file__) as f:
                src = f.read()
            hits = [x for x in analyze_source(mod.__file__, src)
                    if x.rule == "R11"]
            assert not hits, [h.message for h in hits]


class TestR12:
    def test_gauge_of_clock_delta_name_flagged(self):
        """The motivating true positive: serve/batcher.py recorded the
        batch predict duration as a `batch_predict_ms_last` gauge —
        last-write-wins, so the tail sample is gone by the next batch.
        The fix observes into the serve/compute_s histogram."""
        found = findings("""
            import time

            def dispatch(hub, batch):
                t0 = time.perf_counter()
                run(batch)
                dt = time.perf_counter() - t0
                hub.gauge("batch_predict_ms_last", round(dt * 1e3, 3))
        """, "R12")
        assert len(found) == 1
        assert "tail" in found[0].message
        assert "histogram" in found[0].hint

    def test_gauge_of_inline_delta_flagged(self):
        found = findings("""
            import time

            def dispatch(hub):
                t0 = time.monotonic()
                work()
                hub.gauge("work_ms", (time.monotonic() - t0) * 1e3)
        """, "R12")
        assert len(found) == 1

    def test_non_duration_gauges_clean(self):
        """Queue depth, ratios, and re-derivable sums are genuinely
        last-write facts — the rule must stay silent on them."""
        assert not findings("""
            import time

            def stats(hub, q, folded, consumed):
                hub.gauge("queue_depth", q.qsize())
                hub.gauge("stale_reuse_ratio", folded / max(consumed, 1))
        """, "R12")

    def test_histogram_observe_of_delta_clean(self):
        assert not findings("""
            import time

            def dispatch(hub, batch):
                t0 = time.perf_counter()
                run(batch)
                dt = time.perf_counter() - t0
                hub.observe("serve/compute_s", dt, n=len(batch))
        """, "R12")

    def test_wall_clock_delta_not_this_rules_business(self):
        """A time.time() delta is R09's finding (wrong clock), not a
        gauge-shaped-latency one — no double-reporting."""
        assert not findings("""
            import time

            def stamp(hub):
                t0 = time.time()
                work()
                hub.gauge("age_s", time.time() - t0)
        """, "R12")

    def test_batcher_and_spans_self_clean(self):
        """The rule's motivating modules must pass it (self-apply: the
        batch_predict_ms_last gauge became a histogram observe)."""
        import estorch_tpu.obs.spans as spans
        import estorch_tpu.serve.batcher as batcher

        for mod in (batcher, spans):
            with open(mod.__file__) as f:
                src = f.read()
            hits = [x for x in analyze_source(mod.__file__, src)
                    if x.rule == "R12"]
            assert not hits, [h.message for h in hits]


# ---------------------------------------------------------------------
# R13 untimed-network-call
# ---------------------------------------------------------------------

class TestR13:
    def test_urlopen_without_timeout_flagged(self):
        """The motivating hazard: the fleet collector scrapes N replicas
        every tick — one peer that accepts the TCP connection and then
        goes silent would wedge the whole loop through the global socket
        default (None = block forever)."""
        found = findings("""
            import urllib.request

            def scrape(url):
                with urllib.request.urlopen(url) as r:
                    return r.read()
        """, "R13")
        assert len(found) == 1
        assert "urlopen" in found[0].message
        assert "timeout" in found[0].hint

    def test_http_client_ctor_without_timeout_flagged(self):
        found = findings("""
            import http.client

            def connect(host):
                return http.client.HTTPConnection(host, 80)
        """, "R13")
        assert len(found) == 1

    def test_create_connection_without_timeout_flagged(self):
        found = findings("""
            import socket

            def connect(addr):
                return socket.create_connection(addr)
        """, "R13")
        assert len(found) == 1

    def test_timeout_none_still_flagged(self):
        """timeout=None is SPELLING the unbounded default, not bounding
        it — same treatment R05 gives wait(timeout=None); the positional
        form too."""
        found = findings("""
            import urllib.request

            def scrape(url):
                return urllib.request.urlopen(url, timeout=None).read()
        """, "R13")
        assert len(found) == 1
        found = findings("""
            import urllib.request

            def scrape(url):
                return urllib.request.urlopen(url, None, None).read()
        """, "R13")
        assert len(found) == 1

    def test_https_ctor_positional_tls_params_not_a_timeout(self):
        """HTTPSConnection's 3rd/4th positionals are key_file/cert_file
        — only the FIFTH positional is timeout, and mistaking the TLS
        params for it would be a false negative on an unbounded
        connect."""
        found = findings("""
            import http.client

            def connect(host, kf, cf):
                return http.client.HTTPSConnection(host, 443, kf, cf)
        """, "R13")
        assert len(found) == 1
        assert not findings("""
            import http.client

            def connect(host, kf, cf, t):
                return http.client.HTTPSConnection(host, 443, kf, cf, t)
        """, "R13")

    def test_bounded_calls_clean(self):
        """Keyword and positional timeouts both count — urlopen's
        timeout is its third positional, create_connection's second."""
        assert not findings("""
            import http.client
            import socket
            import urllib.request

            def ok(url, addr, host, t):
                a = urllib.request.urlopen(url, timeout=10).read()
                b = urllib.request.urlopen(url, None, t).read()
                c = socket.create_connection(addr, 2.0)
                d = http.client.HTTPConnection(host, 80, timeout=3)
                return a, b, c, d
        """, "R13")

    def test_unrelated_open_clean(self):
        """builtins.open / file reads are not network connects."""
        assert not findings("""
            def read(path):
                with open(path) as f:
                    return f.read()
        """, "R13")

    def test_network_modules_self_clean(self):
        """Self-application across every socket-touching module the rule
        was written for: the serve client, the loadgen, the sidecar, the
        doctor's probes, and the fleet collector."""
        import estorch_tpu.doctor as doctor
        import estorch_tpu.obs.agg.collector as collector
        import estorch_tpu.obs.agg.dash as dash
        import estorch_tpu.obs.export.sidecar as sidecar
        import estorch_tpu.serve.client as client
        import estorch_tpu.serve.loadgen as loadgen

        for mod in (client, loadgen, sidecar, doctor, collector, dash):
            with open(mod.__file__) as f:
                src = f.read()
            hits = [x for x in analyze_source(mod.__file__, src)
                    if x.rule == "R13"]
            assert not hits, [h.message for h in hits]


class TestR14:
    def test_jit_in_http_handler_flagged(self):
        """The motivating hazard: a jit constructed inside do_POST means
        trace + XLA compile on EVERY request — the recompile storm the
        warm-bundle machinery kills, reintroduced one line at a time."""
        found = findings("""
            import jax

            class Handler:
                def do_POST(self):
                    fn = jax.jit(lambda x: x * 2)
                    return fn(self.obs)
        """, "R14")
        assert len(found) == 1
        assert "per call" in found[0].message
        assert "load/init" in found[0].hint

    def test_jit_in_loop_body_flagged(self):
        found = findings("""
            import jax

            def worker(batches):
                while True:
                    batch = batches.get()
                    out = jax.jit(forward)(batch)
        """, "R14")
        assert len(found) == 1

    def test_pmap_and_shard_map_count_as_ctors(self):
        found = findings("""
            import jax

            def drain(items):
                for x in items:
                    jax.pmap(step)(x)
        """, "R14")
        assert len(found) == 1

    def test_module_level_and_init_loops_clean(self):
        """Load-time construction is the FIX, not a finding: module
        scope, __init__, and builder-named functions may build a ladder
        of programs in a loop."""
        assert not findings("""
            import jax

            PROGRAMS = {}
            for b in (2, 4, 8):
                PROGRAMS[b] = jax.jit(forward)

            class Engine:
                def __init__(self, buckets):
                    self._fns = {b: jax.jit(forward) for b in buckets}

            def build_ladder(buckets):
                out = {}
                for b in buckets:
                    out[b] = jax.jit(forward)
                return out
        """, "R14")

    def test_calling_a_jitted_name_in_a_loop_clean(self):
        """Dispatching an already-built wrapper per iteration is the
        correct steady state — only CONSTRUCTION reports."""
        assert not findings("""
            import jax

            fn = jax.jit(lambda x: x * 2)

            def worker(batches):
                for batch in batches:
                    fn(batch)
        """, "R14")

    def test_for_iterator_expression_clean_while_test_flagged(self):
        """A for's iterator evaluates ONCE before the loop — jit there
        is construction, not per-iteration work; a while's TEST re-runs
        every iteration and stays flagged."""
        assert not findings("""
            import jax

            def drain(batch):
                for row in jax.jit(forward)(batch):
                    consume(row)
        """, "R14")
        found = findings("""
            import jax

            def spin(state):
                while jax.jit(pred)(state):
                    state = step(state)
        """, "R14")
        assert len(found) == 1

    def test_nested_def_in_loop_clean(self):
        assert not findings("""
            import jax

            def router(routes):
                for name in routes:
                    def handler(x):
                        return jax.jit(lambda y: y)(x)
                    routes[name] = handler
        """, "R14")

    def test_serve_modules_self_clean(self):
        """Self-application across the serving vertical the rule was
        written for."""
        import estorch_tpu.serve.batcher as batcher
        import estorch_tpu.serve.bundle as bundle
        import estorch_tpu.serve.predictor as predictor
        import estorch_tpu.serve.server as server
        import estorch_tpu.serve.warm as warm

        for mod in (predictor, bundle, batcher, server, warm):
            with open(mod.__file__) as f:
                src = f.read()
            hits = [x for x in analyze_source(mod.__file__, src)
                    if x.rule == "R14"]
            assert not hits, [h.message for h in hits]


class TestR15:
    def test_unbounded_while_true_retry_flagged(self):
        """The motivating hazard: a `while True` that swallows the
        connect error and tries again turns one dead replica into an
        infinite hammer — no attempt bound, no escalation, ever."""
        found = findings("""
            import urllib.request

            def fetch(url):
                while True:
                    try:
                        return urllib.request.urlopen(url,
                                                      timeout=5).read()
                    except OSError:
                        continue
        """, "R15")
        assert len(found) == 1
        assert "forever" in found[0].message
        assert "budget" in found[0].hint

    def test_bounded_retry_without_backoff_flagged(self):
        found = findings("""
            import urllib.request

            def fetch(url):
                for attempt in range(5):
                    try:
                        return urllib.request.urlopen(url,
                                                      timeout=5).read()
                    except OSError:
                        continue
        """, "R15")
        assert len(found) == 1
        assert "backoff" in found[0].message

    def test_itertools_count_is_unbounded(self):
        found = findings("""
            import itertools
            import socket
            import time

            def connect(addr):
                for attempt in itertools.count():
                    try:
                        return socket.create_connection(addr, 5)
                    except OSError:
                        time.sleep(1)
        """, "R15")
        assert len(found) == 1
        assert "forever" in found[0].message

    def test_conn_request_retry_loop_flagged(self):
        found = findings("""
            def fetch(conn_pool):
                while True:
                    try:
                        conn = conn_pool.take()
                        conn.request("GET", "/x")
                        return conn.getresponse().read()
                    except OSError:
                        continue
        """, "R15")
        assert len(found) == 1

    def test_budgeted_retry_with_backoff_clean(self):
        """The router's prescribed shape (serve/router.py): bounded
        attempts, exponential backoff + jitter between them."""
        assert not findings("""
            import random
            import time
            import urllib.request

            def fetch(url, budget=2):
                for attempt in range(1 + budget):
                    if attempt:
                        time.sleep(0.05 * 2 ** attempt
                                   * (0.5 + random.random()))
                    try:
                        return urllib.request.urlopen(url,
                                                      timeout=5).read()
                    except OSError:
                        continue
                raise TimeoutError(url)
        """, "R15")

    def test_reraising_handler_clean(self):
        """A handler that escalates (even conditionally) bounds its own
        patience — the stale-keep-alive reconnect idiom
        (serve/client.py) raises on its second failure."""
        assert not findings("""
            import http.client

            def request(self, method, path):
                for attempt in (0, 1):
                    try:
                        self.conn.request(method, path)
                        return self.conn.getresponse().read()
                    except OSError:
                        self.close()
                        if attempt:
                            raise
        """, "R15")

    def test_loop_without_net_call_clean(self):
        assert not findings("""
            def drain(q):
                while True:
                    try:
                        q.process_one()
                    except ValueError:
                        continue
        """, "R15")

    def test_outer_dispatcher_with_inner_bounded_retry_clean(self):
        """An unbounded WORKER loop wrapping a correctly budgeted inner
        retry is judged at the innermost loop — pinning the retry on
        the outer `while True` would flag every dispatcher."""
        assert not findings("""
            import time
            import urllib.request

            def worker(q):
                while True:
                    url = q.get()
                    for attempt in range(3):
                        try:
                            urllib.request.urlopen(url, timeout=5)
                            break
                        except OSError:
                            time.sleep(0.1 * 2 ** attempt)
        """, "R15")

    def test_outer_retry_of_inner_batch_still_flagged(self):
        """The try itself living on the outer loop (retrying a whole
        inner batch forever) is still the outer loop's finding."""
        found = findings("""
            import urllib.request

            def push_all(urls):
                while True:
                    try:
                        for u in urls:
                            urllib.request.urlopen(u, timeout=5)
                        return
                    except OSError:
                        continue
        """, "R15")
        assert len(found) == 1
        assert "forever" in found[0].message

    def test_net_call_without_retry_shape_clean(self):
        """A loop OVER network calls (one per item, failure escapes) is
        iteration, not retry."""
        assert not findings("""
            import urllib.request

            def scrape_all(urls):
                out = []
                for url in urls:
                    out.append(urllib.request.urlopen(url,
                                                      timeout=5).read())
                return out
        """, "R15")

    def test_router_and_client_self_clean(self):
        """Self-application: the front router's budgeted retry is THE
        negative exemplar, and the keep-alive client's single reconnect
        stays clean via its escalating handler."""
        import estorch_tpu.serve.client as client
        import estorch_tpu.serve.fleet as fleet
        import estorch_tpu.serve.router as router

        for mod in (router, fleet, client):
            with open(mod.__file__) as f:
                src = f.read()
            hits = [x for x in analyze_source(mod.__file__, src)
                    if x.rule == "R15"]
            assert not hits, [h.message for h in hits]


# ---------------------------------------------------------------------
# R16 scenario-constant-closure
# ---------------------------------------------------------------------

class TestR16:
    def test_jit_closure_over_loop_constant_flagged(self):
        found = findings("""
            import jax

            def build(scenario_gravities, dyn):
                steps = []
                for variant, g in enumerate(scenario_gravities):
                    steps.append(jax.jit(lambda s, a: dyn(s, a, g)))
                return steps
        """, "R16")
        assert len(found) == 1
        assert "'g'" in found[0].message

    def test_rollout_builder_comprehension_flagged_once(self):
        """jit(make_rollout(.., v)) is ONE construction site, not two —
        and comprehensions count as scenario loops."""
        found = findings("""
            import jax
            from estorch_tpu.envs.rollout import make_rollout

            def rollouts(scenarios, apply_fn, envs):
                return [jax.jit(make_rollout(envs[v], apply_fn, 100))
                        for v in scenarios]
        """, "R16")
        assert len(found) == 1

    def test_derived_per_scenario_name_flagged(self):
        """`gc = scenario.g` keeps the value per-scenario: the closure
        smell survives one straight-line rename."""
        found = findings("""
            import jax

            def per_scenario(scenario_list, step):
                fns = {}
                for scenario in scenario_list:
                    gc = scenario.g
                    fns[scenario.name] = jax.jit(
                        lambda s, a: step(s, a, gc))
                return fns
        """, "R16")
        assert len(found) == 1
        assert "'gc'" in found[0].message

    def test_fires_even_in_builder_scope(self):
        """Unlike R14, load-time builder scopes are NOT exempt: one
        program per scenario at load time is still O(N) programs."""
        found = findings("""
            import jax

            def build_engine(scenario_params, dyn):
                progs = []
                for sp in scenario_params:
                    progs.append(jax.jit(lambda s, a: dyn(s, a, sp)))
                return progs
        """, "R16")
        assert len(found) == 1

    def test_traced_operand_call_clean(self):
        """THE fix: one jitted program, the variant's params an
        argument — per-variant values as traced operands."""
        found = findings("""
            import jax

            def evaluate(jitted_rollout, dist, params, keys):
                outs = []
                for variant in range(10):
                    outs.append(jitted_rollout(params, dist.draw(variant),
                                               keys))
                return outs
        """, "R16")
        assert found == []

    def test_non_scenario_loop_clean(self):
        """A bucket-ladder build is R14's jurisdiction (and exempt
        there in builder scopes); R16 keys on scenario-ish names."""
        found = findings("""
            import jax

            def build_ladder(buckets, fwd):
                fns = {}
                for b in buckets:
                    fns[b] = jax.jit(fwd)
                return fns
        """, "R16")
        assert found == []

    def test_variant_independent_jit_in_scenario_loop_clean(self):
        found = findings("""
            import jax

            def shared(scenarios, step):
                f = None
                for scenario in scenarios:
                    f = jax.jit(step)
                return f
        """, "R16")
        assert found == []

    def test_scenarios_package_self_clean(self):
        """Self-application: the scenario suite itself must honor its
        own traced-operand contract."""
        import estorch_tpu.scenarios.distribution as dist
        import estorch_tpu.scenarios.env as senv
        import estorch_tpu.scenarios.pbt as pbt

        for mod in (dist, senv, pbt):
            with open(mod.__file__) as f:
                src = f.read()
            hits = [x for x in analyze_source(mod.__file__, src)
                    if x.rule == "R16"]
            assert not hits, [h.message for h in hits]


# ---------------------------------------------------------------------
# engine / CLI / config / baseline mechanics
# ---------------------------------------------------------------------

SNIPPET_WITH_FINDING = """
import subprocess

def launch(cmd):
    proc = subprocess.Popen(cmd)
    proc.wait()
"""

SNIPPET_FIXED = """
import subprocess

def launch(cmd):
    proc = subprocess.Popen(cmd)
    proc.wait(timeout=30)
"""


class TestR17:
    """unfenced-cross-host-barrier — the R05/R11/R13 family lifted to
    the host layer (docs/analysis.md)."""

    def test_distributed_initialize_without_timeout_flagged(self):
        """The motivating hazard: the cluster barrier.  One peer that
        never dials in hangs EVERY host identically, so no survivor can
        even name the missing one."""
        found = findings("""
            import jax

            def bring_up(addr, n, pid):
                jax.distributed.initialize(addr, n, pid)
        """, "R17")
        assert len(found) == 1
        assert "initialization_timeout" in found[0].message

    def test_distributed_initialize_timeout_none_flagged(self):
        found = findings("""
            import jax

            def bring_up():
                jax.distributed.initialize(initialization_timeout=None)
        """, "R17")
        assert len(found) == 1

    def test_distributed_initialize_with_timeout_clean(self):
        assert not findings("""
            import jax

            def bring_up(addr):
                jax.distributed.initialize(
                    addr, initialization_timeout=120)
        """, "R17")

    def test_untimed_accept_flagged(self):
        found = findings("""
            import socket

            def serve(srv_sock):
                conn, addr = srv_sock.accept()
                return conn
        """, "R17")
        assert len(found) == 1
        assert "accept" in found[0].message

    def test_untimed_socket_recv_flagged(self):
        """Buffer-sized recv on a socket-ish receiver: the coordinator-
        socket wait; the argless pipe recv() stays R11's."""
        found = findings("""
            def read_result(conn_sock):
                return conn_sock.recv(65536)
        """, "R17")
        assert len(found) == 1

    def test_settimeout_in_scope_clean(self):
        assert not findings("""
            import socket

            def serve(srv_sock):
                srv_sock.settimeout(0.05)
                conn, addr = srv_sock.accept()
                return conn
        """, "R17")

    def test_settimeout_none_not_a_fence(self):
        """settimeout(None) is SPELLING blocking mode, not bounding it."""
        found = findings("""
            def serve(srv_sock):
                srv_sock.settimeout(None)
                conn, addr = srv_sock.accept()
                return conn
        """, "R17")
        assert len(found) == 1

    def test_timeout_handler_counts_as_fence(self):
        """except socket.timeout only ever fires on a timed socket —
        catching it is evidence the deadline was set at the
        connect/accept site (the elastic protocol helpers' shape)."""
        assert not findings("""
            import socket

            def pump(conn_sock, deadline):
                while True:
                    try:
                        return conn_sock.recv(4096)
                    except socket.timeout:
                        continue
        """, "R17")

    def test_select_readiness_counts_as_fence(self):
        assert not findings("""
            def pump(sel, conn_sock):
                for key, _ in sel.select(timeout=0.05):
                    return conn_sock.recv(4096)
        """, "R17")

    def test_settimeout_on_other_socket_not_a_fence(self):
        """A deadline on some OTHER socket bounds nothing here — the
        fence must be on the receiver that waits."""
        found = findings("""
            def pump(ctl_sock, conn_sock):
                ctl_sock.settimeout(5.0)
                return conn_sock.recv(65536)
        """, "R17")
        assert len(found) == 1

    def test_non_selector_select_not_a_fence(self):
        """`.select(...)` on a non-selector receiver (an ORM query, a
        soup) is a name collision, not a readiness wait."""
        found = findings("""
            def scrape(soup, conn_sock):
                rows = soup.select("div.row")
                return conn_sock.recv(65536)
        """, "R17")
        assert len(found) == 1

    def test_non_socketish_receiver_clean(self):
        """dict.get-style receivers and non-sock names stay quiet —
        the receiver heuristic is the R05/R11 one."""
        assert not findings("""
            def pull(ring):
                return ring.recv(16)
        """, "R17")

    def test_elastic_layer_self_clean(self):
        """Self-application over the modules the rule was written for:
        the elastic coordinator/host protocol and the multihost init."""
        import estorch_tpu.parallel.elastic as elastic
        import estorch_tpu.parallel.multihost as multihost

        for mod in (elastic, multihost):
            with open(mod.__file__) as f:
                src = f.read()
            hits = [x for x in analyze_source(mod.__file__, src)
                    if x.rule == "R17"]
            assert not hits, [h.message for h in hits]


class TestEngine:
    def test_every_rule_registered(self):
        ids = [r.id for r in all_rules()]
        assert ids == ["R01", "R02", "R03", "R04", "R05", "R06", "R07",
                       "R08", "R09", "R10", "R11", "R12", "R13", "R14",
                       "R15", "R16", "R17", "R18", "R19", "R20", "R21",
                       "R22", "R23"]

    def test_syntax_error_becomes_finding(self):
        found = analyze_source("bad.py", "def broken(:\n")
        assert len(found) == 1 and found[0].rule == "R00"

    def test_finding_fields(self):
        f = findings(SNIPPET_WITH_FINDING, "R05")[0]
        assert f.file == "snippet.py"
        assert f.symbol == "launch"
        assert f.snippet == "proc.wait()"
        assert f.hint

    def test_severity_ordering_in_output(self):
        src = """
            import subprocess, inspect

            def a(fn):
                try:
                    ok = bool(inspect.signature(fn).parameters)
                except ValueError:
                    ok = True
                subprocess.run(["ls"])  # error severity
                return ok
        """
        from estorch_tpu.analysis import sort_findings
        out = sort_findings(findings(src))
        assert [f.severity for f in out] == ["error", "warning"]


class TestPathNormalization:
    def test_exclude_applies_to_absolute_inputs(self, tmp_path,
                                                monkeypatch):
        """Repo-relative exclude globs must hold whether the analyzer is
        pointed at `pkg` or `/abs/path/pkg`."""
        from estorch_tpu.analysis import iter_py_files

        pkg = tmp_path / "pkg"
        (pkg / "native").mkdir(parents=True)
        (pkg / "ok.py").write_text("x = 1\n")
        (pkg / "native" / "skipme.py").write_text("x = 1\n")
        monkeypatch.chdir(tmp_path)

        rel = list(iter_py_files(["pkg"], exclude=["pkg/native/*"]))
        abs_ = list(iter_py_files([str(pkg)], exclude=["pkg/native/*"]))
        assert rel == abs_ == [os.path.join("pkg", "ok.py")]


class TestBaselineRoundTrip:
    def test_add_suppress_fix_stale(self, tmp_path):
        """The full life of a grandfathered finding: it appears, the
        baseline suppresses it, the code gets fixed, the baseline entry
        turns stale."""
        baseline_path = str(tmp_path / "baseline.json")

        # 1. the finding appears
        found = analyze_source("pkg/launch.py",
                               textwrap.dedent(SNIPPET_WITH_FINDING))
        assert [f.rule for f in found] == ["R05"]

        # 2. written to the baseline, it suppresses exactly that finding
        save_baseline(baseline_path, found, reason="legacy launcher")
        baseline = load_baseline(baseline_path)
        assert [e.reason for e in baseline.entries] == ["legacy launcher"]
        res = baseline.apply(found)
        assert not res.unsuppressed and len(res.suppressed) == 1
        assert not res.stale

        # 3. the finding survives line drift (identity is line-free)
        drifted = "# new header comment\n" + textwrap.dedent(
            SNIPPET_WITH_FINDING)
        res = baseline.apply(analyze_source("pkg/launch.py", drifted))
        assert not res.unsuppressed and len(res.suppressed) == 1

        # 4. the code is fixed -> the entry is flagged stale
        res = baseline.apply(
            analyze_source("pkg/launch.py",
                           textwrap.dedent(SNIPPET_FIXED)))
        assert not res.unsuppressed and not res.suppressed
        assert len(res.stale) == 1 and res.stale[0].rule == "R05"

    def test_unjustified_entries_reported(self, tmp_path):
        baseline_path = str(tmp_path / "baseline.json")
        found = analyze_source("pkg/launch.py",
                               textwrap.dedent(SNIPPET_WITH_FINDING))
        save_baseline(baseline_path, found, reason="")
        assert len(load_baseline(baseline_path).unjustified()) == 1

    def test_missing_baseline_is_empty(self, tmp_path):
        baseline = load_baseline(str(tmp_path / "nope.json"))
        assert baseline.entries == []


class TestConfig:
    def test_parse_esguard_table(self):
        table = parse_esguard_table(textwrap.dedent("""
            [tool.other]
            enable = ["nope"]

            [tool.esguard]
            enable = ["R01", "R05"]  # trailing comment
            disable = ["R04"]
            baseline = "base.json"
            exclude = [
                "*_pb2.py",
                "build/*",
            ]

            [tool.after]
            baseline = "other.json"
        """))
        assert table["enable"] == ["R01", "R05"]
        assert table["disable"] == ["R04"]
        assert table["baseline"] == "base.json"
        assert table["exclude"] == ["*_pb2.py", "build/*"]

    def test_rule_selection(self, tmp_path):
        pyproject = tmp_path / "pyproject.toml"
        pyproject.write_text(textwrap.dedent("""
            [tool.esguard]
            enable = ["R01", "R02", "R04"]
            disable = ["R04"]
            baseline = "b.json"
        """))
        cfg = load_config(str(pyproject))
        assert cfg.rule_ids([r.id for r in all_rules()]) == ["R01", "R02"]
        assert cfg.baseline_path() == str(tmp_path / "b.json")

    def test_repo_config_parses(self):
        root = os.path.join(os.path.dirname(__file__), "..")
        cfg = load_config(os.path.join(root, "pyproject.toml"))
        assert cfg.baseline == "esguard_baseline.json"
        assert cfg.ratchet == "esguard_ratchet.json"
        assert cfg.rule_ids([r.id for r in all_rules()]) == [
            "R01", "R02", "R03", "R04", "R05", "R06", "R07", "R08", "R09",
            "R10", "R11", "R12", "R13", "R14", "R15", "R16", "R17",
            "R18", "R19", "R20", "R21", "R22", "R23"]


# ---------------------------------------------------------------------
# R23 dropped-trace-context
# ---------------------------------------------------------------------

class TestR23:
    """dropped-trace-context — a handler that received X-Trace-Id but
    whose outbound HTTP hop never forwards it cuts the assembled trace
    at this process (docs/analysis.md, docs/observability.md
    'Distributed tracing')."""

    def test_dropped_context_flagged(self):
        found = findings("""
            import json
            import urllib.request

            class Handler:
                def do_POST(self):
                    trace = self.headers.get("X-Trace-Id")
                    req = urllib.request.Request(
                        "http://up/predict", data=b"{}")
                    with urllib.request.urlopen(req, timeout=2) as resp:
                        body = resp.read()
                    self.reply(200, body, trace)
        """, "R23")
        assert len(found) == 1
        assert "X-Trace-Id" in found[0].message

    def test_httpconnection_request_flagged(self):
        found = findings("""
            import http.client

            class Handler:
                def do_POST(self):
                    trace = self.headers.get("X-Trace-Id")
                    conn = http.client.HTTPConnection("up", timeout=2)
                    conn.request("POST", "/predict", b"{}")
                    return conn.getresponse().read()
        """, "R23")
        assert len(found) == 1

    def test_header_constant_read_flagged(self):
        """Reading via the TRACE_HEADER constant is the same inbound
        receipt as the literal."""
        found = findings("""
            import urllib.request
            from estorch_tpu.obs.tracing import TRACE_HEADER

            class Handler:
                def do_POST(self):
                    trace = self.headers.get(TRACE_HEADER)
                    with urllib.request.urlopen("http://up/x",
                                                timeout=2) as resp:
                        return resp.read()
        """, "R23")
        assert len(found) == 1

    def test_dict_literal_forward_clean(self):
        """The router's shape: the trace id rides a headers dict keyed
        by the literal."""
        assert not findings("""
            import json
            import urllib.request

            class Handler:
                def do_POST(self):
                    trace = self.headers.get("X-Trace-Id")
                    req = urllib.request.Request(
                        "http://up/predict", data=b"{}",
                        headers={"X-Trace-Id": trace})
                    with urllib.request.urlopen(req, timeout=2) as resp:
                        return resp.read()
        """, "R23")

    def test_add_header_constant_forward_clean(self):
        assert not findings("""
            import urllib.request
            from estorch_tpu.obs.tracing import TRACE_HEADER

            class Handler:
                def do_POST(self):
                    trace = self.headers.get(TRACE_HEADER)
                    req = urllib.request.Request("http://up/predict")
                    req.add_header(TRACE_HEADER, trace)
                    with urllib.request.urlopen(req, timeout=2) as resp:
                        return resp.read()
        """, "R23")

    def test_subscript_store_forward_clean(self):
        assert not findings("""
            import urllib.request

            class Handler:
                def do_POST(self):
                    trace = self.headers.get("X-Trace-Id")
                    headers = {}
                    headers["X-Trace-Id"] = trace
                    req = urllib.request.Request("http://up/predict",
                                                 headers=headers)
                    with urllib.request.urlopen(req, timeout=2) as resp:
                        return resp.read()
        """, "R23")

    def test_response_header_read_clean(self):
        """A CLIENT reading X-Trace-Id off a response (the loadgen
        shape) received nothing inbound — out of scope."""
        assert not findings("""
            import urllib.request

            def probe(url):
                with urllib.request.urlopen(url, timeout=2) as resp:
                    return resp.headers.get("X-Trace-Id")
        """, "R23")

    def test_no_outbound_hop_clean(self):
        """Receiving a trace id and answering locally (the replica
        handler shape) drops nothing — there is no next hop."""
        assert not findings("""
            class Handler:
                def do_POST(self):
                    trace = self.headers.get("X-Trace-Id")
                    self.reply(200, {"trace": trace})
        """, "R23")


class TestCLI:
    def _run(self, args, cwd):
        repo_root = os.path.abspath(
            os.path.join(os.path.dirname(__file__), ".."))
        return subprocess.run(
            [sys.executable, "-m", "estorch_tpu.analysis", *args],
            capture_output=True, text=True, cwd=cwd, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": repo_root})

    def test_clean_file_exits_zero(self, tmp_path, capsys):
        # in-process (subprocess startup re-imports jax; two true
        # subprocess tests below already cover the real entry point)
        from estorch_tpu.analysis.__main__ import main

        target = tmp_path / "clean.py"
        target.write_text("def f(x):\n    return x\n")
        # --no-ratchet for the same reason as --no-baseline: the repo's
        # own ledgers describe the whole tree, not this tmp file
        assert main([str(target), "--no-baseline", "--no-ratchet"]) == 0
        assert "0 findings" in capsys.readouterr().out

    def test_findings_exit_one_and_json(self, tmp_path):
        target = tmp_path / "dirty.py"
        target.write_text(textwrap.dedent(SNIPPET_WITH_FINDING))
        res = self._run(["--json", str(target), "--no-baseline"],
                        cwd=str(tmp_path))
        assert res.returncode == 1
        report = json.loads(res.stdout)
        assert [f["rule"] for f in report["findings"]] == ["R05"]

    def test_write_baseline_then_clean(self, tmp_path):
        target = tmp_path / "dirty.py"
        target.write_text(textwrap.dedent(SNIPPET_WITH_FINDING))
        base = tmp_path / "b.json"
        res = self._run(["--baseline", str(base), "--write-baseline",
                         str(target)], cwd=str(tmp_path))
        assert res.returncode == 0, res.stdout + res.stderr
        res = self._run(["--baseline", str(base), str(target)],
                        cwd=str(tmp_path))
        # findings suppressed; auto-written entries still need a reason
        assert res.returncode == 2
        assert "UNJUSTIFIED" in res.stdout

    def test_select_filters_rules(self, tmp_path, capsys):
        from estorch_tpu.analysis.__main__ import main

        target = tmp_path / "dirty.py"
        target.write_text(textwrap.dedent(SNIPPET_WITH_FINDING))
        assert main(["--select", "R01", str(target), "--no-baseline",
                     "--no-ratchet"]) == 0
        capsys.readouterr()


# ---------------------------------------------------------------------
# regression: the R06 seed true positive (rollout carry_init probing)
# ---------------------------------------------------------------------

class TestCarryInitProbe:
    def test_introspectable_forms(self):
        from estorch_tpu.envs.rollout import carry_init_takes_params

        assert carry_init_takes_params(lambda params: params) is True
        assert carry_init_takes_params(lambda: 0) is False
        assert carry_init_takes_params(lambda params=None: params) is True

    def test_non_introspectable_zero_arg_probed_not_guessed(self):
        """rollout.py's old fallback guessed params-form on signature
        failure and crashed zero-arg callables at trace time; the fix
        probes instead."""
        from estorch_tpu.envs.rollout import carry_init_takes_params

        class NoSignature:
            @property
            def __signature__(self):
                raise ValueError("not introspectable")

            def __call__(self):
                return 0.0

        assert carry_init_takes_params(NoSignature()) is False

    def test_non_introspectable_params_form_probed(self):
        from estorch_tpu.envs.rollout import carry_init_takes_params

        class NoSignatureParams:
            @property
            def __signature__(self):
                raise ValueError("not introspectable")

            def __call__(self, params):
                return params

        assert carry_init_takes_params(NoSignatureParams()) is True


# ---------------------------------------------------------------------
# R18–R22 lockset family (project scope; analyze_source runs them on a
# single-module "program" so fixtures stay one snippet each)
# ---------------------------------------------------------------------

class TestR18:
    def test_bare_write_next_to_locked_write_flagged(self):
        found = findings("""
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.state = {}

                def put(self, k, v):
                    with self._lock:
                        self.state[k] = v
                        self.version = 1

                def clear(self):
                    self.version = 2
        """, "R18")
        assert len(found) == 1
        assert found[0].symbol == "Store.clear"
        assert "bare here" in found[0].message

    def test_init_writes_never_count_as_bare(self):
        """__init__ runs before the object escapes to other threads —
        the constructor publishing unlocked fields is the normal idiom,
        not a race."""
        assert not findings("""
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.version = 0

                def bump(self):
                    with self._lock:
                        self.version += 1
        """, "R18")

    def test_locked_suffix_convention_suppresses(self):
        """Documented suppression: a `*_locked` helper asserts its
        caller holds the lock — flagging its body would punish the
        exact factoring the hint recommends."""
        assert not findings("""
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.version = 0

                def bump(self):
                    with self._lock:
                        self._bump_locked()
                        self.version += 1

                def _bump_locked(self):
                    self.version += 1
        """, "R18")


class TestR19:
    def test_inverted_order_flagged(self):
        found = findings("""
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def forward():
                with a:
                    with b:
                        pass

            def backward():
                with b:
                    with a:
                        pass
        """, "R19")
        assert len(found) == 1
        assert found[0].severity == "error"
        assert "inversion" in found[0].message

    def test_consistent_order_clean(self):
        assert not findings("""
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def one():
                with a:
                    with b:
                        pass

            def two():
                with a:
                    with b:
                        pass
        """, "R19")

    def test_one_level_call_expansion(self):
        """An inner acquire one call down still forms an edge: holder()
        takes `a` then calls helper() which takes `b`; inverse() takes
        b→a lexically."""
        found = findings("""
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def helper():
                with b:
                    pass

            def holder():
                with a:
                    helper()

            def inverse():
                with b:
                    with a:
                        pass
        """, "R19")
        assert found


class TestR20:
    def test_thread_target_mutating_foreign_state_flagged(self):
        found = findings("""
            import threading

            def poll(rep):
                rep.health = "ok"

            def start(rep):
                t = threading.Thread(target=poll, args=(rep,))
                t.daemon = True
                t.start()
        """, "R20")
        assert len(found) == 1
        assert "torn update" in found[0].message

    def test_locked_foreign_write_clean(self):
        assert not findings("""
            import threading

            def poll(rep):
                with rep.lock:
                    rep.health = "ok"

            def start(rep):
                t = threading.Thread(target=poll, args=(rep,))
                t.daemon = True
                t.start()
        """, "R20")

    def test_fresh_object_clean(self):
        """Documented suppression boundary: an object the function
        itself constructed cannot be shared yet — mutating it bare is
        fine even on a thread."""
        assert not findings("""
            import threading

            class Report:
                pass

            def poll(q):
                rep = Report()
                rep.health = "ok"
                q.put(rep)

            def start(q):
                t = threading.Thread(target=poll, args=(q,))
                t.daemon = True
                t.start()
        """, "R20")

    def test_unreachable_helper_clean(self):
        """A function no thread/callback/handler can reach is
        single-threaded by construction — its bare foreign writes are
        the caller's normal synchronous mutation."""
        assert not findings("""
            def tweak(cfg):
                cfg.verbose = True
        """, "R20")


class TestR21:
    def test_blocking_get_under_lock_flagged(self):
        found = findings("""
            import threading

            class Pump:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q

                def drain(self):
                    with self._lock:
                        item = self._q.get()
                        return item
        """, "R21")
        assert len(found) == 1
        assert "block indefinitely" in found[0].message

    def test_timeout_clean(self):
        assert not findings("""
            import threading

            class Pump:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q

                def drain(self):
                    with self._lock:
                        return self._q.get(timeout=1.0)
        """, "R21")

    def test_condition_wait_idiom_exempt(self):
        """Documented suppression: `with cond: cond.wait()` RELEASES
        the lock while waiting — the one blocking-under-lock shape that
        is not just correct but required by the API."""
        assert not findings("""
            import threading

            class Gate:
                def __init__(self):
                    self._cond = threading.Condition()

                def block_until_open(self):
                    with self._cond:
                        self._cond.wait()
        """, "R21")


class TestR22:
    def test_unjoined_nondaemon_flagged(self):
        found = findings("""
            import threading

            def work():
                pass

            def start():
                t = threading.Thread(target=work)
                t.start()
                return t
        """, "R22")
        assert len(found) == 1
        assert "never" in found[0].message

    def test_daemon_clean(self):
        assert not findings("""
            import threading

            def work():
                pass

            def start():
                t = threading.Thread(target=work, daemon=True)
                t.start()
        """, "R22")

    def test_joined_clean(self):
        assert not findings("""
            import threading

            def work():
                pass

            def run():
                t = threading.Thread(target=work)
                t.start()
                t.join()
        """, "R22")

    def test_list_append_loop_join_clean(self):
        """Documented suppression: threads appended to a list and
        joined in a loop ARE joined — matching `list:xs` idents keeps
        the fan-out/fan-in idiom quiet."""
        assert not findings("""
            import threading

            def work(i):
                pass

            def fan_out():
                ts = []
                for i in range(4):
                    t = threading.Thread(target=work, args=(i,))
                    ts.append(t)
                    t.start()
                for t in ts:
                    t.join()
        """, "R22")


# ---------------------------------------------------------------------
# ratchet: per-rule shrink-only counts
# ---------------------------------------------------------------------

class TestRatchet:
    def _findings(self, n):
        return [Finding(rule="R20", file=f"f{i}.py", line=1, col=0,
                        severity="warning", message="m", hint="h",
                        symbol="s", snippet=f"x = {i}")
                for i in range(n)]

    def test_round_trip(self, tmp_path):
        from estorch_tpu.analysis import (check_ratchet, count_findings,
                                          load_ratchet, save_ratchet)

        path = str(tmp_path / "ratchet.json")
        save_ratchet(path, count_findings(self._findings(2), ["R20"]))
        recorded = load_ratchet(path)
        assert recorded == {"R20": 2}
        assert check_ratchet(recorded, self._findings(2)).ok()

    def test_growth_is_regression(self, tmp_path):
        from estorch_tpu.analysis import check_ratchet

        res = check_ratchet({"R20": 1}, self._findings(3))
        assert res.regressions == [("R20", 1, 3)]
        assert not res.ok()

    def test_shrink_is_stale(self):
        """Fixing a race without lowering the count reports STALE, so
        the improvement gets locked in instead of silently regressable."""
        from estorch_tpu.analysis import check_ratchet

        res = check_ratchet({"R20": 3}, self._findings(1))
        assert res.stale == [("R20", 3, 1)]
        assert not res.ok()

    def test_missing_file_checks_nothing(self, tmp_path):
        from estorch_tpu.analysis import check_ratchet, load_ratchet

        recorded = load_ratchet(str(tmp_path / "nope.json"))
        assert recorded == {}
        assert check_ratchet(recorded, self._findings(5)).ok()

    def test_cli_regression_exits_one(self, tmp_path, capsys):
        from estorch_tpu.analysis.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(textwrap.dedent("""
            import threading

            def poll(rep):
                rep.health = "ok"

            def start(rep):
                t = threading.Thread(target=poll, args=(rep,), daemon=True)
                t.start()
        """))
        ratchet = tmp_path / "ratchet.json"
        ratchet.write_text('{"version": 1, "counts": {"R20": 0}}\n')
        code = main([str(dirty), "--no-baseline",
                     "--ratchet", str(ratchet)])
        assert code == 1
        assert "RATCHET regression" in capsys.readouterr().out

    def test_cli_write_then_clean_then_stale(self, tmp_path, capsys):
        from estorch_tpu.analysis.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(textwrap.dedent("""
            import threading

            def poll(rep):
                rep.health = "ok"

            def start(rep):
                t = threading.Thread(target=poll, args=(rep,), daemon=True)
                t.start()
        """))
        ratchet = tmp_path / "ratchet.json"
        # pin current counts; baseline suppression is separate, so run
        # with --no-baseline and rely on the ratchet alone
        assert main([str(dirty), "--no-baseline", "--select", "R20",
                     "--ratchet", str(ratchet), "--write-ratchet"]) == 0
        capsys.readouterr()
        # still 1: ratchet bounds total debt; the finding itself is
        # unsuppressed without a baseline
        assert main([str(dirty), "--no-baseline", "--select", "R20",
                     "--ratchet", str(ratchet)]) == 1
        capsys.readouterr()
        # fix the race -> count shrinks -> STALE (exit 2) until re-pinned
        dirty.write_text("def poll(rep):\n    return rep\n")
        assert main([str(dirty), "--no-baseline", "--select", "R20",
                     "--ratchet", str(ratchet)]) == 2
        assert "STALE ratchet" in capsys.readouterr().out


# ---------------------------------------------------------------------
# CLI: --changed, --format=json, --jobs
# ---------------------------------------------------------------------

class TestChangedMode:
    def _git(self, *args, cwd):
        subprocess.run(["git", *args], cwd=cwd, check=True,
                       capture_output=True, timeout=30,
                       env={**os.environ,
                            "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                            "GIT_COMMITTER_NAME": "t",
                            "GIT_COMMITTER_EMAIL": "t@t"})

    def test_changed_analyzes_only_touched_files(self, tmp_path,
                                                 monkeypatch, capsys):
        from estorch_tpu.analysis.__main__ import main

        self._git("init", "-q", cwd=tmp_path)
        clean = tmp_path / "clean.py"
        clean.write_text("def f(x):\n    return x\n")
        dirty = tmp_path / "dirty.py"
        dirty.write_text("def g(x):\n    return x\n")
        self._git("add", ".", cwd=tmp_path)
        self._git("commit", "-q", "-m", "base", cwd=tmp_path)
        dirty.write_text(textwrap.dedent(SNIPPET_WITH_FINDING))
        self._git("add", ".", cwd=tmp_path)
        self._git("commit", "-q", "-m", "edit", cwd=tmp_path)

        monkeypatch.chdir(tmp_path)
        code = main(["--changed", "HEAD~1..HEAD", "--no-baseline"])
        out = capsys.readouterr().out
        assert code == 1
        assert "dirty.py" in out and "clean.py" not in out

    def test_changed_with_no_python_edits_exits_zero(self, tmp_path,
                                                     monkeypatch, capsys):
        from estorch_tpu.analysis.__main__ import main

        self._git("init", "-q", cwd=tmp_path)
        (tmp_path / "notes.txt").write_text("a\n")
        self._git("add", ".", cwd=tmp_path)
        self._git("commit", "-q", "-m", "base", cwd=tmp_path)
        (tmp_path / "notes.txt").write_text("b\n")
        self._git("add", ".", cwd=tmp_path)
        self._git("commit", "-q", "-m", "edit", cwd=tmp_path)

        monkeypatch.chdir(tmp_path)
        assert main(["--changed", "HEAD~1..HEAD", "--no-baseline"]) == 0
        assert "no changed python files" in capsys.readouterr().out

    def test_bad_range_exits_three(self, tmp_path, monkeypatch, capsys):
        from estorch_tpu.analysis.__main__ import main

        self._git("init", "-q", cwd=tmp_path)
        monkeypatch.chdir(tmp_path)
        assert main(["--changed", "not-a-ref..HEAD",
                     "--no-baseline"]) == 3
        capsys.readouterr()


class TestJsonFormat:
    def test_format_json_includes_ratchet_block(self, tmp_path, capsys):
        from estorch_tpu.analysis.__main__ import main

        target = tmp_path / "clean.py"
        target.write_text("def f(x):\n    return x\n")
        ratchet = tmp_path / "ratchet.json"
        ratchet.write_text('{"version": 1, "counts": {"R20": 0}}\n')
        assert main(["--format=json", str(target), "--no-baseline",
                     "--ratchet", str(ratchet)]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["findings"] == []
        assert report["ratchet"]["regressions"] == []
        assert report["ratchet"]["stale"] == []

    def test_legacy_json_flag_still_works(self, tmp_path, capsys):
        from estorch_tpu.analysis.__main__ import main

        target = tmp_path / "clean.py"
        target.write_text("def f(x):\n    return x\n")
        assert main(["--json", str(target), "--no-baseline",
                     "--no-ratchet"]) == 0
        assert json.loads(capsys.readouterr().out)["findings"] == []


class TestParallelEquivalence:
    def test_pool_and_serial_agree(self, tmp_path, monkeypatch):
        """The fork pool is an optimization, never a semantic change:
        16+ files (the pool threshold) through jobs=2 and jobs=1 must
        produce identical findings, including the project-scope pass
        over summaries shipped back from workers."""
        from estorch_tpu.analysis import analyze_paths, sort_findings

        racy = textwrap.dedent("""
            import threading

            def poll(rep):
                rep.health = "ok"

            def start(rep):
                t = threading.Thread(target=poll, args=(rep,), daemon=True)
                t.start()
        """)
        for i in range(17):
            (tmp_path / f"m{i:02d}.py").write_text(
                racy if i % 3 == 0 else "def f(x):\n    return x\n")
        monkeypatch.chdir(tmp_path)
        serial = sort_findings(analyze_paths([str(tmp_path)], jobs=1))
        pooled = sort_findings(analyze_paths([str(tmp_path)], jobs=2))
        assert [f.to_dict() for f in serial] == [
            f.to_dict() for f in pooled]
        assert any(f.rule == "R20" for f in serial)


class TestRuleTableSync:
    def test_docs_table_matches_registry(self):
        """docs/analysis.md embeds the generated rule table between
        markers; regenerating must be a no-op or the catalog drifted."""
        from estorch_tpu.analysis import render_rule_table

        doc = os.path.join(os.path.dirname(__file__), "..", "docs",
                           "analysis.md")
        text = open(doc, encoding="utf-8").read()
        begin, end = "<!-- BEGIN RULE TABLE -->", "<!-- END RULE TABLE -->"
        assert begin in text and end in text
        embedded = text.split(begin)[1].split(end)[0].strip()
        assert embedded == render_rule_table().strip()
