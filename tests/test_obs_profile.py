"""Performance attribution (estorch_tpu/obs/profile/): cost model,
roofline, compile ledger, `obs profile` CLI, the phase-localized regress
gate, and bench.py's probe-gated platform decision.

The acceptance contract (ISSUE 6): a run with known per-step FLOPs
produces exactly the expected MFU; ledger entries round-trip the
Prometheus exposition parser; degenerate inputs degrade to a note
(never a crash); an injected 30% eval-phase slowdown is flagged NAMING
the eval phase; and bench decides its platform from the typed device
probe instead of a 480s timeout.
"""

import json
import os
import subprocess
import sys

import pytest

from estorch_tpu.obs.__main__ import main as obs_main
from estorch_tpu.obs.export import regress
from estorch_tpu.obs.export.prometheus import (is_gauge, parse_exposition,
                                               render_exposition,
                                               samples_by_name)
from estorch_tpu.obs.profile import (CompileLedger, collect_compile_events,
                                     find_cost_model, format_profile,
                                     generation_cost, ledger_counters,
                                     measure_cpu_roofline, phase_cost_for,
                                     platform_roofline, profile_records)
from estorch_tpu.obs.profile.report import selfcheck as profile_selfcheck
from estorch_tpu.obs.spans import Telemetry


# ---------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------

class TestCostModel:
    SHAPES = [(3, 64), (64, 64), (64, 1)]

    def test_generation_cost_known_math(self):
        kernels = sum(m * n for m, n in self.SHAPES)
        param_dim = kernels + 64 + 64 + 1
        m = generation_cost(population=4096, matmul_shapes=self.SHAPES,
                            param_dim=param_dim, horizon=200)
        assert m["flops_per_env_step"] == 2 * kernels
        assert m["bytes_per_env_step"] == param_dim * 4
        assert m["env_steps_per_generation"] == 4096 * 200
        # mirrored: one table row per antithetic pair
        assert m["noise_dim"] == param_dim
        assert m["per_generation"]["sample"]["flops"] == \
            2 * 4096 * param_dim
        assert m["per_generation"]["update"]["flops"] == \
            2 * (4096 // 2) * param_dim
        assert m["per_generation"]["eval"]["flops"] == \
            4096 * 200 * 2 * kernels

    def test_low_rank_shrinks_noise_dim(self):
        kernels = sum(m * n for m, n in self.SHAPES)
        param_dim = kernels + 129
        m = generation_cost(population=256, matmul_shapes=self.SHAPES,
                            param_dim=param_dim, horizon=10, low_rank=2)
        factors = sum((a + b) * 2 for a, b in self.SHAPES)
        assert m["noise_dim"] == factors + 129  # factored kernels + dense rest
        assert m["noise_dim"] < param_dim
        # the factored apply adds the per-step reconstruction term
        assert m["flops_per_env_step"] > 2 * kernels

    def test_phase_cost_fused_device_is_the_sum(self):
        m = generation_cost(population=64, matmul_shapes=self.SHAPES,
                            param_dim=4481, horizon=10)
        steps = 64 * 10 * 3  # 3 generations' recorded env steps
        parts = [phase_cost_for(m, p, env_steps=steps, n_generations=3)
                 for p in ("sample", "eval", "update")]
        fused = phase_cost_for(m, "device", env_steps=steps,
                               n_generations=3)
        assert fused["flops"] == sum(p["flops"] for p in parts)
        assert fused["bytes"] == sum(p["bytes"] for p in parts)
        # host bookkeeping phases carry no modeled cost, by design
        assert phase_cost_for(m, "dispatch", env_steps=steps,
                              n_generations=3) is None

    def test_horizonless_model_omits_eval(self):
        m = generation_cost(population=16, matmul_shapes=self.SHAPES,
                            param_dim=4481, horizon=None)
        assert "eval" not in m["per_generation"]
        # eval cost still derivable from recorded env_steps
        c = phase_cost_for(m, "eval", env_steps=100, n_generations=1)
        assert c["flops"] == 100 * m["flops_per_env_step"]


class TestRoofline:
    def test_cpu_calibration_measures_positive_peaks(self):
        cal = measure_cpu_roofline(budget_s=0.05, gemm_n=128, copy_mb=4)
        assert cal["peak_flops_per_s"] > 0
        assert cal["peak_bytes_per_s"] > 0
        assert cal["basis"] == "cpu_calibrated"

    def test_tpu_roofline_is_the_datasheet(self):
        r = platform_roofline("tpu")
        assert r["peak_flops_per_s"] == 197e12
        assert r["basis"] == "tpu_v5e_bf16_peak"

    def test_unmeasured_cpu_roofline_keeps_the_tag(self):
        r = platform_roofline("cpu", measure=False)
        assert r["peak_flops_per_s"] is None
        assert r["basis"] == "cpu_calibrated"

    def test_unknown_platform_gets_no_denominator(self):
        """A gpu (or anything that isn't tpu/cpu) must NOT inherit the
        host CPU's measured GEMM ceiling as its peak — None-peaks and no
        basis, so MFU honestly stays null there."""
        r = platform_roofline("gpu")
        assert r["peak_flops_per_s"] is None
        assert r["peak_bytes_per_s"] is None
        assert r["basis"] is None
        assert r["platform"] == "gpu"


# ---------------------------------------------------------------------
# compile ledger + exposition round trip
# ---------------------------------------------------------------------

class TestCompileLedger:
    def test_take_new_cursor(self):
        led = CompileLedger()
        led.record("a", 1.0, generation=0)
        led.record("b", 2.0, generation=0, xla_flops=5e9)
        first = led.take_new()
        assert [e["program"] for e in first] == ["a", "b"]
        assert led.take_new() == []
        led.record("c", 3.0, generation=1)
        assert [e["program"] for e in led.take_new()] == ["c"]
        assert len(led) == 3

    def test_ledger_rides_exposition_and_parses_back(self):
        """Satellite 3: compile-ledger entries round-trip through the
        validating Prometheus parser."""
        entries = [{"program": "generation_step", "compile_s": 12.5,
                    "generation": 0, "xla_flops": 7.25e9,
                    "peak_bytes": 2.5e9}]
        folded = ledger_counters(entries)
        assert folded["compile_s_generation_step"] == 12.5
        assert folded["compile_xla_flops_generation_step"] == 7.25e9
        body = render_exposition(folded, up=True)
        vals = samples_by_name(parse_exposition(body))
        assert vals["estorch_compile_s_generation_step"] == 12.5
        assert vals["estorch_compile_peak_bytes_generation_step"] == 2.5e9
        # ledger facts are gauges (last-write-wins per program)
        assert is_gauge("compile_s_generation_step")
        assert is_gauge("compile_peak_bytes_generation_step")
        assert not is_gauge("recompiles")
        assert "# TYPE estorch_compile_s_generation_step gauge" in body

    def test_telemetry_compile_event_feeds_counters_and_flush(self):
        t = Telemetry()
        t.compile_event("prog_a", 1.5, first_call=True)
        t.compile_event("prog_b", 0.5, count_recompiles=0)
        snap = t.counters.snapshot()
        assert snap["recompiles"] == 1  # count_recompiles=0 respected
        assert snap["compile_time_s"] == 2.0  # cumulative over the ledger
        assert snap["compile_s_prog_a"] == 1.5
        evs = t.take_compile_events()
        assert [e["program"] for e in evs] == ["prog_a", "prog_b"]
        assert evs[0]["first_call"] is True
        assert t.take_compile_events() == []

    def test_disabled_telemetry_is_inert(self):
        t = Telemetry(enabled=False)
        assert t.compile_event("x", 1.0) is None
        assert t.take_compile_events() == []
        t.set_cost_model({"schema": 1})
        assert t.cost_model is None

    def test_collect_compile_events_skips_garbage(self):
        recs = [{"compile_events": [{"program": "a", "compile_s": 1.0},
                                    "not-a-dict"]},
                {"compile_events": "nope"}, {}, "junk"]
        assert collect_compile_events(recs) == \
            [{"program": "a", "compile_s": 1.0}]


# ---------------------------------------------------------------------
# profile_records: known math + the tolerance contract
# ---------------------------------------------------------------------

def _synth_run(eval_s=1.0, n=6, with_model=True, with_compiles=True):
    shapes = [(3, 64), (64, 64), (64, 1)]
    kernels = sum(m * n for m, n in shapes)
    model = generation_cost(population=512, matmul_shapes=shapes,
                            param_dim=kernels + 129, horizon=50)
    recs = []
    for g in range(n):
        rec = {"generation": g, "env_steps": 512 * 50,
               "env_steps_per_sec": 512 * 50 / (eval_s + 0.1),
               "wall_time_s": eval_s + 0.1, "reward_mean": 0.0,
               "reward_max": 0.0, "best_reward": 0.0,
               "phases": {"sample": 0.02, "eval": eval_s, "update": 0.08}}
        if g == 0:
            if with_model:
                rec["cost_model"] = model
            if with_compiles:
                rec["compile_events"] = [
                    {"program": "generation_step", "compile_s": 4.0,
                     "generation": 0,
                     "xla_flops": float(512 * 50 * 2 * kernels)}]
        recs.append(json.loads(json.dumps(rec)))
    return recs, model, kernels


class TestProfileRecords:
    ROOF = {"platform": "synthetic", "basis": "selfcheck",
            "peak_flops_per_s": 1e12, "peak_bytes_per_s": 1e11}

    def test_known_flops_exact_mfu(self):
        recs, model, kernels = _synth_run()
        p = profile_records(recs, self.ROOF)
        eval_row = p["phases"]["eval"]
        n = len(recs)
        want = (n * 512 * 50 * 2 * kernels) / (n * 1.0) / 1e12
        assert eval_row["mfu"] == pytest.approx(want, abs=0, rel=1e-12)
        assert eval_row["bound"] == "memory"  # GEMV regime vs ridge 10
        assert p["compile"]["n_events"] == 1
        # the fused program's XLA estimate vs the analytic per-gen total:
        # eval dominates, so the ratio lands near (eval+sample+update)/eval
        assert 0.9 < p["compile"]["model_vs_xla_flops_ratio"] < 1.5
        assert "eval" in format_profile(p)

    def test_phaseless_records_degrade_to_a_note(self):
        recs = [{"generation": g, "env_steps": 10,
                 "env_steps_per_sec": 1.0, "wall_time_s": 10.0,
                 "reward_mean": 0, "reward_max": 0, "best_reward": 0}
                for g in range(3)]
        p = profile_records(recs, self.ROOF)
        assert any("no phase spans" in n for n in p["notes"])
        assert any("no cost_model" in n for n in p["notes"])
        assert any("no compile events" in n for n in p["notes"])
        assert format_profile(p)  # renders, never raises

    def test_empty_and_modelless_runs(self):
        assert profile_records([], self.ROOF)["generations"] == 0
        recs, _, _ = _synth_run(with_model=False, with_compiles=False)
        p = profile_records(recs, self.ROOF)
        assert p["has_cost_model"] is False
        # time shares still reported without a model
        assert p["phases"]["eval"]["share"] > 0.8
        assert "mfu" not in p["phases"]["eval"]

    def test_uncalibrated_roofline_is_rates_only(self):
        recs, _, _ = _synth_run()
        p = profile_records(recs, {"platform": "cpu",
                                   "basis": "cpu_calibrated",
                                   "peak_flops_per_s": None,
                                   "peak_bytes_per_s": None})
        assert "flops_per_s" in p["phases"]["eval"]
        assert "mfu" not in p["phases"]["eval"]

    def test_replayed_generations_deduped(self):
        recs, _, _ = _synth_run(n=4)
        slow_replay = json.loads(json.dumps(recs[1]))
        slow_replay["phases"]["eval"] = 99.0
        recs_replayed = recs + [slow_replay]  # gen 1 replayed, last wins
        p = profile_records(recs_replayed, self.ROOF)
        assert p["generations"] == 4
        assert p["phases"]["eval"]["seconds"] == pytest.approx(
            3 * 1.0 + 99.0)

    def test_find_cost_model(self):
        recs, model, _ = _synth_run()
        assert find_cost_model(recs) == model
        assert find_cost_model([{"a": 1}]) is None

    def test_selfcheck_clean(self):
        assert profile_selfcheck() == []


# ---------------------------------------------------------------------
# phase-localized regress
# ---------------------------------------------------------------------

class TestPhaseRegress:
    def test_identical_runs_pass(self):
        recs, _, _ = _synth_run()
        v = regress.compare_phases(recs, recs)
        assert v["verdict"] == "pass"
        assert v["regressed_phases"] == []

    def test_eval_slowdown_flagged_naming_eval_only(self):
        """THE acceptance check: a 30% eval-phase slowdown is flagged
        naming the eval phase — and only it."""
        base, _, _ = _synth_run()
        slow, _, _ = _synth_run(eval_s=1.3)
        v = regress.compare_phases(slow, base)
        assert v["verdict"] == "regress"
        assert v["regressed_phases"] == ["eval"]
        assert v["phases"]["sample"]["verdict"] == "pass"
        assert v["phases"]["eval"]["slowdown_pct"] == pytest.approx(30, abs=1)

    def test_no_phase_rows_is_a_one_line_error(self):
        """Phase-less records degrade to the mixed-schema diagnosis (one
        line, names the side lacking rows) — never a bogus verdict."""
        with pytest.raises(ValueError,
                           match="carries no per-phase rows") as ei:
            regress.compare_phases([{"generation": 0}], [{"generation": 0}])
        assert "\n" not in str(ei.value)

    def test_disjoint_phase_names_is_an_error(self):
        with pytest.raises(ValueError, match="no shared top-level phases"):
            regress.compare_phases(
                [{"generation": 0, "phases": {"eval": 1.0}}],
                [{"generation": 0, "phases": {"update": 1.0}}])

    def test_cli_phases_exit_codes(self, tmp_path, capsys):
        base, _, _ = _synth_run()
        slow, _, _ = _synth_run(eval_s=1.3)
        bp, sp = tmp_path / "base.jsonl", tmp_path / "slow.jsonl"
        for path, recs in ((bp, base), (sp, slow)):
            with open(path, "w") as f:
                for r in recs:
                    f.write(json.dumps(r) + "\n")
        rc = obs_main(["regress", str(bp), "--baseline", str(bp),
                       "--phases"])
        assert rc == 0
        rc = obs_main(["regress", str(sp), "--baseline", str(bp),
                       "--phases"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "eval" in out and "REGRESSION" in out

    def test_cli_phases_rejects_label(self, tmp_path, capsys):
        """--label filters bench A/B rows; phase records carry no labels.
        Combining them is a usage error (exit 3), not a silently
        unfiltered verdict."""
        base, _, _ = _synth_run()
        bp = tmp_path / "base.jsonl"
        with open(bp, "w") as f:
            for r in base:
                f.write(json.dumps(r) + "\n")
        rc = obs_main(["regress", str(bp), "--baseline", str(bp),
                       "--phases", "--label", "headline"])
        assert rc == 3
        assert "cannot combine" in capsys.readouterr().err


class TestPlatformGuard:
    def test_cpu_fallback_vs_tpu_baseline_is_an_error(self, tmp_path):
        """Satellite 1: a cpu-fallback artifact against a TPU baseline is
        a platform-mismatch ERROR, never a bogus verdict."""
        tpu = tmp_path / "BENCH_tpu.json"
        with open(tpu, "w") as f:
            json.dump({"parsed": {"metric": "m", "value": 5e6,
                                  "unit": "env-steps/s/chip (x, tpu)"}}, f)
        cpu = tmp_path / "BENCH_cpu.json"
        with open(cpu, "w") as f:
            json.dump({"parsed": {"metric": "m", "value": 4e4},
                       "extras": {"device_probe": {
                           "status": "failed", "reason": "init-hang",
                           "platform": "cpu", "cpu_fallback": True}}}, f)
        with pytest.raises(ValueError, match="platform mismatch"):
            regress.compare_files(str(cpu), str(tpu))
        rc = obs_main(["regress", str(cpu), "--baseline", str(tpu)])
        assert rc == 1

    def test_legacy_fallback_prose_reads_as_cpu(self):
        row = {"parsed": {"metric": "m", "value": 1.0,
                          "unit": "env-steps/s/chip (Pendulum, cpu, "
                                  "TPU-PATH-FAILED cpu fallback — see "
                                  "stderr)"}}
        assert regress.measurement_platform([row]) == "cpu"

    def test_same_platform_still_verdicts(self, tmp_path):
        a = tmp_path / "a.json"
        with open(a, "w") as f:
            json.dump({"parsed": {"metric": "m", "value": 100.0},
                       "platform": "cpu"}, f)
        v = regress.compare_files(str(a), str(a))
        assert v["verdict"] == "pass"
        assert v["platform"] == "cpu"


# ---------------------------------------------------------------------
# a REAL run end to end: cost model + ledger ride the records
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def profiled_run(tmp_path_factory):
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import CartPole
    from estorch_tpu.obs import JsonlSink

    es = ES(
        MLPPolicy, JaxAgent, optax.adam,
        population_size=16, sigma=0.1, seed=0,
        policy_kwargs={"action_dim": 2, "hidden": (8,), "discrete": True},
        agent_kwargs={"env": CartPole(), "horizon": 25},
        optimizer_kwargs={"learning_rate": 0.05},
    )
    d = tmp_path_factory.mktemp("profiled_run")
    path = str(d / "run.jsonl")
    sink = JsonlSink(path)
    es.train(3, verbose=False, log_fn=sink)
    sink.close()
    return es, path


class TestRealRun:
    def test_cost_model_and_ledger_ride_the_records(self, profiled_run):
        es, path = profiled_run
        from estorch_tpu.obs import JsonlSink

        recs = JsonlSink.read(path)
        model = find_cost_model(recs)
        assert model is not None
        assert model["population"] == 16
        # CartPole MLP 4 -> 8 -> 2: kernels (4,8) and (8,2)
        assert sorted(map(tuple, model["matmul_shapes"])) == \
            [(4, 8), (8, 2)]
        events = collect_compile_events(recs)
        assert any(e["program"] == "generation_step" for e in events)
        assert all(e["compile_s"] >= 0 for e in events)
        # the model rides ONCE (first record), not every record
        assert sum(1 for r in recs if "cost_model" in r) == 1

    def test_profile_cli_on_real_run(self, profiled_run, capsys):
        _, path = profiled_run
        assert obs_main(["profile", path]) == 0
        out = capsys.readouterr().out
        assert "cpu_calibrated" in out
        assert "compiles" in out
        assert obs_main(["profile", path, "--json"]) == 0
        p = json.loads(capsys.readouterr().out)
        assert p["has_cost_model"] is True
        assert p["compile"]["n_events"] >= 1
        assert p["phases"]["device"]["mfu"] > 0

    def test_profile_cli_tolerates_truncated_tail(self, profiled_run,
                                                  tmp_path, capsys):
        _, path = profiled_run
        clone = tmp_path / "truncated.jsonl"
        with open(path) as f:
            text = f.read()
        with open(clone, "w") as f:
            f.write(text + '{"generation": 99, "env_ste')
        assert obs_main(["profile", str(clone)]) == 0
        err = capsys.readouterr().err
        assert "truncated" in err

    def test_profile_reads_real_manifest_device_list(self, profiled_run,
                                                     tmp_path, capsys):
        """The manifest schema (obs/manifest.py) stores ``devices`` as a
        LIST of per-device dicts — platform auto-detection must read it
        (a real manifest beside the jsonl used to crash the CLI)."""
        import shutil

        _, path = profiled_run
        d = tmp_path / "run_with_manifest"
        d.mkdir()
        shutil.copy(path, d / "run.jsonl")
        with open(d / "manifest.json", "w") as f:
            json.dump({"devices": [
                {"id": 0, "platform": "tpu", "kind": "TPU v5 lite",
                 "process_index": 0}]}, f)
        assert obs_main(["profile", str(d / "run.jsonl"), "--json"]) == 0
        p = json.loads(capsys.readouterr().out)
        assert p["platform"] == "tpu"
        assert p["basis"] == "tpu_v5e_bf16_peak"
        # cpu manifest keeps the measured-host basis
        with open(d / "manifest.json", "w") as f:
            json.dump({"devices": [
                {"id": 0, "platform": "cpu", "kind": "cpu",
                 "process_index": 0}]}, f)
        assert obs_main(["profile", str(d / "run.jsonl"), "--json"]) == 0
        p = json.loads(capsys.readouterr().out)
        assert p["platform"] == "cpu"
        assert p["basis"] == "cpu_calibrated"

    def test_trace_renders_compiles_lane(self, profiled_run):
        from estorch_tpu.obs import JsonlSink
        from estorch_tpu.obs.export.traceevent import (export_trace,
                                                       validate_trace)

        _, path = profiled_run
        recs = JsonlSink.read(path)
        trace = export_trace(recs)
        assert validate_trace(trace) == []
        compiles = [e for e in trace["traceEvents"]
                    if e.get("cat") == "compile"]
        assert any(e["name"] == "compile:generation_step"
                   for e in compiles)
        assert all(e["tid"] == 3 for e in compiles)

    def test_disabled_telemetry_skips_model_build(self, monkeypatch):
        """telemetry=False must not pay for the model at all — building
        it unravels the device param tree to host only for set_cost_model
        to discard it."""
        import torch

        from estorch_tpu import ES
        from estorch_tpu.algo import es as es_mod

        def boom(self):
            raise AssertionError("_build_cost_model called with "
                                 "telemetry disabled")

        monkeypatch.setattr(es_mod.ES, "_build_cost_model", boom)

        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = torch.nn.Linear(4, 2)

            def forward(self, x):
                return self.lin(x)

        class A:
            def rollout(self, policy):
                self.last_episode_steps = 1
                return 0.0

        es = ES(P, A, torch.optim.Adam, population_size=4, sigma=0.1,
                seed=0, optimizer_kwargs={"lr": 1e-2},
                table_size=1 << 10, telemetry=False)
        assert es.obs.cost_model is None

    def test_host_backend_cost_model(self):
        """The third engine family: torch policies get their matmul model
        from the live parameter tensors; horizon stays unknown (host
        agents own their rollout length) and no XLA compile events
        exist — `obs profile` notes both instead of crashing."""
        import torch

        from estorch_tpu import ES

        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.net = torch.nn.Sequential(
                    torch.nn.Linear(4, 8), torch.nn.Tanh(),
                    torch.nn.Linear(8, 2))

            def forward(self, x):
                return self.net(x)

        class A:
            def rollout(self, policy):
                with torch.no_grad():
                    v = torch.nn.utils.parameters_to_vector(
                        policy.parameters())
                self.last_episode_steps = 1
                return -float((v ** 2).sum())

        es = ES(P, A, torch.optim.Adam, population_size=4, sigma=0.1,
                seed=0, optimizer_kwargs={"lr": 1e-2}, table_size=1 << 10)
        m = es.obs.cost_model
        assert sorted(map(tuple, m["matmul_shapes"])) == [(2, 8), (8, 4)]
        assert "env_steps_per_generation" not in m
        es.train(2, verbose=False)
        assert "cost_model" in es.history[0]
        assert "compile_events" not in es.history[0]
        p = profile_records(es.history, platform_roofline("cpu"))
        assert any("no compile events" in n for n in p["notes"])
        assert "eval" in p["phases"]
        es.engine.close()

    def test_ledger_gauges_reach_the_registry(self, profiled_run):
        es, _ = profiled_run
        snap = es.obs.counters.snapshot()
        assert snap["compile_s_generation_step"] > 0
        assert snap["compile_time_s"] > 0
        # and they render as gauges in the exposition
        body = render_exposition(snap)
        assert "# TYPE estorch_compile_s_generation_step gauge" in body
        parse_exposition(body)  # must stay parseable with ledger gauges


# ---------------------------------------------------------------------
# bench.py: probe-gated platform decision + scratch hygiene (jax-free)
# ---------------------------------------------------------------------

@pytest.fixture()
def bench_mod():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def _fake_row(platform="cpu"):
    return {"rate": 1000.0, "platform": platform, "dtype": "float32",
            "mfu": 2.5e-05, "mfu_basis": "cpu_calibrated",
            "phases": {"device": {"share": 0.5, "seconds": 1.0,
                                  "mfu": 2.5e-05}},
            "compile": {"n_events": 1},
            "peak_hbm_gb": None, "peak_rss_gb": 1.0, "cfg": {}}


class _FakeDoctor:
    def __init__(self, verdict):
        self.verdict = verdict

    def check_device(self, timeout_s=20.0, platform=None):
        return dict(self.verdict)


class TestBenchPlatformDecision:
    def _run_main(self, bench_mod, monkeypatch, capsys, probe,
                  stage_result):
        calls = {"measure_one": 0, "run_stage": 0, "run_stage_device": 0}

        def fake_run_stage(cfg, timeout_s=480, force_cpu=False):
            calls["run_stage"] += 1
            if not force_cpu:
                # a stage child that would touch the default (possibly
                # wedged) backend — the 480s-discovery path
                calls["run_stage_device"] += 1
            return stage_result if not force_cpu else _fake_row()

        def fake_measure_one(cfg, force_cpu=False):
            calls["measure_one"] += 1
            assert force_cpu
            return _fake_row()

        monkeypatch.setattr(bench_mod, "_lock_or_warn", lambda *a, **k: None)
        monkeypatch.setattr(bench_mod, "_load_doctor",
                            lambda: _FakeDoctor(probe))
        monkeypatch.setattr(bench_mod, "run_stage", fake_run_stage)
        monkeypatch.setattr(bench_mod, "measure_one", fake_measure_one)
        monkeypatch.setattr(bench_mod, "measure_reference_style_baseline",
                            lambda budget_s=6.0: 100.0)
        bench_mod.main()
        out = capsys.readouterr().out
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        return json.loads(line), calls

    def test_probe_ok_measures_and_fills_mfu(self, bench_mod, monkeypatch,
                                             capsys):
        """Acceptance: non-null mfu_headline tagged cpu_calibrated, the
        typed probe verdict in extras, no fallback prose in the unit."""
        probe = {"status": "ok", "platform": "cpu", "n_devices": 8,
                 "elapsed_s": 2.0, "timeout_s": 20.0}
        row, calls = self._run_main(bench_mod, monkeypatch, capsys, probe,
                                    _fake_row())
        assert row["extras"]["mfu_headline"] == 2.5e-05
        assert row["extras"]["mfu_basis"] == "cpu_calibrated"
        assert row["extras"]["device_probe"]["status"] == "ok"
        assert row["extras"]["device_probe"]["cpu_fallback"] is False
        assert row["extras"]["phases_headline"]["device"]["mfu"] > 0
        assert "TPU-PATH-FAILED" not in row["unit"]
        assert row["platform"] == "cpu"
        assert calls["measure_one"] == 0

    def test_stage_drivers_share_the_probe_decision(self, bench_mod,
                                                    monkeypatch):
        """--regress/--stage-ab/--obs-ab go through _probe_or_force_cpu:
        a failed probe forces the cpu fallback up front (one probe
        timeout, not a full stage timeout per repeat) and an explicit
        --cpu skips the probe entirely."""
        calls = {"probe": 0}

        class CountingDoctor(_FakeDoctor):
            def check_device(self, timeout_s=20.0, platform=None):
                calls["probe"] += 1
                return dict(self.verdict)

        bad = CountingDoctor({"status": "failed", "reason": "init-hang",
                              "elapsed_s": 20.0, "timeout_s": 20.0})
        monkeypatch.setattr(bench_mod, "_load_doctor", lambda: bad)
        assert bench_mod._probe_or_force_cpu(False) is True
        assert calls["probe"] == 1
        # explicit --cpu: no probe spent
        assert bench_mod._probe_or_force_cpu(True) is True
        assert calls["probe"] == 1
        ok = CountingDoctor({"status": "ok", "platform": "cpu",
                             "n_devices": 8, "elapsed_s": 2.0,
                             "timeout_s": 20.0})
        monkeypatch.setattr(bench_mod, "_load_doctor", lambda: ok)
        assert bench_mod._probe_or_force_cpu(False) is False

    def test_probe_failure_skips_the_480s_discovery(self, bench_mod,
                                                    monkeypatch, capsys):
        """A failed probe goes STRAIGHT to the cpu fallback — zero stage
        children launched, the reason code recorded in the artifact."""
        probe = {"status": "failed", "reason": "init-hang",
                 "elapsed_s": 20.0, "timeout_s": 20.0}
        row, calls = self._run_main(bench_mod, monkeypatch, capsys, probe,
                                    None)
        # zero stage children on the possibly-wedged default backend (the
        # cpu-relative extras stages run force_cpu and are safe)
        assert calls["run_stage_device"] == 0
        assert calls["measure_one"] == 1
        assert row["extras"]["device_probe"]["reason"] == "init-hang"
        assert row["extras"]["device_probe"]["cpu_fallback"] is True
        assert row["extras"]["mfu_headline"] is not None


class TestBenchScratchHygiene:
    def test_stale_dirs_and_legacy_buffers_swept(self, bench_mod,
                                                 monkeypatch, tmp_path):
        """Satellite 2: scratch from CRASHED prior runs (per-pid workdirs
        with dead owners, legacy flat bench_stderr_/bench_hb_ files) is
        swept; the live process's scratch survives."""
        import tempfile as _tempfile

        monkeypatch.setattr(_tempfile, "gettempdir", lambda: str(tmp_path))
        root = tmp_path / "estorch_bench"
        monkeypatch.setattr(bench_mod, "_BENCH_TMP_ROOT", str(root))
        dead = subprocess.Popen(["sleep", "0"])
        dead.wait()
        os.makedirs(root / str(dead.pid))
        (root / str(dead.pid) / "fallback_stderr.log").write_text("boom")
        os.makedirs(root / str(os.getpid()))
        (tmp_path / f"bench_stderr_{dead.pid}.log").write_text("old")
        (tmp_path / f"bench_hb_{dead.pid}_123.json").write_text("{}")
        (tmp_path / f"bench_stderr_{os.getpid()}.log").write_text("live")
        bench_mod._sweep_stale_bench_dirs()
        assert not (root / str(dead.pid)).exists()
        assert (root / str(os.getpid())).exists()
        assert not (tmp_path / f"bench_stderr_{dead.pid}.log").exists()
        assert not (tmp_path / f"bench_hb_{dead.pid}_123.json").exists()
        assert (tmp_path / f"bench_stderr_{os.getpid()}.log").exists()

    def test_workdir_created_and_cleaned(self, bench_mod, monkeypatch,
                                         tmp_path):
        monkeypatch.setattr(bench_mod, "_BENCH_TMP_ROOT",
                            str(tmp_path / "estorch_bench"))
        d = bench_mod._bench_workdir()
        assert os.path.isdir(d)
        assert os.path.basename(d) == str(os.getpid())
        bench_mod._cleanup_bench_workdir()
        assert not os.path.isdir(d)
