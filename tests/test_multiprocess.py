"""REAL multi-process distributed validation (SURVEY.md §2 item 7).

The reference scales by ``mpirun`` process groups with torch.distributed
CPU collectives (upstream ``estorch/estorch.py`` per SURVEY.md).  Our
equivalent is JAX's multi-process runtime; until now it was only exercised
through the single-process fallback (round-1 VERDICT "What's weak" #4).
This test launches TWO actual OS processes, each a JAX process with 4
local CPU devices, connected by ``jax.distributed`` over Gloo/TCP, and
trains the SAME ES program the single-host engine compiles — the
collectives (fitness all_gather + update psum) genuinely cross the process
boundary, which is the DCN-analog layering of a TPU pod.

Claims pinned here:
- distributed init succeeds with explicit coordinator/nproc/pid args;
- the population mesh spans all processes' devices (8 global);
- training runs end-to-end and the final parameters are BIT-IDENTICAL
  across processes (the broadcast-free SPMD synchronization property —
  divergence would mean the processes silently trained apart);
- the cross-process result matches the single-process 8-device run to
  float32 reduction tolerance (the Gloo allreduce may order the sum
  differently than the in-process psum, so exact bitwise equality across
  TOPOLOGIES is not claimed — measured Δchecksum ≈ 2e-8 relative);
- ``leader_only`` elects exactly one writer.
"""

import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

WORKER = Path(__file__).with_name("_mp_worker.py")
REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_two(tmp_path, algo="es"):
    port = _free_port()
    env = {"PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin"}
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port),
             str(tmp_path), algo],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker hung (>420s)")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"


@pytest.mark.slow
def test_two_process_training_bit_synchronized(tmp_path):
    _launch_two(tmp_path, algo="es")

    r0 = np.load(tmp_path / "proc0.npz")
    r1 = np.load(tmp_path / "proc1.npz")

    # SPMD synchronization: both processes hold the SAME trained state,
    # with no parameter broadcast anywhere in the program
    np.testing.assert_array_equal(r0["params"], r1["params"])
    assert r0["best"] == r1["best"]

    # exactly one leader writer
    assert bool(r0["is_leader_writer"]) and not bool(r1["is_leader_writer"])

    # cross-topology agreement: same program on 1 process x 8 devices.
    # In-process import is safe: conftest pins the CPU platform with 8
    # virtual devices for the whole test session.
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import CartPole

    es = ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=16,
        sigma=0.1,
        policy_kwargs={"action_dim": 2, "hidden": (8,), "discrete": True},
        agent_kwargs={"env": CartPole(), "horizon": 64},
        optimizer_kwargs={"learning_rate": 1e-2},
        seed=7,
    )
    es.train(2, verbose=False)
    single = np.asarray(es.state.params_flat, np.float64)
    np.testing.assert_allclose(r0["params"], single, rtol=0, atol=5e-6)


@pytest.mark.slow
def test_two_process_novelty_family_host_state_synchronized(tmp_path):
    """NSR-ES across two real processes: the archive, meta-centers, and
    meta-selection sequence live HOST-side on every process, derived from
    replicated device results plus the seeded RNG — they must come out
    bit-identical with zero inter-process communication (the design claim
    in parallel/multihost.py)."""
    _launch_two(tmp_path, algo="nsr")
    r0 = np.load(tmp_path / "proc0.npz")
    r1 = np.load(tmp_path / "proc1.npz")
    np.testing.assert_array_equal(r0["params"], r1["params"])
    np.testing.assert_array_equal(r0["archive"], r1["archive"])
    np.testing.assert_array_equal(r0["meta_sums"], r1["meta_sums"])
    np.testing.assert_array_equal(r0["meta_indices"], r1["meta_indices"])
