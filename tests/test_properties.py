"""Property-based tests (hypothesis) for the ES math core."""

import numpy as np
import pytest

pytest.importorskip(
    "hypothesis",
    reason="hypothesis not installed in this image; property tests skip")
from hypothesis import given, settings, strategies as st  # noqa: E402
from hypothesis.extra import numpy as hnp  # noqa: E402

import jax.numpy as jnp

from estorch_tpu.algo.archive import NoveltyArchive
from estorch_tpu.ops import centered_rank, centered_rank_np, fold_mirrored_weights
from estorch_tpu.utils.fault import mask_and_renormalize

# no subnormals: XLA flushes them to zero, so device/numpy ranks legitimately
# diverge for subnormal-magnitude differences (documented in ops/ranks.py)
_floats = st.floats(-1e6, 1e6, allow_nan=False, allow_subnormal=False, width=32)


class TestCenteredRankProperties:
    @given(hnp.arrays(np.float32, st.integers(2, 64), elements=_floats, unique=True))
    @settings(max_examples=30, deadline=None)
    def test_device_matches_numpy_twin(self, x):
        np.testing.assert_allclose(
            np.asarray(centered_rank(jnp.asarray(x))), centered_rank_np(x),
            atol=1e-7,
        )

    @given(hnp.arrays(np.float32, st.integers(2, 64), elements=_floats, unique=True))
    @settings(max_examples=30, deadline=None)
    def test_bounded_and_zero_sum(self, x):
        r = centered_rank_np(x)
        assert r.min() >= -0.5 - 1e-6 and r.max() <= 0.5 + 1e-6
        assert abs(float(r.sum())) < 1e-4

    @given(
        hnp.arrays(np.float32, st.integers(2, 32), elements=_floats, unique=True),
        st.randoms(use_true_random=False),
    )
    @settings(max_examples=25, deadline=None)
    def test_permutation_equivariance(self, x, rnd):
        perm = np.arange(len(x))
        rnd.shuffle(perm)
        np.testing.assert_allclose(
            centered_rank_np(x[perm]), centered_rank_np(x)[perm], atol=1e-7
        )

    @given(
        hnp.arrays(np.float32, st.integers(2, 32), elements=_floats, unique=True),
        st.floats(1e-3, 1e3),
        st.floats(-100, 100),
    )
    @settings(max_examples=25, deadline=None)
    def test_affine_invariance(self, x, a, b):
        y = (a * x + b).astype(np.float32)
        if len(np.unique(y)) == len(y):  # affine map kept values distinct
            np.testing.assert_allclose(
                centered_rank_np(y), centered_rank_np(x), atol=1e-7
            )


class TestFoldProperties:
    @given(hnp.arrays(np.float32, st.integers(1, 32).map(lambda k: 2 * k),
                      elements=st.floats(-10, 10, allow_nan=False, allow_subnormal=False, width=32)))
    @settings(max_examples=30, deadline=None)
    def test_fold_is_signed_pair_sum(self, w):
        folded = np.asarray(fold_mirrored_weights(jnp.asarray(w)))
        expected = w[0::2] - w[1::2]
        np.testing.assert_allclose(folded, expected, atol=1e-6)


class TestArchiveProperties:
    @given(
        hnp.arrays(np.float32, st.tuples(st.integers(1, 12), st.just(3)),
                   elements=st.floats(-5, 5, allow_nan=False,
                                      allow_subnormal=False, width=32)),
        hnp.arrays(np.float32, st.tuples(st.integers(1, 6), st.just(3)),
                   elements=st.floats(-5, 5, allow_nan=False,
                                      allow_subnormal=False, width=32)),
    )
    @settings(max_examples=25, deadline=None)
    def test_novelty_nonnegative_and_self_zero_with_k1(self, bcs, queries):
        ar = NoveltyArchive(k=1)
        for row in bcs:
            ar.add(row)
        nov = ar.novelty(queries)
        assert np.all(nov >= 0)
        # a query that IS an archive point has k=1 novelty 0
        nov_self = ar.novelty(bcs[0])
        assert float(nov_self) < 1e-5

    @given(st.integers(1, 10))
    @settings(max_examples=10, deadline=None)
    def test_novelty_monotone_in_distance(self, scale):
        ar = NoveltyArchive(k=2)
        ar.add(np.zeros(2))
        ar.add(np.ones(2))
        near = ar.novelty(np.full(2, 0.1, np.float32))
        far = ar.novelty(np.full(2, 0.1 + scale, np.float32))
        assert far > near


class TestFaultProperties:
    @given(
        hnp.arrays(np.float32, st.integers(3, 32),
                   elements=st.floats(-10, 10, allow_nan=False,
                                      allow_subnormal=False, width=32)),
        st.data(),
    )
    @settings(max_examples=25, deadline=None)
    def test_renormalized_mean_contribution_preserved(self, w, data):
        n = len(w)
        # at least 2 survivors
        valid = np.array(
            data.draw(st.lists(st.booleans(), min_size=n, max_size=n)), bool
        )
        if valid.sum() < 2:
            valid[:2] = True
        out = mask_and_renormalize(w, valid)
        # invalid entries zeroed; survivors scaled by n/n_valid
        assert np.all(out[~valid] == 0.0)
        np.testing.assert_allclose(
            out[valid], w[valid] * (n / valid.sum()), rtol=1e-5
        )