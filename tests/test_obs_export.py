"""Export layer (estorch_tpu/obs/export/): Prometheus exposition +
metrics sidecar, Perfetto trace-event export, the `obs regress` perf
gate, atomic flight-recorder dumps — and THE e2e acceptance demo: a
supervised training run killed mid-flight stays scrapeable from the
sidecar throughout, with counter totals monotone across the restart.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from estorch_tpu.obs import FlightRecorder, Heartbeat, read_heartbeat
from estorch_tpu.obs.__main__ import main as obs_main
from estorch_tpu.obs.export.prometheus import (is_gauge, metric_name,
                                               parse_exposition,
                                               render_exposition,
                                               samples_by_name)
from estorch_tpu.obs.export.regress import (compare, compare_files,
                                            load_measurement)
from estorch_tpu.obs.export.regress import selfcheck as regress_selfcheck
from estorch_tpu.obs.export.sidecar import (MetricsSidecar, compose_totals,
                                            publish_counters,
                                            read_published_counters)
from estorch_tpu.obs.export.traceevent import (export_trace, validate_trace,
                                               write_trace)

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------

class TestPrometheus:
    def test_render_parse_round_trip(self):
        body = render_exposition(
            {"env_steps": 1234, "recompiles": 3, "peak_rss_mb": 512.5},
            {"ts": time.time(), "age_s": 1.0, "pid": 42,
             "phase": "eval", "generation": 7},
        )
        vals = samples_by_name(parse_exposition(body))
        assert vals["estorch_env_steps"] == 1234
        assert vals["estorch_recompiles"] == 3
        assert vals["estorch_peak_rss_mb"] == 512.5
        assert vals["estorch_heartbeat_generation"] == 7
        assert vals["estorch_heartbeat_stale"] == 0
        assert vals["estorch_up"] == 1

    def test_counter_vs_gauge_classification(self):
        assert not is_gauge("env_steps")
        assert not is_gauge("requests_total")
        assert is_gauge("peak_rss_mb")
        assert is_gauge("compile_time_s")
        assert is_gauge("queue_depth")
        assert is_gauge("batch_size_last")
        body = render_exposition({"env_steps": 1, "queue_depth": 2})
        assert "# TYPE estorch_env_steps counter" in body
        assert "# TYPE estorch_queue_depth gauge" in body

    def test_stale_heartbeat_reads_down(self):
        body = render_exposition(
            {}, {"ts": 0.0, "age_s": 9999.0, "pid": 1, "phase": "device",
                 "generation": 3},
            stale_after_s=120.0)
        vals = samples_by_name(parse_exposition(body))
        assert vals["estorch_heartbeat_stale"] == 1
        assert vals["estorch_up"] == 0

    def test_no_heartbeat_up_override(self):
        """The serve server IS the scraped process: up=True without any
        heartbeat file; a run-dir sidecar with no heartbeat reads down."""
        assert samples_by_name(parse_exposition(
            render_exposition({}, None)))["estorch_up"] == 0
        assert samples_by_name(parse_exposition(
            render_exposition({}, None, up=True)))["estorch_up"] == 1

    def test_name_sanitization_and_label_escape(self):
        assert metric_name("serve.requests-total") == \
            "estorch_serve_requests_total"
        body = render_exposition(
            {}, {"ts": 0.0, "age_s": 0.0, "pid": 9,
                 "phase": 'ev"al\nx\\y', "generation": 0})
        samples = parse_exposition(body)
        labels = [lab for name, lab, _ in samples
                  if name == "estorch_heartbeat_info"][0]
        assert labels["pid"] == "9"

    def test_non_numeric_registry_values_skipped(self):
        body = render_exposition({"env_steps": 5, "note": "hello",
                                  "flag": True})
        vals = samples_by_name(parse_exposition(body))
        assert vals["estorch_env_steps"] == 5
        assert "estorch_note" not in vals
        assert "estorch_flag" not in vals

    def test_extra_gauge_shadows_registry_entry(self):
        """The serve server's live queue-depth read and the batcher's
        registry gauge share a name — the point-in-time extra must
        SHADOW the registry entry, not duplicate its TYPE (a duplicate
        is exactly what the validating parser rejects)."""
        body = render_exposition({"queue_depth": 7, "env_steps": 1},
                                 extra_gauges={"queue_depth": 3})
        vals = samples_by_name(parse_exposition(body))  # parses: no dup
        assert vals["estorch_queue_depth"] == 3  # the fresher read wins
        assert vals["estorch_env_steps"] == 1

    def test_parser_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_exposition("this is not an exposition line\n")
        with pytest.raises(ValueError):
            parse_exposition("estorch_x notanumber\n")
        with pytest.raises(ValueError):
            parse_exposition("# TYPE estorch_x counter\n"
                             "# TYPE estorch_x gauge\n")
        # garbage INSIDE a label block must not be blessed just because
        # one well-formed pair is also present — a real scraper rejects
        # the whole scrape
        with pytest.raises(ValueError):
            parse_exposition('estorch_x{phase="eval" JUNK==,} 1\n')


# ---------------------------------------------------------------------
# sidecar: publish/compose + live loopback scrape
# ---------------------------------------------------------------------

class TestSidecarComposition:
    def test_publish_read_round_trip(self, tmp_path):
        d = str(tmp_path)
        publish_counters(d, {"env_steps": 100, "note": "skip-me"},
                         through_ts=123.0, extra={"restart_count": 2})
        back = read_published_counters(d)
        assert back["counters"] == {"env_steps": 100}
        assert back["through_ts"] == 123.0
        assert back["restart_count"] == 2
        assert not os.path.exists(os.path.join(d, "counters.json.tmp"))

    def test_corrupt_or_missing_published_is_none(self, tmp_path):
        assert read_published_counters(str(tmp_path)) is None
        (tmp_path / "counters.json").write_text("{half")
        assert read_published_counters(str(tmp_path)) is None
        (tmp_path / "counters.json").write_text(
            json.dumps({"schema": 999, "counters": {}}))
        assert read_published_counters(str(tmp_path)) is None

    def test_compose_skips_already_folded_beat(self):
        """The cross-restart double-count guard: a dead child's final
        beat (ts == through_ts) is already inside the published totals —
        only a NEWER beat (the next child) adds on top."""
        published = {"through_ts": 100.0, "counters": {"env_steps": 50}}
        dead = {"ts": 100.0, "counters": {"env_steps": 50}}
        live = {"ts": 101.0, "counters": {"env_steps": 7}}
        assert compose_totals(published, dead) == {"env_steps": 50}
        assert compose_totals(published, live) == {"env_steps": 57}
        assert compose_totals(None, live) == {"env_steps": 7}
        assert compose_totals(published, None) == {"env_steps": 50}

    def test_loopback_scrape_and_health(self, tmp_path):
        d = str(tmp_path)
        Heartbeat(os.path.join(d, "heartbeat.json")).beat(
            "eval", 3, {"env_steps": 11})
        publish_counters(d, {"env_steps": 31}, through_ts=1.0,
                         extra={"restart_count": 1})
        sc = MetricsSidecar(d, port=0)
        sc.start_background()
        try:
            with urllib.request.urlopen(
                    f"http://{sc.host}:{sc.port}/metrics", timeout=10) as r:
                assert r.status == 200
                assert "text/plain" in r.headers["Content-Type"]
                vals = samples_by_name(
                    parse_exposition(r.read().decode()))
            assert vals["estorch_env_steps"] == 42  # 31 published + 11 live
            assert vals["estorch_supervisor_restarts"] == 1
            assert vals["estorch_up"] == 1
            assert "estorch_run_completed" not in vals  # still running
            with urllib.request.urlopen(
                    f"http://{sc.host}:{sc.port}/healthz", timeout=10) as r:
                h = json.load(r)
            assert h["ok"] and h["generation"] == 3
        finally:
            sc.close()

    def test_completed_verdict_distinguishes_done_from_dead(self,
                                                            tmp_path):
        """After a run ends its heartbeat goes stale and estorch_up
        drops either way — the published completion verdict is what
        tells an alert 'done' from 'dead'."""
        d = str(tmp_path)
        publish_counters(d, {"env_steps": 9}, through_ts=1.0,
                         extra={"restart_count": 0, "completed": True})
        sc = MetricsSidecar(d, port=0)
        vals = samples_by_name(parse_exposition(sc.scrape()))
        sc.close()
        assert vals["estorch_up"] == 0  # no fresh heartbeat
        assert vals["estorch_run_completed"] == 1

    def test_health_503_without_heartbeat(self, tmp_path):
        sc = MetricsSidecar(str(tmp_path), port=0)
        sc.start_background()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://{sc.host}:{sc.port}/healthz", timeout=10)
            assert ei.value.code == 503
            # /metrics still answers — the sidecar outlives the run
            with urllib.request.urlopen(
                    f"http://{sc.host}:{sc.port}/metrics", timeout=10) as r:
                vals = samples_by_name(parse_exposition(r.read().decode()))
            assert vals["estorch_up"] == 0
        finally:
            sc.close()

    def test_file_run_never_imports_package_or_jax(self, tmp_path):
        """The wedged-host contract: the sidecar must serve a scrape when
        run AS A FILE, without the estorch_tpu package init (and hence
        without jax) ever loading — same discipline as bench.py."""
        Heartbeat(str(tmp_path / "heartbeat.json")).beat("eval", 1, {})
        src = os.path.join(REPO, "estorch_tpu", "obs", "export",
                           "sidecar.py")
        probe = (
            "import json, sys, threading, urllib.request\n"
            "import importlib.util\n"
            f"spec = importlib.util.spec_from_file_location('sc', {src!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "assert 'jax' not in sys.modules, 'sidecar imported jax'\n"
            "assert 'estorch_tpu' not in sys.modules, 'package init ran'\n"
            f"sc = m.MetricsSidecar({str(tmp_path)!r}, port=0)\n"
            "sc.start_background()\n"
            "url = f'http://{sc.host}:{sc.port}/metrics'\n"
            "body = urllib.request.urlopen(url, timeout=10).read().decode()\n"
            "assert 'estorch_up 1' in body, body\n"
            "sc.close()\n"
        )
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr


# ---------------------------------------------------------------------
# flight recorder: atomic dump (satellite)
# ---------------------------------------------------------------------

class TestAtomicDump:
    def test_dump_appends_and_leaves_no_tmp(self, tmp_path):
        path = str(tmp_path / "ring.jsonl")
        r = FlightRecorder(capacity=4)
        r.add("event", "first")
        r.dump_jsonl(path)
        r2 = FlightRecorder(capacity=4)
        r2.add("event", "second")
        r2.dump_jsonl(path)
        names = [json.loads(ln)["name"] for ln in open(path)]
        assert names == ["first", "second"]
        assert not os.path.exists(path + ".tmp")

    def test_dump_drops_truncated_tail(self, tmp_path):
        """A pre-existing truncated file (crash during a non-atomic-era
        dump, or a torn copy) loses only the partial line: keeping it
        would either glue the new first event onto it or park malformed
        JSON mid-file, where tolerant readers rightly raise."""
        from estorch_tpu.obs.summarize import load_records_tolerant

        path = str(tmp_path / "ring.jsonl")
        with open(path, "w") as f:
            f.write('{"kind": "event", "name": "old"}\n{"kind": "ev')
        r = FlightRecorder(capacity=4)
        r.add("event", "new")
        r.dump_jsonl(path)
        rows = [json.loads(ln) for ln in open(path)]  # every line parses
        assert [row["name"] for row in rows] == ["old", "new"]
        records, dropped = load_records_tolerant(path)
        assert dropped == 0 and len(records) == 2


# ---------------------------------------------------------------------
# trace-event export
# ---------------------------------------------------------------------

def _run_records(gens, rate=1000.0, phases=None):
    recs = []
    for g in gens:
        rec = {"generation": g, "wall_time_s": 1.0, "env_steps": 1000,
               "env_steps_per_sec": rate, "reward_mean": 0.0,
               "reward_max": 0.0, "best_reward": 0.0, "n_failed": 0}
        if phases is not None:
            rec["phases"] = dict(phases)
        recs.append(rec)
    return recs


class TestTraceEvent:
    def test_single_run_lanes_and_nesting(self):
        recs = _run_records(range(3), phases={
            "eval": 0.6, "eval/sample": 0.2, "update": 0.3})
        trace = export_trace(recs)
        assert validate_trace(trace) == []
        evs = trace["traceEvents"]
        gens = [e for e in evs if e.get("cat") == "generation"]
        assert [e["name"] for e in gens] == ["gen 0", "gen 1", "gen 2"]
        # generations laid end to end on the synthesized clock
        assert [e["ts"] for e in gens] == [0.0, 1e6, 2e6]
        child = [e for e in evs if e["name"] == "eval/sample"][0]
        parent = [e for e in evs if e["name"] == "eval"][0]
        assert parent["ts"] <= child["ts"]
        assert child["dur"] <= parent["dur"]
        assert trace["otherData"]["segments"] == 1
        assert trace["otherData"]["restart_markers"] == 0

    def test_restart_becomes_segment_and_marker(self):
        """A supervised run whose child died at gen 5 and resumed from
        the gen-3 checkpoint replays gens 4..: the exporter must split
        lanes at the replay boundary and mark the restart with the
        manifest's provenance."""
        recs = _run_records(range(5)) + _run_records(range(4, 8))
        manifest = {"pid": 111, "resilience": {"restarts": [
            {"reason": "child died with exit code -9",
             "heartbeat": {"pid": 222, "generation": 4}},
        ]}}
        trace = export_trace(recs, manifest=manifest)
        assert validate_trace(trace) == []
        markers = [e for e in trace["traceEvents"]
                   if e["name"] == "supervisor restart"]
        assert len(markers) == 1
        assert "exit code -9" in markers[0]["args"]["reason"]
        assert trace["otherData"]["segments"] == 2
        # the dead child's lane is keyed by its heartbeat pid
        names = {e["args"]["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert any("pid 222" in n for n in names)

    def test_flight_recorder_events_get_wall_clock_lane(self):
        recs = _run_records(range(2))
        events = [{"ts": 1000.0, "kind": "event", "name": "compile"},
                  {"ts": 1001.5, "kind": "note", "name": "init"}]
        hb = {"ts": 1002.0, "pid": 1, "phase": "eval", "generation": 1}
        trace = export_trace(recs, events=events, heartbeat=hb)
        assert validate_trace(trace) == []
        wall = [e for e in trace["traceEvents"] if e.get("pid") == 0
                and e.get("ph") == "i"]
        assert [e["ts"] for e in wall] == [0.0, 1.5e6, 2e6]  # rebased
        assert wall[-1]["name"] == "last heartbeat"

    def test_heartbeat_without_numeric_ts_does_not_crash(self):
        """A hand-edited or foreign heartbeat (ts missing or a string)
        cannot be placed on the wall-clock lane — the export must skip
        it, not die on min() of an empty sequence."""
        recs = _run_records(range(2))
        for hb in ({"phase": "eval", "pid": 1},
                   {"ts": "not-a-number", "phase": "eval", "pid": 1}):
            trace = export_trace(recs, heartbeat=hb)
            assert validate_trace(trace) == []
            assert not [e for e in trace["traceEvents"]
                        if e.get("pid") == 0]  # no wall-clock lane

    def test_records_without_phases_still_render(self):
        trace = export_trace(_run_records(range(3)))
        assert validate_trace(trace) == []
        assert len([e for e in trace["traceEvents"]
                    if e.get("cat") == "generation"]) == 3
        assert not [e for e in trace["traceEvents"]
                    if e.get("cat") == "phase"]

    def test_validator_catches_malformed_events(self):
        assert validate_trace([]) != []
        assert validate_trace({"traceEvents": None}) != []
        bad = {"traceEvents": [
            {"ph": "Z", "name": "x", "pid": 1, "tid": 1, "ts": 0},
            {"ph": "X", "name": "", "pid": 1, "tid": 1, "ts": 0, "dur": 1},
            {"ph": "X", "name": "ok", "pid": 1, "tid": 1, "ts": -5,
             "dur": 1},
            {"ph": "X", "name": "ok", "pid": 1, "tid": 1, "ts": 0},
        ]}
        problems = validate_trace(bad)
        assert len(problems) == 4

    def test_write_trace_is_atomic(self, tmp_path):
        path = str(tmp_path / "trace.json")
        write_trace(export_trace(_run_records(range(2))), path)
        assert validate_trace(json.load(open(path))) == []
        assert not os.path.exists(path + ".tmp")


# ---------------------------------------------------------------------
# degenerate inputs: summarize + trace CLIs (satellite)
# ---------------------------------------------------------------------

class TestDegenerateInputs:
    def test_empty_jsonl(self, tmp_path, capsys):
        path = tmp_path / "run.jsonl"
        path.write_text("")
        assert obs_main(["summarize", str(path)]) == 0
        assert obs_main(["trace", str(path),
                         "-o", str(tmp_path / "t.json")]) == 0
        capsys.readouterr()
        trace = json.load(open(tmp_path / "t.json"))
        assert validate_trace(trace) == []
        assert trace["otherData"]["generations"] == 0

    def test_truncated_final_line_dropped_with_note(self, tmp_path,
                                                    capsys):
        """A SIGKILLed writer legitimately leaves a partial last line —
        the post-mortem tools exist for exactly those runs."""
        path = tmp_path / "run.jsonl"
        with open(path, "w") as f:
            for rec in _run_records(range(3)):
                f.write(json.dumps(rec) + "\n")
            f.write('{"generation": 3, "env_ste')
        assert obs_main(["summarize", str(path), "--json"]) == 0
        out = capsys.readouterr()
        assert json.loads(out.out)["generations"] == 3
        assert "truncated final line" in out.err
        assert obs_main(["trace", str(path),
                         "-o", str(tmp_path / "t.json")]) == 0
        capsys.readouterr()
        assert json.load(open(
            tmp_path / "t.json"))["otherData"]["generations"] == 3

    def test_garbage_mid_file_still_raises(self, tmp_path, capsys):
        path = tmp_path / "run.jsonl"
        with open(path, "w") as f:
            f.write('{"generation": 0}\nGARBAGE\n{"generation": 1}\n')
        assert obs_main(["summarize", str(path)]) == 1
        assert obs_main(["trace", str(path)]) == 1
        assert "cannot read" in capsys.readouterr().err

    def test_wrong_file_with_one_malformed_line_is_error(self, tmp_path,
                                                         capsys):
        """A torn tail is tolerated only BEHIND valid records: pointing
        the tools at the wrong file (one malformed line, zero records)
        must error, not exit 0 with an empty result."""
        path = tmp_path / "notes.txt"
        path.write_text("this is not a run JSONL\n")
        assert obs_main(["summarize", str(path)]) == 1
        assert obs_main(["trace", str(path)]) == 1
        assert "cannot read" in capsys.readouterr().err

    def test_records_missing_phases(self, tmp_path, capsys):
        path = tmp_path / "run.jsonl"
        with open(path, "w") as f:
            for rec in _run_records(range(4)):
                f.write(json.dumps(rec) + "\n")
        assert obs_main(["summarize", str(path)]) == 0
        assert obs_main(["trace", str(path),
                         "-o", str(tmp_path / "t.json")]) == 0
        capsys.readouterr()

    def test_heartbeat_only_run_dir(self, tmp_path, capsys):
        """A run that wedged before logging a single generation still has
        a story: its heartbeat."""
        hb = tmp_path / "heartbeat.json"
        Heartbeat(str(hb)).beat("device", 2, {"env_steps": 5})
        assert obs_main(["summarize", "--heartbeat", str(hb)]) == 0
        assert "device" in capsys.readouterr().out


# ---------------------------------------------------------------------
# obs regress
# ---------------------------------------------------------------------

class TestRegress:
    def test_selfcheck_clean(self):
        assert regress_selfcheck() == []

    def test_verdict_math(self):
        base = [100.0] * 12
        assert compare([100.0] * 12, base)["verdict"] == "pass"
        slow = compare([60.0] * 12, base)
        assert slow["verdict"] == "regress" and slow["drop_pct"] == 40.0
        fast = compare([140.0] * 12, base)
        assert fast["verdict"] == "pass" and fast["improved"]

    def test_noisy_sample_widens_band(self):
        """A sample whose own scatter exceeds the floor must not flag a
        same-distribution rerun: the band is learned, not assumed."""
        base = [100.0, 80.0, 120.0, 95.0, 105.0, 70.0, 130.0, 100.0]
        shifted = [x * 0.85 for x in base]  # well inside the ~22% MAD band
        v = compare(shifted, base)
        assert v["band_pct"] > 15.0
        assert v["verdict"] == "pass"

    def test_load_measurement_shapes(self, tmp_path):
        bench_path = tmp_path / "BENCH_x.json"
        bench_path.write_text(json.dumps(
            {"parsed": {"metric": "env_steps_per_sec_per_chip",
                        "value": 123.0}}))
        samples, metric = load_measurement(str(bench_path))
        assert samples == [123.0]
        assert metric == "env_steps_per_sec_per_chip"
        ab_path = tmp_path / "ab.jsonl"
        with open(ab_path, "w") as f:
            for lab, rate in (("on", 10.0), ("off", 20.0), ("on", 12.0)):
                f.write(json.dumps({"label": lab, "rate": rate}) + "\n")
        samples, _ = load_measurement(str(ab_path), label="on")
        assert samples == [10.0, 12.0]

    def test_cli_exit_codes_and_verdict_json(self, tmp_path, capsys):
        base = tmp_path / "BENCH_base.json"
        base.write_text(json.dumps({"parsed": {
            "metric": "env_steps_per_sec", "value": 1000.0}}))
        run = tmp_path / "run.jsonl"
        with open(run, "w") as f:
            for rec in _run_records(range(8), rate=990.0):
                f.write(json.dumps(rec) + "\n")
        assert obs_main(["regress", str(run), "--baseline", str(base),
                         "--json"]) == 0
        assert json.loads(capsys.readouterr().out)["verdict"] == "pass"
        slow = tmp_path / "slow.jsonl"
        with open(slow, "w") as f:
            for rec in _run_records(range(8), rate=600.0):
                f.write(json.dumps(rec) + "\n")
        assert obs_main(["regress", str(slow), "--baseline", str(base),
                         "--json"]) == 1
        v = json.loads(capsys.readouterr().out)
        assert v["verdict"] == "regress" and v["drop_pct"] == 40.0

    def test_cli_unusable_input_is_error(self, tmp_path, capsys):
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        base = tmp_path / "b.json"
        base.write_text(json.dumps({"parsed": {"metric": "m",
                                               "value": 1.0}}))
        assert obs_main(["regress", str(empty), "--baseline",
                         str(base)]) == 1
        assert "regress:" in capsys.readouterr().err


class TestMixedSchemaBaselines:
    """BENCH_r06+ artifacts embed phase_rows/tail_rows; older baselines
    predate them.  The gates must consume the new schema and DEGRADE
    with a one-line diagnosis — never a traceback or a bogus verdict —
    on the old one."""

    def _run_jsonl(self, path, n=8):
        with open(path, "w") as f:
            for g in range(n):
                f.write(json.dumps({
                    "generation": g, "env_steps_per_sec": 1000.0,
                    "wall_time_s": 0.10,
                    "phases": {"eval": 0.08, "update": 0.02}}) + "\n")

    def _r06(self, path, eval_s=0.08):
        with open(path, "w") as f:
            json.dump({
                "n": 3, "platform": "cpu",
                "parsed": {"metric": "env_steps_per_sec_per_chip",
                           "value": 1000.0, "unit": "x (cpu)"},
                "phase_rows": [
                    {"generation": g, "env_steps_per_sec": 1000.0,
                     "wall_time_s": eval_s + 0.02,
                     "phases": {"eval": eval_s, "update": 0.02}}
                    for g in range(8)],
            }, f)

    def test_r06_schema_feeds_phase_and_tail_gates(self, tmp_path):
        from estorch_tpu.obs.export.regress import (compare_phase_files,
                                                    compare_tail_files)

        cur = str(tmp_path / "cur.jsonl")
        self._run_jsonl(cur)
        base = str(tmp_path / "BENCH_r06.json")
        self._r06(base)
        v = compare_phase_files(cur, base)
        assert v["verdict"] == "pass"
        assert set(v["phases"]) == {"eval", "update"}
        t = compare_tail_files(cur, base)
        assert t["verdict"] == "pass"
        assert "eval" in t["groups"] and "wall_time_s" in t["groups"]

    def test_r06_baseline_catches_phase_slowdown(self, tmp_path):
        from estorch_tpu.obs.export.regress import compare_phase_files

        cur = str(tmp_path / "cur.jsonl")
        self._run_jsonl(cur)
        base = str(tmp_path / "BENCH_r06.json")
        self._r06(base, eval_s=0.05)  # baseline 37% faster at eval
        v = compare_phase_files(cur, base)
        assert v["verdict"] == "regress"
        assert v["regressed_phases"] == ["eval"]

    def test_pre_r06_baseline_degrades_one_line(self, tmp_path, capsys):
        from estorch_tpu.obs.export.regress import (compare_phase_files,
                                                    compare_tail_files)

        cur = str(tmp_path / "cur.jsonl")
        self._run_jsonl(cur)
        old = str(tmp_path / "BENCH_r05.json")
        with open(old, "w") as f:
            json.dump({"n": 5, "parsed": {
                "metric": "env_steps_per_sec_per_chip",
                "value": 62791.4, "unit": "env-steps/s/chip (cpu)"}}, f)
        for fn, what in ((compare_phase_files, "per-phase"),
                         (compare_tail_files, "tail")):
            with pytest.raises(ValueError) as ei:
                fn(cur, old)
            msg = str(ei.value)
            assert "\n" not in msg, msg  # ONE line
            assert "baseline" in msg and f"no {what} rows" in msg
            assert "capture-baseline" in msg  # says how to fix it
        # the CLI prints it as a one-line error, exit 1, no traceback
        rc = obs_main(["regress", cur, "--baseline", old, "--phases"])
        assert rc == 1
        err = capsys.readouterr().err
        assert err.startswith("regress:") and err.count("\n") == 1

    def test_empty_current_names_the_current_side(self, tmp_path):
        from estorch_tpu.obs.export.regress import compare_phase_files

        base = str(tmp_path / "BENCH_r06.json")
        self._r06(base)
        bare = str(tmp_path / "bare.jsonl")
        with open(bare, "w") as f:
            f.write(json.dumps({"generation": 0,
                                "env_steps_per_sec": 5.0}) + "\n")
        with pytest.raises(ValueError) as ei:
            compare_phase_files(bare, base)
        assert "current measurement carries no per-phase rows" \
            in str(ei.value)

    def test_embedded_repeats_are_distinct_samples_not_replays(self):
        """Baseline phase_rows carry a 'repeat' stamp: generation g of
        repeat 0 and of repeat 1 are different measurements and must
        BOTH survive; a replayed generation within one repeat (same
        (repeat, generation)) still dedupes keeping the last."""
        from estorch_tpu.obs.export.regress import (extract_phase_samples,
                                                    extract_tail_groups)

        rows = [{"phase_rows": [
            {"repeat": r, "generation": g, "wall_time_s": 1.0 + r,
             "phases": {"eval": 0.5 + r}}
            for r in range(3) for g in range(4)]}]
        phases = extract_phase_samples(rows)
        assert len(phases["eval"]) == 12
        assert sorted(set(phases["eval"])) == [0.5, 1.5, 2.5]
        groups = extract_tail_groups(rows)
        assert len(groups["wall_time_s"]) == 12
        # replay within one repeat: last occurrence wins, no double count
        rows[0]["phase_rows"].append(
            {"repeat": 0, "generation": 0, "wall_time_s": 9.0,
             "phases": {"eval": 9.0}})
        phases = extract_phase_samples(rows)
        assert len(phases["eval"]) == 12 and 9.0 in phases["eval"] \
            and phases["eval"].count(0.5) == 3

    def test_committed_r06_artifact_carries_what_the_gates_need(self):
        """The REAL committed baseline (satellite: the trajectory no
        longer ends at r05): embedded phase rows, a tail headline, and
        the typed device probe."""
        path = os.path.join(REPO, "BENCH_r06.json")
        with open(path) as f:
            art = json.load(f)
        assert art["phase_rows"] and all(
            isinstance(r.get("phases"), dict) for r in art["phase_rows"])
        assert art["extras"]["phases_headline"]
        assert art["extras"]["tail_headline"]["wall_time_s"]["p99_s"] > 0
        # the tail baseline must be STEADY STATE: a warm-up/compile
        # generation left in phase_rows becomes the p99 (nearest-rank
        # over ~35 samples is the max) and would wave a real 100x
        # dispatch-tail regression through
        walls = [r["wall_time_s"] for r in art["phase_rows"]]
        assert max(walls) < 3 * sorted(walls)[len(walls) // 2], (
            "compile-spike rows leaked into the committed tail baseline")
        assert art["extras"]["device_probe"]["status"] in (
            "ok", "failed")
        from estorch_tpu.obs.export.regress import (
            extract_phase_samples, extract_tail_groups, load_rows,
            measurement_platform)

        rows = load_rows(path)
        assert measurement_platform(rows) in ("cpu", "tpu")
        phases = extract_phase_samples(rows)
        # every repeat's every generation is a sample (n repeats ×
        # gens-per-repeat == the embedded row count — nothing collapsed)
        assert phases and all(len(v) == len(art["phase_rows"])
                              for v in phases.values())
        assert "wall_time_s" in extract_tail_groups(rows)

    def test_committed_r07_artifact_carries_the_elastic_row(self):
        """The round-19 committed baseline (ISSUE 15 satellite: fresh
        committed history for this round's gates): same steady-state
        phase-row contract as r06 PLUS the elastic multi-host row —
        sync-SPMD vs elastic-fold gps under the shared straggle_host
        plan, with the fold actually exercised and the accounting
        invariant intact at capture time."""
        path = os.path.join(REPO, "BENCH_r07.json")
        with open(path) as f:
            art = json.load(f)
        assert art["phase_rows"] and all(
            isinstance(r.get("phases"), dict) for r in art["phase_rows"])
        walls = [r["wall_time_s"] for r in art["phase_rows"]]
        assert max(walls) < 3 * sorted(walls)[len(walls) // 2], (
            "compile-spike rows leaked into the committed tail baseline")
        el = art["extras"]["elastic"]
        assert el["ratio"] >= 1.25
        assert el["elastic_gps"] > el["sync_gps"]
        assert el["results_folded"] > 0
        assert el["accounting_ok"] is True


# ---------------------------------------------------------------------
# THE e2e acceptance demo
# ---------------------------------------------------------------------

def _demo_factory():
    """Supervisor child factory (spawned: fresh interpreter — pin the
    backend to CPU before anything touches this image's default)."""
    import torch

    from estorch_tpu import ES
    from estorch_tpu.utils import force_cpu_backend

    force_cpu_backend(1)

    class TinyMLP(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Linear(4, 2)

        def forward(self, x):
            return self.net(x)

    class QuadAgent:
        def rollout(self, policy):
            with torch.no_grad():
                vec = torch.nn.utils.parameters_to_vector(
                    policy.parameters())
                reward = -float((vec ** 2).sum())
            self.last_episode_steps = 1
            return reward

    return ES(TinyMLP, QuadAgent, torch.optim.Adam, population_size=8,
              sigma=0.05, seed=11, table_size=1 << 12)


class TestExportE2E:
    def test_supervised_run_scrapeable_throughout(self, tmp_path,
                                                  monkeypatch, capsys):
        """ISSUE 5 acceptance: SIGKILL a supervised training run
        mid-flight; the metrics sidecar keeps answering /metrics scrapes
        throughout with counter totals MONOTONE across the restart; the
        finished run's `obs trace` validates with a restart-boundary
        marker; `obs regress` passes the clean baseline and flags the
        injected-slowdown one."""
        from estorch_tpu.resilience import CHAOS_ENV, Supervisor
        from estorch_tpu.resilience import chaos as chaos_mod

        root = tmp_path / "run"
        plan = {"events": [{"kind": "die", "gen": 5}],
                "ledger": str(tmp_path / "chaos_ledger")}
        monkeypatch.setenv(CHAOS_ENV, json.dumps(plan))
        chaos_mod.reset_cache()

        sc = MetricsSidecar(str(root.absolute()), port=0)
        os.makedirs(root, exist_ok=True)
        sc.start_background()
        url = f"http://{sc.host}:{sc.port}/metrics"
        series: list[dict] = []
        scrape_errors: list[str] = []
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    with urllib.request.urlopen(url, timeout=10) as r:
                        body = r.read().decode()
                    series.append(samples_by_name(parse_exposition(body)))
                except Exception as e:  # noqa: BLE001 — collected and
                    scrape_errors.append(repr(e))  # asserted empty below
                stop.wait(0.2)

        t = threading.Thread(target=scraper, daemon=True)
        t.start()
        try:
            sup = Supervisor(_demo_factory, str(root),
                             target_generation=8, every=2,
                             max_restarts=2, backoff_s=0.1, poll_s=0.25,
                             startup_grace_s=300.0)
            res = sup.run()
            # one last scrape AFTER the final publish: the post-run truth
            with urllib.request.urlopen(url, timeout=10) as r:
                series.append(samples_by_name(
                    parse_exposition(r.read().decode())))
        finally:
            stop.set()
            t.join(timeout=10)
            sc.close()
        assert res["ok"], f"supervisor failed: {res}"
        assert len(res["restarts"]) == 1  # exactly the gen-5 SIGKILL

        # (a) scrapeable throughout: every scrape answered and parsed,
        # spanning both children, and env_steps totals never went
        # backwards — the published+live composition did not double count
        # or lose the dead child's totals
        assert not scrape_errors, scrape_errors
        assert len(series) >= 5
        steps = [s["estorch_env_steps"] for s in series
                 if "estorch_env_steps" in s]
        assert steps, "no scrape ever saw counters"
        assert steps == sorted(steps), f"totals went backwards: {steps}"
        # totals are "through each child's last beat" (a heartbeat cannot
        # see past itself, so each child's final generation lags one
        # beat): > 40 proves child2's live counters rode ON TOP of
        # child1's published totals (child1 alone could reach at most
        # 5 gens x 8 steps), and the final scrape must equal the
        # manifest's cross-restart totals exactly
        assert steps[-1] > 5 * 8
        manifest = json.load(open(root / "manifest.json"))
        assert steps[-1] == manifest["resilience"]["counters"]["env_steps"]
        final = series[-1]
        assert final["estorch_supervisor_restarts"] == 1

        # (b) the finished run's trace validates, with the restart marked
        out_path = str(tmp_path / "trace.json")
        assert obs_main(["trace", str(root / "run.jsonl"),
                         "-o", out_path]) == 0
        capsys.readouterr()
        trace = json.load(open(out_path))
        assert validate_trace(trace) == []
        markers = [e for e in trace["traceEvents"]
                   if e["name"] == "supervisor restart"]
        assert len(markers) == 1
        assert trace["otherData"]["segments"] == 2

        # (c) regress: clean baseline passes, injected slowdown flagged
        rates, _ = load_measurement(str(root / "run.jsonl"))
        med = sorted(rates)[len(rates) // 2]
        clean = tmp_path / "BENCH_clean.json"
        clean.write_text(json.dumps({"parsed": {
            "metric": "env_steps_per_sec", "value": med}}))
        assert obs_main(["regress", str(root / "run.jsonl"),
                         "--baseline", str(clean), "--json"]) == 0
        # a copied baseline claiming 2.5x the measured rate = a 60% drop,
        # far outside any band this noisy host can legitimately learn
        slow = tmp_path / "BENCH_slow.json"
        slow.write_text(json.dumps({"parsed": {
            "metric": "env_steps_per_sec", "value": med * 2.5}}))
        assert obs_main(["regress", str(root / "run.jsonl"),
                         "--baseline", str(slow), "--json"]) == 1
        v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert v["verdict"] == "regress" and v["drop_pct"] > 30.0
