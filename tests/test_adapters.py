"""Torch↔flax parameter adapters and the JaxEnv→gymnasium adapter."""

import numpy as np
import torch

import jax
import jax.numpy as jnp

from estorch_tpu import MLPPolicy
from estorch_tpu.envs import CartPole
from estorch_tpu.envs.gym_adapter import GymFromJax
from estorch_tpu.models.torch_adapter import flax_mlp_to_torch, torch_mlp_to_flax


def _torch_mlp():
    return torch.nn.Sequential(
        torch.nn.Linear(4, 16), torch.nn.Tanh(),
        torch.nn.Linear(16, 16), torch.nn.Tanh(),
        torch.nn.Linear(16, 2),
    )


class TestTorchFlaxAdapter:
    def test_roundtrip_preserves_outputs(self):
        tp = _torch_mlp()
        fm = MLPPolicy(action_dim=2, hidden=(16, 16))
        params = torch_mlp_to_flax(tp, fm)

        obs = np.random.RandomState(0).randn(4).astype(np.float32)
        with torch.no_grad():
            torch_out = tp(torch.from_numpy(obs)).numpy()
        flax_out = np.asarray(fm.apply({"params": params}, jnp.asarray(obs)))
        np.testing.assert_allclose(flax_out, torch_out, rtol=1e-5, atol=1e-6)

        # inverse: mutate flax params, load back, outputs must follow
        params2 = jax.tree_util.tree_map(lambda x: x * 1.5, params)
        flax_mlp_to_torch(params2, tp)
        with torch.no_grad():
            torch_out2 = tp(torch.from_numpy(obs)).numpy()
        flax_out2 = np.asarray(fm.apply({"params": params2}, jnp.asarray(obs)))
        np.testing.assert_allclose(flax_out2, torch_out2, rtol=1e-5, atol=1e-6)

    def test_layer_mismatch_rejected(self):
        tp = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Linear(8, 2))
        fm = MLPPolicy(action_dim=2, hidden=(16, 16))  # 3 dense layers
        import pytest

        with pytest.raises(ValueError, match="layer count"):
            torch_mlp_to_flax(tp, fm)

    def test_bias_free_linear_rejected(self):
        import pytest

        tp = torch.nn.Sequential(
            torch.nn.Linear(4, 8, bias=False), torch.nn.Linear(8, 2)
        )
        fm = MLPPolicy(action_dim=2, hidden=(8,))
        with pytest.raises(ValueError, match="bias=False"):
            torch_mlp_to_flax(tp, fm)

    def test_inverse_shape_mismatch_rejected(self):
        """copy_ broadcasts — the adapter must catch size-1 mismatches."""
        import pytest

        fm = MLPPolicy(action_dim=1, hidden=(8,))
        params = torch_mlp_to_flax(
            torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Linear(8, 1)), fm
        )
        wrong_head = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.Linear(8, 2)
        )
        with pytest.raises(ValueError, match="shape mismatch"):
            flax_mlp_to_torch(params, wrong_head)


class TestGymAdapter:
    def test_reference_style_rollout_over_jax_env(self):
        """The reference's while-not-done loop drives the device env."""
        genv = GymFromJax(CartPole(), seed=0)
        obs, _ = genv.reset(seed=3)
        assert obs.shape == (4,)
        total, steps = 0.0, 0
        done = False
        while not done and steps < 100:
            obs, r, term, trunc, _ = genv.step(genv.action_space.sample())
            total += r
            steps += 1
            done = term or trunc
        assert steps > 0
        assert total == steps  # CartPole: +1 per step

    def test_truncation_at_max_steps(self):
        genv = GymFromJax(CartPole(), seed=0, max_steps=5)
        genv.reset(seed=1)
        for i in range(5):
            _, _, term, trunc, _ = genv.step(1)
            if term:
                break
        assert term or trunc

    def test_spaces_match_env(self):
        genv = GymFromJax(CartPole())
        assert genv.action_space.n == 2
        assert genv.observation_space.shape == (4,)

    def test_continuous_bounds_honored(self):
        from estorch_tpu.envs import Pendulum

        genv = GymFromJax(Pendulum())
        assert float(genv.action_space.high[0]) == 2.0
        assert float(genv.action_space.low[0]) == -2.0

    def test_is_gymnasium_env_and_wrappable(self):
        import gymnasium as gym

        genv = GymFromJax(CartPole(), max_steps=10)
        assert isinstance(genv, gym.Env)
        wrapped = gym.wrappers.RecordEpisodeStatistics(genv)
        obs, _ = wrapped.reset(seed=0)
        for _ in range(10):
            obs, r, term, trunc, info = wrapped.step(wrapped.action_space.sample())
            if term or trunc:
                break
        assert term or trunc

    def test_step_before_reset_raises(self):
        import pytest

        genv = GymFromJax(CartPole())
        with pytest.raises(RuntimeError, match="reset"):
            genv.step(0)

    def test_max_steps_zero_honored(self):
        genv = GymFromJax(CartPole(), max_steps=0)
        genv.reset(seed=0)
        _, _, term, trunc, _ = genv.step(1)
        assert trunc  # horizon 0 → truncated immediately, not defaulted to 500
