"""AtariPreprocessPool: stack/repeat/sticky semantics + pooled integration."""

import numpy as np
import pytest

from estorch_tpu.envs.atari_wrappers import AtariPreprocessPool, apply_prep_to_spec


class FakePool:
    """Scripted pool: frame value = its step index (broadcast over pixels),

    env 1 reports done on a chosen step. Mimics the native-pool auto-reset
    contract (post-done obs is the fresh state)."""

    def __init__(self, n_envs=2, shape=(4, 4, 1), done_at=10**9):
        self.env_name = "fake"
        self.n_envs = n_envs
        self.obs_shape = shape
        self.obs_dim = int(np.prod(shape))
        self.act_dim = 1
        self.discrete = True
        self.n_actions = 3
        self.t = 0
        self.done_at = done_at
        self.actions_seen = []

    def is_native(self):
        return True

    def _frame(self):
        return np.full((self.n_envs, self.obs_dim), float(self.t), np.float32)

    def reset(self):
        self.t = 0
        return self._frame()

    def step(self, actions):
        self.actions_seen.append(np.asarray(actions).copy())
        self.t += 1
        rew = np.full(self.n_envs, 1.0, np.float32)
        done = np.zeros(self.n_envs, bool)
        if self.t == self.done_at:
            done[1] = True
        return self._frame(), rew, done


class TestFrameStack:
    def test_reset_fills_all_slots(self):
        w = AtariPreprocessPool(FakePool(), frame_stack=4)
        obs = w.reset()
        assert w.obs_shape == (4, 4, 4)
        assert obs.shape == (2, 64)
        np.testing.assert_array_equal(obs, 0.0)

    def test_stack_orders_oldest_to_newest(self):
        w = AtariPreprocessPool(FakePool(), frame_stack=4)
        w.reset()
        for _ in range(3):
            obs, _, _ = w.step(np.zeros((2, 1)))
        frames = obs.reshape(2, 4, 4, 4)
        # channels should read [0, 1, 2, 3] after three steps from reset 0
        np.testing.assert_array_equal(frames[0, 0, 0, :], [0.0, 1.0, 2.0, 3.0])

    def test_done_refills_stack_next_step(self):
        w = AtariPreprocessPool(FakePool(done_at=2), frame_stack=4)
        w.reset()
        w.step(np.zeros((2, 1)))
        obs, _, done = w.step(np.zeros((2, 1)))  # env 1 done here
        assert done.tolist() == [False, True]
        obs, _, _ = w.step(np.zeros((2, 1)))
        frames = obs.reshape(2, 4, 4, 4)
        # env 0 keeps history; env 1's stack is all the fresh frame
        np.testing.assert_array_equal(frames[0, 0, 0, :], [0.0, 1.0, 2.0, 3.0])
        np.testing.assert_array_equal(frames[1, 0, 0, :], [3.0, 3.0, 3.0, 3.0])

    def test_vector_obs_stack_along_new_axis(self):
        w = AtariPreprocessPool(FakePool(shape=(3,)), frame_stack=2)
        obs = w.reset()
        assert w.obs_shape == (3, 2)
        assert obs.shape == (2, 6)


class TestActionRepeatAndSticky:
    def test_repeat_sums_rewards_and_steps_k_times(self):
        base = FakePool()
        w = AtariPreprocessPool(base, frame_stack=1, action_repeat=4)
        w.reset()
        obs, rew, done = w.step(np.zeros((2, 1)))
        assert base.t == 4
        np.testing.assert_array_equal(rew, 4.0)

    def test_reward_masked_after_mid_repeat_done(self):
        base = FakePool(done_at=2)
        w = AtariPreprocessPool(base, frame_stack=1, action_repeat=4)
        w.reset()
        obs, rew, done = w.step(np.zeros((2, 1)))
        # env 1 finished at raw step 2: only 2 of 4 rewards count
        np.testing.assert_array_equal(rew, [4.0, 2.0])
        assert done.tolist() == [False, True]

    def test_sticky_replays_previous_action_at_expected_rate(self):
        base = FakePool(n_envs=512)
        w = AtariPreprocessPool(base, frame_stack=1, sticky_prob=0.25, seed=7)
        w.reset()
        w.step(np.full((512, 1), 2.0))
        w.step(np.full((512, 1), 1.0))
        second = base.actions_seen[1]
        frac_sticky = float(np.mean(second == 2.0))
        assert 0.15 < frac_sticky < 0.35  # ~Binomial(512, .25)

    def test_first_step_never_sticky(self):
        base = FakePool()
        w = AtariPreprocessPool(base, frame_stack=1, sticky_prob=0.99)
        w.reset()
        w.step(np.full((2, 1), 2.0))
        np.testing.assert_array_equal(base.actions_seen[0], 2.0)

    def test_max_pool2_requires_repeat(self):
        with pytest.raises(ValueError, match="max_pool2"):
            AtariPreprocessPool(FakePool(), max_pool2=True, action_repeat=1)


class TestSpecAdjustment:
    def test_apply_prep_to_spec(self):
        spec = {"obs_shape": (84, 84, 1), "obs_dim": 84 * 84, "act_dim": 1,
                "discrete": True, "n_actions": 3}
        out = apply_prep_to_spec(spec, 4)
        assert out["obs_shape"] == (84, 84, 4)
        assert out["obs_dim"] == 84 * 84 * 4
        assert out["n_actions"] == 3  # untouched fields preserved


class TestRealPoolIntegration:
    """Smoke-scale tier-1 coverage over a REAL pool (the in-tree pong84
    native env, NumPy fallback inside): the FakePool tests above pin the
    transform semantics, these pin that wrapping actual pool machinery
    constructs and steps — the path the @slow end-to-end test exercises
    at training scale.  (Found real: wrapping NativeEnvPool crashed on
    `is_native` — a property there, a method on GymVecPool.)"""

    def _wrapped(self, **kw):
        from estorch_tpu.envs.gym_vec_pool import make_pool

        return AtariPreprocessPool(make_pool("pong84", 2, seed=0),
                                   seed=0, **kw)

    def test_construct_reset_and_step_shapes(self):
        w = self._wrapped(frame_stack=4, action_repeat=2)
        assert w.obs_shape == (84, 84, 4)
        obs = w.reset()
        assert obs.shape == (2, 84 * 84 * 4) and obs.dtype == np.float32
        for _ in range(3):
            obs, rew, done = w.step(np.zeros((2, 1), np.float32))
        assert obs.shape == (2, 84 * 84 * 4)
        assert np.isfinite(obs).all() and np.isfinite(rew).all()
        assert done.shape == (2,)
        w.close()

    def test_is_native_accepts_property_and_method_pools(self):
        w = self._wrapped(frame_stack=2)
        assert isinstance(w.is_native(), bool)  # crashed before the fix
        # the FakePool (method spelling) keeps working too
        assert AtariPreprocessPool(FakePool(), frame_stack=2).is_native() \
            is True
        w.close()

    def test_sticky_and_maxpool_over_real_pool(self):
        w = self._wrapped(frame_stack=2, action_repeat=2,
                          sticky_prob=0.25, max_pool2=True)
        w.reset()
        obs, rew, done = w.step(np.ones((2, 1), np.float32))
        assert obs.shape == (2, 84 * 84 * 2)
        assert np.isfinite(rew).all()
        w.close()


class TestPooledIntegration:
    @pytest.mark.slow
    def test_pong84_naturecnn_designed_input_end_to_end(self):
        """BASELINE config 5's machinery with the CNN's designed 84x84x4
        input: one pooled generation through the frame-stacked pong."""
        import numpy as np

        from estorch_tpu.configs import pong84_conv

        es = pong84_conv(population_size=16, table_size=1 << 22,
                         agent_kwargs={"env_name": "pong84", "horizon": 40,
                                       "frame_stack": 4, "action_repeat": 2,
                                       "sticky_prob": 0.25})
        assert es.engine.pool.obs_shape == (84, 84, 4)
        es.train(1, verbose=False)
        assert np.isfinite(es.history[0]["reward_mean"])
