"""Tier-1 gate: the framework itself is esguard-clean modulo baseline.

This is the self-application contract of the analyzer — every PR runs
the same rules CI would run on user code against estorch_tpu's own
``algo/``, ``parallel/``, ``envs/``, ``host/``, ``ops/``, ``utils/``,
with the repo's checked-in pyproject config and baseline.  Four things
fail it: a new unsuppressed finding, a stale baseline entry (the bug it
suppressed was fixed — delete the entry), a baseline entry with no
justification, and a ratchet mismatch (more R18–R22 findings than the
committed ceiling = new race debt; fewer = lower the ceiling so the
improvement locks in).
"""

from __future__ import annotations

import functools
import os

from estorch_tpu.analysis import (Baseline, all_rules, analyze_paths,
                                  check_ratchet, load_baseline,
                                  load_config, load_ratchet,
                                  sort_findings)

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@functools.lru_cache(maxsize=1)
def _run_repo_analysis():
    cfg = load_config(os.path.join(REPO_ROOT, "pyproject.toml"))
    rules = [r for r in all_rules()
             if r.id in cfg.rule_ids([r.id for r in all_rules()])]
    findings = analyze_paths(
        [os.path.join(REPO_ROOT, "estorch_tpu")],
        rules=rules,
        exclude=cfg.exclude,
    )
    # baseline entries are repo-relative; findings are cwd-relative (or
    # absolute when run outside the repo) — rebase through abspath so
    # matching is invocation-independent
    rebased = [
        f.__class__(**{**f.to_dict(),
                       "file": os.path.relpath(os.path.abspath(f.file),
                                               REPO_ROOT)})
        for f in findings
    ]
    baseline_path = cfg.baseline_path()
    baseline = (load_baseline(baseline_path)
                if baseline_path and os.path.exists(baseline_path)
                else Baseline())
    return baseline, baseline.apply(sort_findings(rebased))


def test_framework_is_esguard_clean():
    baseline, res = _run_repo_analysis()
    report = "\n".join(f.render() for f in res.unsuppressed)
    assert not res.unsuppressed, (
        f"esguard found new issues in estorch_tpu/ "
        f"(fix them or baseline WITH a reason):\n{report}")


def test_baseline_has_no_stale_entries():
    _, res = _run_repo_analysis()
    stale = "\n".join(
        f"{e.rule} {e.file} [{e.symbol}] `{e.snippet}`" for e in res.stale)
    assert not res.stale, (
        f"baseline entries whose finding no longer exists — delete them:\n"
        f"{stale}")


def test_baseline_entries_are_justified():
    baseline, _ = _run_repo_analysis()
    unjust = [e for e in baseline.unjustified()]
    assert not unjust, (
        "baseline entries need a `reason`: "
        + ", ".join(f"{e.rule}:{e.file}" for e in unjust))


def test_ratchet_matches_current_counts():
    """The committed per-rule ceiling must equal today's totals exactly:
    growth is new race debt, shrink means someone fixed a race and must
    re-pin (`--write-ratchet`) so the win cannot silently regress."""
    cfg = load_config(os.path.join(REPO_ROOT, "pyproject.toml"))
    ratchet_path = cfg.ratchet_path()
    assert ratchet_path and os.path.exists(ratchet_path), (
        "esguard_ratchet.json missing — the lockset debt ceiling must "
        "be checked in")
    _, res = _run_repo_analysis()
    all_findings = res.unsuppressed + res.suppressed
    check = check_ratchet(load_ratchet(ratchet_path), all_findings)
    assert check.ok(), (
        f"ratchet drift — regressions={check.regressions} "
        f"stale={check.stale}; fix new races or re-pin with "
        f"--write-ratchet")
