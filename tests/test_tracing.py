"""Fleet-wide distributed tracing (estorch_tpu/obs/tracing.py +
obs/agg/traces.py, docs/observability.md "Distributed tracing").

Anchors: the tail sampler's keep/drop precedence, the per-process
tracer's pending→verdict lifecycle (late hedge-loser segments follow
the verdict), the atomic traces.jsonl flush, cross-process assembly
with flow arrows, the collector's /traces landing (restart cursor
reset, exemplar grafting onto stored snapshots), the store's exemplar
window semantics across restart (a buried incarnation's trace ids must
NOT resurrect), the dash's ``slowest`` column, and THE acceptance
demo — a real hedged :class:`Router` over tracer-equipped stdlib toy
replicas whose assembled trace shows BOTH upstream legs across three
processes with the win attributed and the loser cancelled, plus
``obs slow --store`` naming the worst trace from the store alone.
"""

import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from estorch_tpu.obs.agg import dash
from estorch_tpu.obs.agg import traces as traces_agg
from estorch_tpu.obs.agg.collector import (Collector, Target,
                                           append_segments,
                                           trace_file_path, traces_url)
from estorch_tpu.obs.agg.store import SeriesStore
from estorch_tpu.obs.counters import Counters
from estorch_tpu.obs.export.prometheus import render_exposition
from estorch_tpu.obs.export.traceevent import validate_trace
from estorch_tpu.obs.hist import Histogram, Histograms
from estorch_tpu.obs.tracing import (PARENT_SPAN_HEADER, SAMPLED_HEADER,
                                     TRACE_HEADER, TRACES_FILENAME,
                                     ProcessTracer, TraceSampler,
                                     head_sampled, make_segment,
                                     read_segments, traces_payload,
                                     valid_segment)
from estorch_tpu.serve.router import Router


def _seg(tid, span, parent, proc, name, ts, dur, **attrs):
    s = make_segment(tid, span, parent, proc, name, ts, dur, attrs, ts=ts)
    s["seq"] = 1
    return s


# =====================================================================
# tail-based sampling
# =====================================================================

class TestTraceSampler:
    def test_outcome_flags_always_keep(self):
        s = TraceSampler(head_every=10 ** 9)
        assert s.verdict("t", 0.01, error=True) == "error"
        assert s.verdict("t", 0.01, shed=True) == "shed"
        assert s.verdict("t", 0.01, retried=True) == "retry"
        assert s.verdict("t", 0.01, hedged=True) == "hedge"
        assert s.verdict("t", 0.01, breaker=True) == "breaker"
        assert s.verdict("t", 0.01, forced=True) == "forced"

    def test_forced_outranks_error(self):
        s = TraceSampler(head_every=10 ** 9)
        assert s.verdict("t", 0.01, forced=True, error=True) == "forced"

    def test_head_sampling_is_deterministic_on_the_id(self):
        # every process reaches the same verdict with no coordination
        assert head_sampled("abc", 1)  # 1-in-1 keeps everything
        for tid in ("a", "b", "c", "d"):
            assert head_sampled(tid, 7) == head_sampled(tid, 7)

    def test_p99_rule_arms_only_with_enough_samples(self):
        hists = Histograms()
        s = TraceSampler(hists=hists, hist_name="router/route_s",
                         head_every=10 ** 9, p99_min_count=100)
        # below min_count: disarmed, clean fast trace drops
        for _ in range(50):
            hists.observe("router/route_s", 0.010)
        assert s.verdict("zz-no-head", 0.500) is None
        for _ in range(100):
            hists.observe("router/route_s", 0.010)
        # armed: slower than the live p99 keeps, faster drops
        assert s.verdict("zz-no-head", 0.500) == "p99"
        assert s.verdict("zz-no-head", 0.001) is None


# =====================================================================
# per-process tracer lifecycle
# =====================================================================

class TestProcessTracer:
    def test_kept_trace_gets_seq_and_sampling_reason_on_root(self):
        c = Counters()
        tr = ProcessTracer("router", counters=c, head_every=10 ** 9)
        root = tr.span_id()
        tr.add(make_segment("t1", root, None, "router", "route",
                            0.0, 0.02))
        tr.add(make_segment("t1", tr.span_id(), root, "router",
                            "upstream", 0.0, 0.015))
        assert tr.finish("t1", 0.02, error=True)
        segs, cursor = tr.since(0)
        assert len(segs) == 2 and cursor == 2
        assert all(s["seq"] > 0 for s in segs)
        roots = [s for s in segs if not s["parent_span_id"]]
        assert [s["attrs"].get("sampled") for s in roots] == ["error"]
        assert c.get("traces_sampled") == 1

    def test_dropped_trace_leaves_nothing_and_counts(self):
        c = Counters()
        tr = ProcessTracer("router", counters=c, head_every=10 ** 9)
        tr.add(make_segment("zz-no-head", tr.span_id(), None, "router",
                            "route", 0.0, 0.001))
        assert not tr.finish("zz-no-head", 0.001)
        assert tr.since(0) == ([], 0)
        assert c.get("traces_dropped") == 1

    def test_late_segment_follows_the_verdict(self):
        # a cancelled hedge loser's leg lands AFTER finish — it must
        # join a kept trace, and stay dropped for a dropped one
        tr = ProcessTracer("router", head_every=10 ** 9)
        tr.add(make_segment("tk", "router.1", None, "router", "route",
                            0.0, 0.02))
        tr.finish("tk", 0.02, hedged=True)
        tr.add(make_segment("tk", "router.2", "router.1", "router",
                            "upstream", 0.0, 0.01, {"cancelled": True}))
        segs, _ = tr.since(0)
        assert {s["span_id"] for s in segs} == {"router.1", "router.2"}
        tr.add(make_segment("zz-no-head", "router.3", None, "router",
                            "route", 0.0, 0.001))
        tr.finish("zz-no-head", 0.001)
        tr.add(make_segment("zz-no-head", "router.4", "router.3",
                            "router", "upstream", 0.0, 0.001))
        segs, _ = tr.since(0)
        assert not [s for s in segs if s["trace_id"] == "zz-no-head"]

    def test_flush_is_atomic_append_and_caps_the_file(self, tmp_path):
        path = str(tmp_path / "run" / TRACES_FILENAME)
        tr = ProcessTracer("server", head_every=1, path=path,
                           max_file_lines=5)
        for i in range(8):
            tr.add(make_segment(f"t{i}", tr.span_id(), None, "server",
                                "request", 0.0, 0.01))
            tr.finish(f"t{i}", 0.01)
            assert tr.flush() == 1
        assert tr.flush() == 0  # ring drained — nothing re-flushes
        assert not os.path.exists(path + ".tmp")
        rows = read_segments(path)
        assert len(rows) == 5  # oldest lines evicted by the cap
        assert rows[-1]["trace_id"] == "t7"

    def test_since_cursor_and_restart_goes_backward(self, tmp_path):
        tr = ProcessTracer("server", head_every=1)
        for i in range(3):
            tr.add(make_segment(f"t{i}", tr.span_id(), None, "server",
                                "request", 0.0, 0.01))
            tr.finish(f"t{i}", 0.01)
        segs, cursor = tr.since(0)
        assert len(segs) == 3 and cursor == 3
        segs2, cursor2 = tr.since(cursor)
        assert segs2 == [] and cursor2 == 3
        # a restarted process starts seq over: its cursor is SMALLER
        # than the collector's high-water mark — the reset signal
        fresh = ProcessTracer("server", head_every=1)
        _, fresh_cursor = fresh.since(0)
        assert fresh_cursor < cursor

    def test_traces_payload_carries_exemplars(self):
        hists = Histograms()
        hists.observe("serve/request_s", 0.5, exemplar="t-slow")
        tr = ProcessTracer("server", head_every=1)
        tr.add(make_segment("t-slow", tr.span_id(), None, "server",
                            "request", 0.0, 0.5))
        tr.finish("t-slow", 0.5)
        p = traces_payload(tr, 0, hists=hists)
        assert p["proc"] == "server" and p["cursor"] == 1
        assert [s["trace_id"] for s in p["segments"]] == ["t-slow"]
        ex = p["exemplars"]["serve/request_s"]
        assert ["t-slow"] in [ids for ids in ex.values()]
        # tracer-less process still answers the scrape shape
        empty = traces_payload(None, 7)
        assert empty == {"proc": None, "segments": [], "cursor": 7,
                         "exemplars": {}}


# =====================================================================
# segment schema / file IO
# =====================================================================

class TestSegmentIO:
    def test_valid_segment_rejects_malformed_rows(self):
        good = make_segment("t", "s", None, "p", "n", 0.0, 0.1)
        assert valid_segment(good)
        assert not valid_segment("nope")
        assert not valid_segment({**good, "trace_id": ""})
        assert not valid_segment({**good, "dur_s": "fast"})
        assert not valid_segment({**good, "ts": True})

    def test_read_segments_tolerates_torn_tail(self, tmp_path):
        path = tmp_path / TRACES_FILENAME
        rows = [make_segment("t", f"s{i}", None, "p", "n", 0.0, 0.1)
                for i in range(2)]
        path.write_text("\n".join(json.dumps(r) for r in rows)
                        + '\nnot json\n{"trace_id": "torn", "sp')
        got = read_segments(str(path))
        assert [r["span_id"] for r in got] == ["s0", "s1"]
        assert read_segments(str(tmp_path / "absent.jsonl")) == []


# =====================================================================
# assembly + export
# =====================================================================

class TestAssembly:
    def _fleet(self):
        return [
            _seg("t", "router.1", None, "router", "route", 10.0, 0.08,
                 sampled="retry"),
            _seg("t", "router.2", "router.1", "router", "upstream",
                 10.001, 0.07, replica="r0", status=200),
            _seg("t", "server.1", "router.2", "server", "request",
                 10.004, 0.06, status=200),
            _seg("t", "server.2", "server.1", "server", "compute",
                 10.01, 0.04),
        ]

    def test_assemble_orders_and_unions_wall_clock(self):
        asm = traces_agg.assemble(self._fleet())
        t = asm["t"]
        assert t["procs"] == ["router", "server"]
        assert [s["span_id"] for s in t["segments"]] == [
            "router.1", "router.2", "server.1", "server.2"]
        assert t["t0"] == 10.0
        assert t["dur_s"] == pytest.approx(0.08)
        assert t["sampled"] == "retry"

    def test_cross_process_edges_only_cross_hops(self):
        t = traces_agg.assemble(self._fleet())["t"]
        edges = traces_agg.cross_process_edges(t)
        assert [(p["span_id"], c["span_id"]) for p, c in edges] == [
            ("router.2", "server.1")]

    def test_export_validates_with_lanes_and_flows(self):
        t = traces_agg.assemble(self._fleet())["t"]
        trace = traces_agg.export_fleet_trace([t], files=1)
        assert validate_trace(trace) == []
        pids = {e["pid"] for e in trace["traceEvents"]
                if e["ph"] == "X"}
        assert len(pids) == 2  # one lane per process
        flows = [e for e in trace["traceEvents"]
                 if e["ph"] in ("s", "f")]
        assert len(flows) == 2  # the one cross-process edge

    def test_format_trace_names_the_hops(self):
        t = traces_agg.assemble(self._fleet())["t"]
        text = traces_agg.format_trace(t)
        assert "sampled=retry" in text
        assert "replica=r0" in text
        assert "compute" in text

    def test_trace_files_discovers_fleet_layout_deduped(self, tmp_path):
        row = json.dumps(_seg("t", "s", None, "p", "n", 0.0, 0.1))
        (tmp_path / "router").mkdir()
        (tmp_path / "r0").mkdir()
        (tmp_path / "router" / TRACES_FILENAME).write_text(row + "\n")
        (tmp_path / "r0" / TRACES_FILENAME).write_text(row + "\n")
        (tmp_path / "traces-r0.jsonl").write_text(row + "\n")
        (tmp_path / "notes.txt").write_text("not a segment file\n")
        files = traces_agg.trace_files([str(tmp_path), str(tmp_path)])
        assert len(files) == 3  # same dir twice must not double spans
        # scraped + fleet copies of the same span dedup on load
        assert len(traces_agg.load_segments(files)) == 1


class TestTraceCLI:
    def test_fleet_assembles_and_writes_perfetto(self, tmp_path, capsys):
        d = tmp_path / "router"
        d.mkdir()
        with open(d / TRACES_FILENAME, "w") as f:
            for s in TestAssembly()._fleet():
                f.write(json.dumps(s) + "\n")
        rc = traces_agg.main(["--fleet", str(tmp_path), "--print"])
        assert rc == 0
        out_path = tmp_path / "fleet_trace.json"
        assert out_path.exists()
        assert validate_trace(json.loads(out_path.read_text())) == []
        out = capsys.readouterr().out
        assert "1 trace" in out or "trace" in out

    def test_needs_exactly_one_source(self, tmp_path):
        assert traces_agg.main([]) == 3
        assert traces_agg.main(["--fleet", str(tmp_path), "--store",
                                str(tmp_path)]) == 3

    def test_empty_dir_is_rc2(self, tmp_path):
        assert traces_agg.main(["--fleet", str(tmp_path)]) == 2

    def test_slow_rejects_silly_quantile(self, tmp_path):
        assert traces_agg.main_slow(["--store", str(tmp_path),
                                     "--quantile", "1.5"]) == 3

    def test_module_cli_routes_trace_and_slow(self, tmp_path):
        from estorch_tpu.obs.__main__ import main as obs_main

        assert obs_main(["trace", "--fleet", "--selfcheck"]) == 0
        assert obs_main(["slow", "--store", str(tmp_path)]) == 1


# =====================================================================
# collector: /traces landing
# =====================================================================

class TestCollectorTraceLanding:
    def test_append_segments_caps_and_skips_invalid(self, tmp_path):
        path = trace_file_path(str(tmp_path), "serve a/b")
        assert os.path.basename(path) == "traces-serve_a_b.jsonl"
        good = [make_segment(f"t{i}", "s", None, "p", "n", 0.0, 0.1)
                for i in range(3)]
        # invalid rows are skipped (return counts VALID rows landed);
        # the file itself keeps only the newest max_lines
        assert append_segments(path, good + ["junk", {"no": "keys"}],
                               max_lines=2) == 3
        rows = read_segments(path)
        assert [r["trace_id"] for r in rows] == ["t1", "t2"]
        assert append_segments(path, ["junk"]) == 0

    def test_traces_url_swaps_the_path(self):
        assert traces_url("http://127.0.0.1:9000/metrics") == \
            "http://127.0.0.1:9000/traces"

    def _collector(self, tmp_path):
        store = SeriesStore(str(tmp_path / "store"))
        t = Target("s1", url="http://127.0.0.1:1/metrics")
        return Collector([t], store, serve_http=False), t, store

    def test_land_traces_grafts_exemplars_and_advances_cursor(
            self, tmp_path):
        col, t, store = self._collector(tmp_path)
        state = col._states["s1"]
        h = Histogram()
        h.observe(0.5)
        sample = {"name": "estorch_serve_request_s",
                  "labels": {"target": "s1"}, "hist": h.to_dict()}
        r = {"samples": [sample], "error": None, "trace_error": None,
             "traces": {"proc": "server", "cursor": 4,
                        "segments": [make_segment("t-slow", "s", None,
                                                  "server", "request",
                                                  0.0, 0.5)],
                        "exemplars": {"serve/request_s":
                                      {"7": ["t-slow"]}}}}
        assert col._land_traces(t, state, r) == 1
        assert state.trace_cursor == 4
        assert col.counters["agg_trace_segments_total"] == 1
        # exemplars grafted onto THIS tick's snapshot (Prometheus text
        # cannot carry them), keyed by the prometheus metric name
        assert sample["hist"]["exemplars"] == {"7": ["t-slow"]}
        assert read_segments(
            trace_file_path(store.root, "s1"))[0]["trace_id"] == "t-slow"

    def test_backward_cursor_means_restart_and_resets(self, tmp_path):
        col, t, state_store = self._collector(tmp_path)
        state = col._states["s1"]
        state.trace_cursor = 40
        r = {"samples": [], "error": None, "trace_error": None,
             "traces": {"proc": "server", "cursor": 2, "segments": [],
                        "exemplars": {}}}
        col._land_traces(t, state, r)
        assert state.trace_cursor == 0  # next tick re-reads the window

    def test_trace_scrape_error_counts_not_raises(self, tmp_path):
        col, t, _ = self._collector(tmp_path)
        r = {"samples": [], "error": None,
             "trace_error": "URLError: refused", "traces": None}
        assert col._land_traces(t, col._states["s1"], r) == 0
        assert col.counters["agg_trace_scrape_errors_total"] == 1

    def test_tick_scrapes_metrics_and_traces_together(self, tmp_path):
        hists = Histograms()
        tracer = ProcessTracer("server", hists=hists, head_every=1)

        class FakeTarget(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.startswith("/traces"):
                    since = int(self.path.split("since=")[-1]) \
                        if "since=" in self.path else 0
                    body = json.dumps(traces_payload(
                        tracer, since, hists=hists)).encode()
                    ctype = "application/json"
                else:
                    body = render_exposition(
                        {"requests_total": 1}, None, up=True,
                        histograms=hists.export()).encode()
                    ctype = "text/plain; version=0.0.4"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        srv = ThreadingHTTPServer(("127.0.0.1", 0), FakeTarget)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            hists.observe("serve/request_s", 0.5, exemplar="t-slow")
            tracer.add(make_segment("t-slow", tracer.span_id(), None,
                                    "server", "request", 0.0, 0.5))
            tracer.finish("t-slow", 0.5)
            store = SeriesStore(str(tmp_path / "store"))
            target = Target(
                "s1",
                url=f"http://127.0.0.1:{srv.server_address[1]}/metrics")
            col = Collector([target], store, serve_http=False)
            first = col.tick(now=1000.0)
            assert first["targets"]["s1"]["ok"]
            assert first["targets"]["s1"]["segments"] == 1
            # cursor advanced: an idle second tick lands nothing new
            second = col.tick(now=1001.0)
            assert second["targets"]["s1"]["segments"] == 0
            assert col.counters["agg_trace_segments_total"] == 1
            # the landed exemplar is queryable from the STORE alone
            h = store.hist_window("estorch_serve_request_s",
                                  {"target": "s1"}, window_s=60,
                                  now=1001.0)
            assert h is not None and h.slow_exemplars(0.5) == ["t-slow"]
            got = traces_agg.load_segments(
                traces_agg.store_trace_files(store.root))
            assert [s["trace_id"] for s in got] == ["t-slow"]
        finally:
            srv.shutdown()
            srv.server_close()


# =====================================================================
# store exemplar windows
# =====================================================================

class TestStoreExemplars:
    def _snap(self, h):
        return {"name": "estorch_serve_request_s",
                "labels": {"target": "a"}, "hist": h.to_dict()}

    def test_window_keeps_only_positive_delta_buckets(self, tmp_path):
        s = SeriesStore(str(tmp_path / "store"))
        h = Histogram()
        h.observe(0.5, exemplar="t-old")
        s.append([self._snap(h)], ts=1000.0)
        h.observe(0.004, exemplar="t-new")
        s.append([self._snap(h)], ts=1500.0)
        # the window [1400, 1500] saw only the fast bucket grow — the
        # slow bucket's old exemplar must not be attributed to it
        w = s.hist_window("estorch_serve_request_s", {"target": "a"},
                          window_s=100, now=1500.0)
        assert w.count == 1
        assert w.slow_exemplars(0.5) == ["t-new"]

    def test_restart_buries_pre_restart_exemplars(self, tmp_path):
        # exemplar trace ids from a dead incarnation name traces nobody
        # can assemble — the recent window must not resurrect them
        s = SeriesStore(str(tmp_path / "store"))
        h1 = Histogram()
        for _ in range(10):
            h1.observe(0.5, exemplar="t-dead")
        s.append([self._snap(h1)], ts=1000.0)
        h2 = Histogram()  # restarted process: fresh histogram
        h2.observe(0.3, exemplar="t-live")
        s.append([self._snap(h2)], ts=1001.0)
        w = s.hist_window("estorch_serve_request_s", {"target": "a"},
                          window_s=60, now=1001.0)
        assert w.count == 11  # buried counts still fold in…
        ids = w.slow_exemplars(0.0)
        assert "t-live" in ids and "t-dead" not in ids  # …ids do not

    def test_exemplars_survive_segment_roll(self, tmp_path):
        s = SeriesStore(str(tmp_path / "store"), max_segments=3,
                        segment_max_samples=2)
        h = Histogram()
        for i in range(8):
            h.observe(0.5, exemplar=f"t{i}")
            s.append([self._snap(h)], ts=1000.0 + i)
        w = s.hist_window("estorch_serve_request_s", {"target": "a"},
                          window_s=3, now=1007.0)
        assert w is not None and "t7" in w.slow_exemplars(0.5)


# =====================================================================
# dash: the `slowest` column
# =====================================================================

class TestDashSlowest:
    def _store_with(self, tmp_path, exemplar):
        s = SeriesStore(str(tmp_path / "store"))
        h = Histogram()
        h.observe(0.5, exemplar=exemplar)
        s.append([{"name": "estorch_up", "labels": {"target": "a"},
                   "value": 1},
                  {"name": dash.REQUEST_HIST, "labels": {"target": "a"},
                   "hist": h.to_dict()}], ts=1000.0)
        return str(tmp_path / "store")

    def test_snapshot_names_the_worst_trace(self, tmp_path):
        root = self._store_with(tmp_path, "t-worst")
        snap = dash.fleet_snapshot(root, window_s=60, now=1000.0)
        assert snap["targets"][0]["slowest_trace"] == "t-worst"
        text = dash.render(root, window_s=60, now=1000.0)
        assert "slowest" in text and "t-worst" in text

    def test_exemplar_less_target_renders_dash(self, tmp_path):
        root = self._store_with(tmp_path, None)  # tracing off upstream
        snap = dash.fleet_snapshot(root, window_s=60, now=1000.0)
        assert snap["targets"][0]["slowest_trace"] is None
        row = dash.render(root, window_s=60,
                          now=1000.0).splitlines()[-1]
        assert " - " in row  # honest '-', not a fabricated id


# =====================================================================
# obs slow: worst traces from the store alone
# =====================================================================

class TestSlowFromStore:
    def test_join_exemplars_to_scraped_segments(self, tmp_path, capsys):
        root = str(tmp_path / "store")
        s = SeriesStore(root)
        h = Histogram()
        for _ in range(200):
            h.observe(0.010)
        h.observe(0.800, exemplar="t-tail")
        s.append([{"name": "estorch_serve_request_s",
                   "labels": {"target": "s1"}, "hist": h.to_dict()}],
                 ts=1000.0)
        segs = [_seg("t-tail", "router.1", None, "router", "route",
                     999.0, 0.80, sampled="p99"),
                _seg("t-tail", "server.1", "router.1", "server",
                     "request", 999.1, 0.78, status=200)]
        append_segments(trace_file_path(root, "s1"), segs)
        res = traces_agg.slowest_traces(root, quantile=0.99,
                                        window_s=3600.0)
        assert res["metric"] == "estorch_serve_request_s"
        assert res["ids"] == ["t-tail"]
        assert [t["trace_id"] for t in res["traces"]] == ["t-tail"]
        assert res["traces"][0]["procs"] == ["router", "server"]
        assert res["missing"] == []
        assert traces_agg.main_slow(["--store", root, "--window",
                                     "3600"]) == 0
        out = capsys.readouterr().out
        assert "t-tail" in out and "server" in out

    def test_exemplar_without_segments_reports_missing(self, tmp_path):
        root = str(tmp_path / "store")
        s = SeriesStore(root)
        h = Histogram()
        h.observe(0.5, exemplar="t-gone")
        s.append([{"name": "estorch_serve_request_s",
                   "labels": {"target": "s1"}, "hist": h.to_dict()}],
                 ts=1000.0)
        res = traces_agg.slowest_traces(root, window_s=3600.0)
        assert res["ids"] == ["t-gone"] and res["missing"] == ["t-gone"]
        assert res["traces"] == []

    def test_empty_store_answers_honestly(self, tmp_path):
        res = traces_agg.slowest_traces(str(tmp_path))
        assert res["metric"] is None and res["traces"] == []


# =====================================================================
# loadgen: the measurement-file join key
# =====================================================================

class TestLoadgenTraceIds:
    def test_latency_rows_carry_the_join_key(self, tmp_path):
        from estorch_tpu.serve.loadgen import write_latency_rows

        path = write_latency_rows([0.01, 0.02, 0.03],
                                  str(tmp_path / "lat.jsonl"),
                                  trace_ids=["t-a", "", "t-c"])
        rows = [json.loads(ln)
                for ln in open(path).read().splitlines()]
        assert [r.get("trace_id") for r in rows] == ["t-a", None, "t-c"]
        assert all(r["endpoint"] == "/predict" for r in rows)
        # rows without trace ids keep the legacy shape exactly
        legacy = write_latency_rows([0.01], str(tmp_path / "l2.jsonl"))
        assert json.loads(open(legacy).read()) == {
            "endpoint": "/predict", "latency_s": 0.01}


# =====================================================================
# acceptance: a real hedged router's trace assembles across processes
# =====================================================================

def _traced_toy_replica(proc, run_dir, *, delay_s=0.0):
    os.makedirs(run_dir, exist_ok=True)
    tracer = ProcessTracer(proc, head_every=1,
                           path=os.path.join(run_dir, TRACES_FILENAME))
    state = {"requests": 0}

    class Toy(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _j(self, obj):
            body = json.dumps(obj).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._j({"ok": True, "draining": False,
                         "queue_depth": 0})
            else:
                self._j({"queue_depth": 0, "request_ms": {"p99": 1.0}})

        def do_POST(self):
            t0 = time.monotonic()
            trace = self.headers.get(TRACE_HEADER)
            parent = self.headers.get(PARENT_SPAN_HEADER) or None
            forced = self.headers.get(SAMPLED_HEADER) == "1"
            n = int(self.headers.get("Content-Length", 0))
            data = json.loads(self.rfile.read(n))
            state["requests"] += 1
            if delay_s:
                time.sleep(delay_s)
            # record BEFORE replying: a cancelled hedge loser's client
            # is gone, but its segment must still join the trace
            if trace:
                dt = time.monotonic() - t0
                tracer.add(make_segment(trace, tracer.span_id(), parent,
                                        proc, "request", t0, dt,
                                        {"status": 200}))
                tracer.finish(trace, dt, forced=forced)
            self._j({"action": [v * 2.0 for v in data["obs"]]})

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Toy)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, tracer, state


class TestHedgedTraceAcceptance:
    def test_hedged_trace_assembles_across_three_processes(
            self, tmp_path):
        slow_srv, slow_tr, _ = _traced_toy_replica(
            "replica-slow", str(tmp_path / "r0"), delay_s=0.4)
        fast_srv, fast_tr, _ = _traced_toy_replica(
            "replica-fast", str(tmp_path / "r1"))
        # upstream_timeout_s < the stall: the abandoned loser leg is
        # GUARANTEED to end in an error while its cancel flag is set,
        # so the leg records ``cancelled`` deterministically
        router = Router(
            [("r-slow", f"127.0.0.1:{slow_srv.server_address[1]}"),
             ("r-fast", f"127.0.0.1:{fast_srv.server_address[1]}")],
            port=0, poll_interval_s=30.0, upstream_timeout_s=0.25,
            hedge=True, hedge_min_ms=60.0,
            run_dir=str(tmp_path / "router"))
        router.start_background()
        try:
            time.sleep(0.3)
            url = f"http://{router.host}:{router.port}/predict"
            for i in range(8):  # rr tiebreak: some start on the stall
                req = urllib.request.Request(
                    url, json.dumps({"obs": [float(i)]}).encode(),
                    {"Content-Type": "application/json",
                     TRACE_HEADER: f"t-e2e-{i}", SAMPLED_HEADER: "1"})
                with urllib.request.urlopen(req, timeout=15) as r:
                    assert json.loads(r.read())["action"] == [2.0 * i]
                    assert r.headers.get(TRACE_HEADER) == f"t-e2e-{i}"
            assert router.counters.get("router_hedged_total") >= 1
            time.sleep(0.8)  # let cancelled losers finish server-side
        finally:
            router.shutdown(drain=False)
            for s in (slow_srv, fast_srv):
                s.shutdown(), s.server_close()
        slow_tr.flush(), fast_tr.flush()

        files = traces_agg.trace_files([str(tmp_path)])
        assembled = traces_agg.assemble(traces_agg.load_segments(files))
        hedged = [t for t in assembled.values()
                  if len([s for s in t["segments"]
                          if s["name"] == "upstream"]) == 2]
        assert hedged, "no assembled trace carries both hedge legs"
        t = max(hedged, key=lambda t: len(t["procs"]))
        legs = [s for s in t["segments"] if s["name"] == "upstream"]
        cancelled = [s for s in legs if s["attrs"].get("cancelled")]
        winners = [s for s in legs if s["attrs"].get("status") == 200]
        assert len(cancelled) == 1 and len(winners) == 1
        assert winners[0]["attrs"].get("replica") == "r-fast"
        assert cancelled[0]["attrs"].get("replica") == "r-slow"
        # the trace spans all three processes: the router, the winner,
        # and the loser (whose late segment joins via the verdict cache)
        assert t["procs"][0] == "router"
        assert set(t["procs"]) == {"router", "replica-fast",
                                   "replica-slow"}
        assert traces_agg.cross_process_edges(t)
        trace = traces_agg.export_fleet_trace([t], files=len(files))
        assert validate_trace(trace) == []


class TestCancelRaceMapsToUpstreamError:
    def test_cancel_mid_read_records_cancelled_leg(self, monkeypatch):
        """A hedge cancel races the loser's ``resp.read()``: http.client
        can surface the concurrent close as errors outside the usual
        (TimeoutError, OSError, HTTPException) tuple — seen live as
        ``AttributeError: 'NoneType' object has no attribute 'close'``
        from a half-torn response.  With the cancel flag set that must
        take the normal failed-attempt path (loser leg recorded with
        ``cancelled``, breaker untouched), not kill the leg thread."""
        import http.client as _hc

        from estorch_tpu.serve.router import UpstreamError

        class TornConn:
            def __init__(self, *a, **kw):
                pass

            def request(self, *a, **kw):
                pass

            def getresponse(self):
                raise AttributeError(
                    "'NoneType' object has no attribute 'close'")

            def close(self):
                pass

        monkeypatch.setattr(_hc, "HTTPConnection", TornConn)
        router = Router([("r0", "127.0.0.1:1")], port=0,
                        serve_http=False, poll_interval_s=30.0)
        rep = router.replicas()[0]
        with pytest.raises(UpstreamError, match="cancelled mid-read"):
            router._attempt(rep, b"{}", "t-race",
                            cancel_box={"cancelled": True}, hedge=True)
        pend = router.tracer._pending.get("t-race", [])
        legs = [s for s in pend if s["name"] == "upstream"]
        assert len(legs) == 1 and legs[0]["attrs"]["cancelled"] is True
        assert rep.failures == 0 and rep.breaker.allow()
        # the SAME torn read without a cancel is NOT ours to absorb
        with pytest.raises(AttributeError):
            router._attempt(rep, b"{}", "t-race2")
