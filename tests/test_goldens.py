"""Fixed-seed golden values per algorithm (SURVEY.md §4 'Convergence/
regression'): refactors must not silently change the math.

Captured on the 8-virtual-device CPU backend at the settings below.  A
legitimate algorithm change (e.g. a deliberate estimator fix) should update
these values IN THE SAME COMMIT with a note; an unexpected diff here means
the refactor changed numerics.

Goldens are VERSION-KEYED: the values encode the jax.random stream of
the jax version they were captured under (different jax versions draw
different streams for the same seed — init params and every perturbation
change, so trajectories are incomparable across versions, not merely
fuzzy).  ``GOLDENS`` selects the set matching the running jax's
major.minor at import time, falling back to the canonical round-5 set —
so a NEW jax family fails loudly (record a set for it with the recipe
below) instead of silently skipping regression protection.
"""

import jax
import numpy as np
import optax
import pytest

from estorch_tpu import ES, NS_ES, NSR_ES, NSRA_ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole

# canonical set — captured on the round-5 image's jax (0.5/0.6 family)
GOLDENS_ROUND5 = {
    "ES": {"reward_means": [43.0, 40.375, 43.5625], "params_sum": -5.57803},
    # identical values to ES by construction: the decomposition identity
    # x@(W+cE) = x@W + c(x@E) is exact at these shapes on CPU f32 — if this
    # golden ever drifts from ES's, the decomposed forward broke
    "ES_decomposed": {
        "reward_means": [43.0, 40.375, 43.5625],
        "params_sum": -5.57803,
    },
    "NS_ES": {
        "reward_means": [35.125, 36.875, 34.1875],
        "meta_sums": [-5.61163, -1.94561],
        "archive_sum": -0.00939,
        "meta_indices": [1, 1, 1],
    },
    "NSR_ES": {
        "reward_means": [35.125, 37.125, 40.4375],
        "meta_sums": [-5.61163, -2.01648],
        "archive_sum": 0.29665,
        "meta_indices": [1, 1, 1],
    },
    "NSRA_ES": {
        "reward_means": [35.125, 37.1875, 40.4375],
        "meta_sums": [-5.61163, -1.96853],
        "archive_sum": 0.30099,
        "meta_indices": [1, 1, 1],
    },
    # round-3 modes (captured 8-virtual-device CPU, same recipe):
    "ES_obsnorm": {
        "reward_means": [43.0, 46.1875, 46.4375],
        "params_sum": -5.65297,
        # probe accounting: 1 (init) + 3 gens × 1 episode, CartPole-length
        # episodes — pinned so the stats plumbing can't silently change
        "obs_count": 140.0,
        "obs_mean_sum": 0.03157,
    },
    "ES_recurrent": {"reward_means": [9.875, 9.625, 9.375],
                     "params_sum": -2.02425},
    "ES_lowrank": {"reward_means": [43.625, 41.25, 38.25],
                   "params_sum": -5.60954},
    # round-5 mode: factored noise over the recurrent tree (trunk + GRU
    # gates + head), per-episode materialization (ops/lowrank.py tree form)
    "ES_recurrent_lowrank": {"reward_means": [11.0, 9.375, 9.375],
                             "params_sum": -1.73011},
}

# captured under jax 0.4.37 (this CI image), same recipe/settings —
# every value differs from GOLDENS_ROUND5 because the 0.4 random stream
# differs, NOT because the math does (the ES == ES_decomposed identity
# holds exactly in both sets, which is the cross-version sanity anchor)
GOLDENS_JAX04 = {
    "ES": {"reward_means": [15.75, 17.75, 18.0625], "params_sum": -0.36088},
    "ES_decomposed": {
        "reward_means": [15.75, 17.75, 18.0625],
        "params_sum": -0.36088,
    },
    "NS_ES": {
        "reward_means": [18.0, 15.6875, 14.5625],
        "meta_sums": [-0.34177, 2.15712],
        "archive_sum": -0.15584,
        "meta_indices": [1, 1, 1],
    },
    "NSR_ES": {
        "reward_means": [18.0, 17.0625, 15.625],
        "meta_sums": [-0.34177, 1.94687],
        "archive_sum": -0.41252,
        "meta_indices": [1, 1, 1],
    },
    "NSRA_ES": {
        "reward_means": [18.0, 16.5625, 15.5625],
        "meta_sums": [-0.34177, 2.07359],
        "archive_sum": -0.40019,
        "meta_indices": [1, 1, 1],
    },
    "ES_obsnorm": {
        "reward_means": [15.75, 17.3125, 10.0625],
        "params_sum": -0.39158,
        "obs_count": 45.0,
        "obs_mean_sum": -0.03055,
    },
    "ES_recurrent": {"reward_means": [9.3125, 9.4375, 9.25],
                     "params_sum": -5.22087},
    "ES_lowrank": {"reward_means": [17.6875, 16.0625, 23.0],
                   "params_sum": -0.51577},
    "ES_recurrent_lowrank": {"reward_means": [9.375, 9.3125, 9.25],
                             "params_sum": -4.84677},
}

_GOLDENS_BY_JAX = {"0.4": GOLDENS_JAX04}
GOLDENS = _GOLDENS_BY_JAX.get(
    ".".join(jax.__version__.split(".")[:2]), GOLDENS_ROUND5)

CLASSES = {"ES": ES, "ES_decomposed": ES, "NS_ES": NS_ES, "NSR_ES": NSR_ES,
           "NSRA_ES": NSRA_ES, "ES_obsnorm": ES, "ES_recurrent": ES,
           "ES_lowrank": ES, "ES_recurrent_lowrank": ES}
EXTRA = {
    "ES": {},
    "ES_decomposed": {"decomposed": True},
    "NS_ES": {"meta_population_size": 2, "k": 3},
    "NSR_ES": {"meta_population_size": 2, "k": 3},
    "NSRA_ES": {"meta_population_size": 2, "k": 3, "weight": 0.7},
    "ES_obsnorm": {"obs_norm": True},
    "ES_recurrent": {},
    "ES_lowrank": {"low_rank": 1},
    "ES_recurrent_lowrank": {"low_rank": 1},
}


def _run(name):
    from estorch_tpu import RecurrentPolicy

    recurrent = name.startswith("ES_recurrent")
    policy = RecurrentPolicy if recurrent else MLPPolicy
    pk = (
        {"action_dim": 2, "hidden": (8,), "gru_size": 8}
        if recurrent
        else {"action_dim": 2, "hidden": (8,)}
    )
    es = CLASSES[name](
        policy=policy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=16,
        sigma=0.1,
        seed=7,
        policy_kwargs=pk,
        agent_kwargs={"env": CartPole(), "horizon": 50},
        optimizer_kwargs={"learning_rate": 1e-2},
        table_size=1 << 15,
        **EXTRA[name],
    )
    es.train(3, verbose=False)
    return es


@pytest.mark.parametrize("name", sorted(GOLDENS))
def test_golden(name):
    es = _run(name)
    g = GOLDENS[name]
    got_means = [round(r["reward_mean"], 4) for r in es.history]
    assert got_means == g["reward_means"], f"{name} reward trajectory changed"
    if name.startswith("ES"):
        got = round(float(np.asarray(es.state.params_flat).sum()), 5)
        np.testing.assert_allclose(got, g["params_sum"], atol=2e-4)
        if "obs_count" in g:
            assert float(es.state.obs_stats[0]) == g["obs_count"]
            got_ms = round(float(np.asarray(es.state.obs_stats[1]).sum()), 5)
            np.testing.assert_allclose(got_ms, g["obs_mean_sum"], atol=2e-4)
    else:
        got_sums = [
            round(float(np.asarray(s.params_flat).sum()), 5) for s in es.meta_states
        ]
        np.testing.assert_allclose(got_sums, g["meta_sums"], atol=2e-4)
        np.testing.assert_allclose(
            round(float(es.archive.bcs.sum()), 5), g["archive_sum"], atol=2e-4
        )
        assert [r["meta_index"] for r in es.history] == g["meta_indices"]