"""estorch-parity API surface tests (SURVEY.md Appendix A)."""

import jax
import numpy as np
import optax
import pytest

from estorch_tpu import ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole, Pendulum


def _make_es(**over):
    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=32,
        sigma=0.1,
        seed=0,
        policy_kwargs={"action_dim": 2, "hidden": (16,)},
        agent_kwargs={"env": CartPole(), "horizon": 100},
        optimizer_kwargs={"learning_rate": 3e-2},
        table_size=1 << 17,
    )
    kw.update(over)
    return ES(**kw)


class TestESAPI:
    def test_constructor_mirrors_reference_signature(self):
        es = _make_es()
        assert es.population_size == 32
        assert es.sigma == 0.1

    def test_train_returns_self_and_logs(self):
        es = _make_es()
        out = es.train(2, verbose=False)
        assert out is es
        assert len(es.history) == 2
        rec = es.history[0]
        for k in ("generation", "reward_max", "reward_mean", "reward_min",
                  "best_reward", "env_steps", "env_steps_per_sec", "grad_norm"):
            assert k in rec, k

    def test_policy_and_best_policy_exposed(self):
        es = _make_es()
        es.train(3, verbose=False)
        p = es.policy
        assert "dense_0" in p  # flax param tree
        assert es.best_reward > -np.inf
        bp = es.best_policy
        assert jax.tree_util.tree_structure(p) == jax.tree_util.tree_structure(bp)

    def test_best_reward_monotone(self):
        es = _make_es()
        bests = []
        for _ in range(3):
            es.train(1, verbose=False)
            bests.append(es.best_reward)
        assert bests == sorted(bests)

    def test_predict(self):
        es = _make_es()
        out = es.predict(np.zeros(4, dtype=np.float32))
        assert out.shape == (2,)
        out_best = es.predict(np.zeros(4, dtype=np.float32), use_best=True)
        assert out_best.shape == (2,)

    def test_continuous_env(self):
        es = _make_es(
            policy_kwargs={"action_dim": 1, "hidden": (16,), "discrete": False,
                           "action_scale": 2.0},
            agent_kwargs={"env": Pendulum(), "horizon": 50},
        )
        es.train(2, verbose=False)
        assert len(es.history) == 2
        # pendulum rewards are negative costs
        assert es.history[0]["reward_max"] <= 0.0

    def test_n_proc_accepted_for_parity(self):
        es = _make_es()
        es.train(1, n_proc=4, verbose=False)  # must not raise
        assert len(es.history) == 1

    def test_optimizer_instance_accepted(self):
        es = _make_es(optimizer=optax.sgd(1e-2), optimizer_kwargs={})
        es.train(1, verbose=False)
        assert len(es.history) == 1

    def test_log_fn_hook(self):
        seen = []
        es = _make_es()
        es.train(2, log_fn=seen.append)
        assert len(seen) == 2

    def test_evaluate_policy(self):
        es = _make_es()
        es.train(3, verbose=False)
        out = es.evaluate_policy(n_episodes=6)
        assert out["episodes"] == 6
        assert out["min"] <= out["mean"] <= out["max"]
        assert out["std"] >= 0.0
        out_best = es.evaluate_policy(n_episodes=4, use_best=True)
        assert out_best["episodes"] == 4


class TestVBN:
    def test_vbn_policy_trains_and_stats_frozen(self):
        es = _make_es(
            policy_kwargs={"action_dim": 2, "hidden": (16,), "use_vbn": True},
        )
        stats_before = jax.tree_util.tree_map(
            np.asarray, es._frozen["vbn_stats"]
        )
        es.train(2, verbose=False)
        stats_after = jax.tree_util.tree_map(np.asarray, es._frozen["vbn_stats"])
        for a, b in zip(
            jax.tree_util.tree_leaves(stats_before),
            jax.tree_util.tree_leaves(stats_after),
        ):
            np.testing.assert_array_equal(a, b)

    def test_vbn_stats_not_in_perturbed_params(self):
        es = _make_es(
            policy_kwargs={"action_dim": 2, "hidden": (16,), "use_vbn": True},
        )
        # the ES parameter vector must contain ONLY the 'params' collection:
        # scale/bias (affine) are learned, mean/var (stats) are not
        flat_names = jax.tree_util.tree_leaves_with_path(es.policy)
        names = ["/".join(str(p) for p in path) for path, _ in flat_names]
        assert not any("mean" in n or "var" in n for n in names)
        assert any("vbn_0" in n for n in names)  # affine present


@pytest.mark.slow
def test_evaluate_policy_return_details():
    """return_details adds per-episode rewards and (device path) BCs —
    the public surface locomotion studies use for displacement metrics."""
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import CartPole

    es = ES(
        policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
        population_size=16, sigma=0.1,
        policy_kwargs={"action_dim": 2, "hidden": (8,), "discrete": True},
        agent_kwargs={"env": CartPole(), "horizon": 32},
        optimizer_kwargs={"learning_rate": 1e-2}, seed=0,
    )
    es.train(1, verbose=False)
    ev = es.evaluate_policy(n_episodes=4, return_details=True)
    assert ev["rewards"].shape == (4,)
    assert ev["bc"].shape == (4, 2)
    assert ev["mean"] == pytest.approx(float(ev["rewards"].mean()))
    # default stays detail-free
    assert "rewards" not in es.evaluate_policy(n_episodes=2)


@pytest.mark.slow
def test_evaluate_policy_pooled_batched():
    """Pooled-path evaluate_policy runs every episode through ONE pooled
    pass (round-3 VERDICT weak #6), is seed-deterministic, returns
    per-episode BCs, and leaves the training obs stats untouched."""
    import optax

    from estorch_tpu import ES, MLPPolicy, PooledAgent

    es = ES(
        policy=MLPPolicy, agent=PooledAgent, optimizer=optax.adam,
        population_size=16, sigma=0.1,
        policy_kwargs={"action_dim": 2, "hidden": (8,), "discrete": True},
        agent_kwargs={"env_name": "cartpole", "horizon": 32},
        optimizer_kwargs={"learning_rate": 1e-2}, seed=0, obs_norm=True,
    )
    es.train(1, verbose=False)
    stats_before = [np.asarray(s).copy() for s in es.state.obs_stats]
    ev = es.evaluate_policy(n_episodes=5, seed=3, return_details=True)
    assert ev["episodes"] == 5 and ev["rewards"].shape == (5,)
    assert ev["bc"].shape == (5, 4)  # final observation = BC
    assert np.isfinite(ev["rewards"]).all()
    # same seed → same episode set; different seed → (almost surely) not
    ev2 = es.evaluate_policy(n_episodes=5, seed=3, return_details=True)
    np.testing.assert_array_equal(ev["rewards"], ev2["rewards"])
    # held-out evaluation must not feed the running stats
    for a, b in zip(stats_before, es.state.obs_stats):
        np.testing.assert_array_equal(a, np.asarray(b))
    es.engine.pool.close()
    es.engine.center_pool.close()
