"""Recurrent-policy support (device path): carry threading through the
compiled rollout scan, learning on a memory probe, option guards.

The reference has no recurrent machinery — its user-owned
``agent.rollout`` loop (SURVEY.md §3.3) lets torch users thread hidden
state by hand.  Here the episode loop is a compiled ``lax.scan``
(envs/rollout.py), so the framework threads the carry; these tests pin
that contract end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from estorch_tpu import ES, JaxAgent, MLPPolicy, RecurrentPolicy
from estorch_tpu.envs import RecallEnv
from estorch_tpu.envs.rollout import make_rollout


def _make_es(policy, pk, **over):
    kw = dict(
        policy=policy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=128,
        sigma=0.1,
        policy_kwargs=pk,
        agent_kwargs={"env": RecallEnv(), "horizon": 16},
        optimizer_kwargs={"learning_rate": 5e-2},
        seed=0,
    )
    kw.update(over)
    return ES(**kw)


RECURRENT_PK = {"action_dim": 1, "hidden": (8,), "gru_size": 8,
                "discrete": False}


class TestRecurrentPolicyModule:
    def test_apply_returns_out_and_carry(self):
        mod = RecurrentPolicy(**RECURRENT_PK)
        obs = jnp.zeros((1,))
        h0 = mod.carry_init()
        assert h0.shape == (8,)
        variables = mod.init(jax.random.PRNGKey(0), obs, h0)
        out, h1 = mod.apply(variables, obs, h0)
        assert out.shape == (1,)
        assert h1.shape == (8,)

    def test_carry_accumulates_history(self):
        """Identical observations at t>0 must still produce different
        outputs when the histories differ — that is what the carry is for."""
        mod = RecurrentPolicy(**RECURRENT_PK)
        h0 = mod.carry_init()
        variables = mod.init(jax.random.PRNGKey(0), jnp.zeros((1,)), h0)
        _, h_pos = mod.apply(variables, jnp.ones((1,)), h0)
        _, h_neg = mod.apply(variables, -jnp.ones((1,)), h0)
        zero = jnp.zeros((1,))
        out_pos, _ = mod.apply(variables, zero, h_pos)
        out_neg, _ = mod.apply(variables, zero, h_neg)
        assert not np.allclose(np.asarray(out_pos), np.asarray(out_neg))


class TestCarryThreading:
    def test_rollout_threads_and_resets_carry(self):
        """A hand-built 'policy' whose carry counts its own invocations:
        after a horizon-H rollout the count must be H (threading), and a
        second rollout must start from 0 again (reset per episode)."""
        env = RecallEnv()
        seen = {}

        def policy_apply(params, obs, h):
            seen["h"] = h
            return jnp.zeros((1,)), h + 1.0

        # deliberately the LEGACY zero-arg form: make_rollout must keep
        # accepting it (inspect-based detection in envs/rollout.py)
        rollout = make_rollout(env, policy_apply, horizon=5,
                               carry_init=lambda: jnp.zeros(()))
        res = rollout({}, jax.random.PRNGKey(0))
        assert int(res.steps) == 5
        # trace-time check: the carry entered the scan as the carry slot
        assert seen["h"].shape == ()

        # the carry VALUE is observable through the action: emit h as the
        # action, reward = clip(h)*sign -> with sign=+1 total = 0+1+1+1+1
        # (h clips at 1 from step 2 on)
        def emit_h(params, obs, h):
            return h[None], h + 1.0

        rollout2 = make_rollout(env, emit_h, horizon=5,
                                carry_init=lambda params=None: jnp.zeros(()))
        for key in range(4):
            res2 = rollout2({}, jax.random.PRNGKey(key))
            sign = float(env.reset(jax.random.PRNGKey(key))[0][0])
            assert float(res2.total_reward) == pytest.approx(4.0 * sign)


class TestRecurrentTraining:
    @pytest.mark.slow
    def test_learns_memory_task_where_memoryless_cannot(self):
        """RecallEnv: the ±1 signal is visible only at t=0; reward is
        action*signal each step.  Memoryless expected return caps at ~1
        (the first step); the recurrent policy must blow through that."""
        # pop 256 / 80 gens: converges to the ceiling (16.0) on seeds 0-2;
        # pop 128 / 60 gens was measured NOT enough (stalls ~3)
        es = _make_es(RecurrentPolicy, RECURRENT_PK, population_size=256)
        es.train(80, verbose=False)
        ev = es.evaluate_policy(n_episodes=64, seed=9)
        assert ev["mean"] > 8.0, f"recurrent policy failed to learn: {ev}"

        base = _make_es(MLPPolicy,
                        {"action_dim": 1, "hidden": (8, 8), "discrete": False})
        base.train(60, verbose=False)
        ev0 = base.evaluate_policy(n_episodes=64, seed=9)
        assert ev0["mean"] < 4.0, f"memoryless should cap near 1: {ev0}"

    @pytest.mark.slow
    def test_bf16_recurrent_runs_and_learns(self):
        es = _make_es(RecurrentPolicy, RECURRENT_PK,
                      compute_dtype="bfloat16")
        es.train(25, verbose=False)
        assert es.history[-1]["reward_mean"] > es.history[0]["reward_mean"]

    def test_bf16_legacy_zero_arg_carry_init(self):
        """ADVICE regression: the engine's bf16 carry wrapper used to call
        ``base_carry_init(params)`` unconditionally, so a legacy zero-arg
        ``carry_init`` worked in f32 but raised TypeError under
        compute_dtype='bfloat16'.  It must run (and cast the carry) in
        both dtypes."""
        import optax as _optax

        from estorch_tpu.envs import CartPole
        from estorch_tpu.ops import make_noise_table, make_param_spec
        from estorch_tpu.parallel import (EngineConfig, ESEngine,
                                          single_device_mesh)

        def init_params(key):
            return {
                "w": jax.random.normal(key, (4, 8)) * 0.5,
                "wo": jnp.zeros((8, 2)),
            }

        def apply(params, obs, h):
            h_new = jnp.tanh(obs @ params["w"] + h)
            return h_new @ params["wo"], h_new

        flat, spec = make_param_spec(init_params(jax.random.PRNGKey(0)))
        for dtype in ("float32", "bfloat16"):
            eng = ESEngine(
                CartPole(), apply, spec, make_noise_table(1 << 16, seed=0),
                _optax.sgd(1e-2),
                EngineConfig(population_size=8, sigma=0.1, horizon=10,
                             compute_dtype=dtype),
                single_device_mesh(),
                carry_init=lambda: jnp.zeros((8,)),  # legacy zero-arg form
            )
            state = eng.init_state(flat, jax.random.PRNGKey(1))
            state, metrics = eng.generation_step(state)
            assert np.isfinite(float(np.asarray(metrics["fitness"]).mean()))

    @pytest.mark.slow
    def test_mirrored_off_and_episodes_per_member(self):
        es = _make_es(RecurrentPolicy, RECURRENT_PK, mirrored=False,
                      episodes_per_member=2, population_size=64)
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])


class TestRecurrentGuards:
    def test_decomposed_rejected(self):
        with pytest.raises(ValueError, match="decomposed"):
            _make_es(RecurrentPolicy, RECURRENT_PK, decomposed=True)

    def test_streamed_rejected(self):
        with pytest.raises(ValueError, match="streamed|recurrent"):
            _make_es(RecurrentPolicy, RECURRENT_PK, streamed=True)


class TestRecurrentLowRank:
    """Recurrent × low_rank (round-4 verdict next #7): factored noise over
    the whole recurrent tree — trunk, cell gates, head — with per-episode
    materialization (ops/lowrank.py tree form)."""

    def test_tree_spec_factors_cell_kernels(self):
        es = _make_es(RecurrentPolicy, RECURRENT_PK, low_rank=1)
        spec = es.engine.lr_spec
        assert hasattr(spec, "treedef")
        # every 2-D kernel where rank-1 saves must be factored — the GRU
        # gate kernels included (the whole point of the recurrent form)
        assert len(spec.lr_leaves) >= 6  # trunk + 6 gru gates + head, minus
        # any no-saving shapes
        assert spec.noise_dim < es.engine.spec.dim  # the O(dim) state shrank

    @pytest.mark.slow
    def test_trains_and_split_equals_fused(self):
        from estorch_tpu.utils.fault import rank_weights_with_failures

        es = _make_es(RecurrentPolicy, RECURRENT_PK, low_rank=1,
                      population_size=32)
        ev = es.engine.evaluate(es.state)
        w = rank_weights_with_failures(np.asarray(ev.fitness))
        split_state, _ = es.engine.apply_weights(es.state, w)

        es2 = _make_es(RecurrentPolicy, RECURRENT_PK, low_rank=1,
                       population_size=32)
        fused_state, _ = es2.engine.generation_step(es2.state)
        np.testing.assert_array_equal(
            np.asarray(split_state.params_flat),
            np.asarray(fused_state.params_flat),
        )

    def test_member_params_match_evaluated_member(self):
        """member_params(i) must rebuild exactly the θ_i the rollout saw."""
        es = _make_es(RecurrentPolicy, RECURRENT_PK, low_rank=1,
                      population_size=16)
        res = es.engine.evaluate(es.state)
        fitness = np.asarray(res.fitness)
        i = int(np.argmax(fitness))
        theta = es.engine.member_params(es.state, i)

        okey, rkey = jax.random.fold_in(
            jax.random.fold_in(es.state.key, es.state.generation), 0
        ), jax.random.fold_in(
            jax.random.fold_in(es.state.key, es.state.generation), 1
        )
        pair_keys = jax.random.split(rkey, 8)
        key_i = jnp.repeat(pair_keys, 2, axis=0)[i]
        rollout = make_rollout(es.env, es._policy_apply, 16,
                               carry_init=es.module.carry_init)
        res_i = rollout(es._spec.unravel(theta), key_i)
        assert float(res_i.total_reward) == pytest.approx(
            fitness[i], abs=1e-4
        )

    @pytest.mark.slow
    def test_lstm_low_rank_trains(self):
        pk = dict(RECURRENT_PK, cell="lstm")
        es = _make_es(RecurrentPolicy, pk, low_rank=1, population_size=32)
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])

    @pytest.mark.slow
    def test_bf16_runs(self):
        es = _make_es(RecurrentPolicy, RECURRENT_PK, low_rank=1,
                      population_size=32, compute_dtype="bfloat16")
        es.train(1, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])


class TestRecurrentPooled:
    """The pooled path threads the carry host-side across the generation's
    env-step loop (parallel/pooled.py) — one stacked (population, …) carry
    updated by the same batched forward that computes actions."""

    def _pooled_es(self, **over):
        from estorch_tpu import PooledAgent

        kw = dict(
            policy=RecurrentPolicy,
            agent=PooledAgent,
            optimizer=optax.adam,
            population_size=16,
            sigma=0.1,
            policy_kwargs={"action_dim": 2, "hidden": (8,), "gru_size": 8,
                           "discrete": True},
            agent_kwargs={"env_name": "cartpole", "horizon": 32},
            optimizer_kwargs={"learning_rate": 1e-2},
            seed=0,
        )
        kw.update(over)
        return ES(**kw)

    @pytest.mark.slow
    def test_trains_and_is_finite(self):
        es = self._pooled_es()
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])
        ev = es.evaluate_policy(n_episodes=2)
        assert np.isfinite(ev["mean"])

    def test_carry_changes_actions(self):
        """Same observation, different carries -> different policy output:
        the carry genuinely reaches the pooled batched forward."""
        es = self._pooled_es()
        eng = es.engine
        assert eng.recurrent
        pair_offs = eng.core.all_pair_offsets(es.state)
        thetas = eng._materialize(es.state.params_flat, es.state.sigma,
                                  pair_offs)
        obs = jnp.ones((16, 4))
        h0 = eng._carries(16)
        _, h1 = eng._batch_actions(thetas, obs, h0)
        # after one distinct step the carries must differ from start
        assert not np.allclose(np.asarray(h1), np.asarray(h0))
        # logits path: argmax may coincide, so compare carries after a
        # second step from the two different carry states
        _, h2a = eng._batch_actions(thetas, obs, h1)
        _, h2b = eng._batch_actions(thetas, obs, h0)
        assert not np.allclose(np.asarray(h2a), np.asarray(h2b))

    def test_double_buffer_runs(self):
        es_a = self._pooled_es()
        es_b = self._pooled_es(
            agent_kwargs={"env_name": "cartpole", "horizon": 32,
                          "double_buffer": True},
        )
        ra = es_a.engine.evaluate(es_a.state)
        rb = es_b.engine.evaluate(es_b.state)
        assert ra.fitness.shape == rb.fitness.shape
        assert np.isfinite(ra.fitness).all() and np.isfinite(rb.fitness).all()


class TestRecurrentPredict:
    def test_predict_carry_roundtrip(self):
        es = _make_es(RecurrentPolicy, RECURRENT_PK)
        out, h = es.predict(jnp.ones((1,)))
        assert out.shape == (1,) and h.shape == (8,)
        out2, h2 = es.predict(jnp.zeros((1,)), carry=h)
        assert h2.shape == (8,)

    def test_predict_zero_arg_carry_init_module(self):
        """ADVICE regression: predict() used to call
        ``self.module.carry_init(p)`` unconditionally; a custom recurrent
        module with the historical zero-arg ``carry_init()`` worked in
        the rollout path but broke in predict.  Both paths share the
        compat contract now."""

        class LegacyCarryPolicy(RecurrentPolicy):
            def carry_init(self):  # historical zero-arg form
                return super().carry_init(None)

        es = _make_es(LegacyCarryPolicy, RECURRENT_PK, population_size=32)
        out, h = es.predict(jnp.ones((1,)))
        assert out.shape == (1,) and h.shape == (8,)
        es.train(1, verbose=False)  # rollout path agrees
        assert np.isfinite(es.history[-1]["reward_mean"])


class TestLSTMCore:
    @pytest.mark.slow
    def test_lstm_carry_is_tuple_and_trains(self):
        pk = {**RECURRENT_PK, "cell": "lstm"}
        mod = RecurrentPolicy(**pk)
        c0 = mod.carry_init()
        assert isinstance(c0, tuple) and len(c0) == 2
        es = _make_es(RecurrentPolicy, pk, population_size=64)
        es.train(3, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])

    @pytest.mark.slow
    def test_lstm_learns_memory_task(self):
        pk = {**RECURRENT_PK, "cell": "lstm"}
        es = _make_es(RecurrentPolicy, pk, population_size=256)
        es.train(80, verbose=False)
        ev = es.evaluate_policy(n_episodes=64, seed=9)
        assert ev["mean"] > 8.0, f"LSTM policy failed to learn: {ev}"

    def test_bad_cell_rejected(self):
        with pytest.raises(ValueError, match="cell"):
            _make_es(RecurrentPolicy, {**RECURRENT_PK, "cell": "rnn"})

    @pytest.mark.slow
    def test_lstm_bf16_runs(self):
        pk = {**RECURRENT_PK, "cell": "lstm"}
        es = _make_es(RecurrentPolicy, pk, population_size=32,
                      compute_dtype="bfloat16")
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])


class TestRecurrentVision:
    """RecurrentNatureCNN on the pooled pixel-pong path: conv trunk + GRU
    memory over real 84×84 observations."""

    def test_shapes_and_carry(self):
        from estorch_tpu import RecurrentNatureCNN

        mod = RecurrentNatureCNN(action_dim=3, gru_size=32)
        obs = jnp.zeros((84, 84, 1), jnp.float32)
        h0 = mod.carry_init()
        assert h0.shape == (32,)
        variables = mod.init(jax.random.PRNGKey(0), obs, h0)
        out, h1 = mod.apply(variables, obs, h0)
        assert out.shape == (3,) and h1.shape == (32,)

    @pytest.mark.slow
    def test_pooled_pong_trains(self):
        from estorch_tpu import PooledAgent, RecurrentNatureCNN

        es = ES(
            policy=RecurrentNatureCNN,
            agent=PooledAgent,
            optimizer=optax.adam,
            population_size=16,
            sigma=0.05,
            policy_kwargs={"action_dim": 3, "gru_size": 32},
            agent_kwargs={"env_name": "pong84", "horizon": 48},
            optimizer_kwargs={"learning_rate": 1e-2},
            seed=0,
        )
        es.train(1, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])
        assert es.engine.recurrent


class TestStackedAndLearnedCarry:
    """Round-5 ROADMAP item 6: stacked recurrent cells and a LEARNED
    episode-start carry.  ``carry0_*`` are ordinary params — perturbed by
    ES noise, moved by the update — and ``carry_init(params)`` reads the
    member's values at episode start (envs/rollout.py passes the member's
    perturbed tree).  The reference has no recurrent machinery at all
    (SURVEY.md §3.3), so both are beyond-parity extensions."""

    def test_stacked_carry_structure(self):
        for cell in ("gru", "lstm"):
            pk = dict(RECURRENT_PK, cell=cell, n_layers=2)
            mod = RecurrentPolicy(**pk)
            h0 = mod.carry_init()
            assert isinstance(h0, tuple) and len(h0) == 2
            obs = jnp.zeros((1,))
            v = mod.init(jax.random.PRNGKey(0), obs, h0)
            _, h1 = mod.apply(v, obs, h0)
            assert (jax.tree_util.tree_structure(h1)
                    == jax.tree_util.tree_structure(h0))
            # layer 0 keeps the historic single-layer submodule name (so
            # existing checkpoints/goldens stay valid); layer 1 is suffixed
            assert cell in v["params"] and f"{cell}_1" in v["params"]

    @pytest.mark.slow
    def test_stacked_trains(self):
        es = _make_es(RecurrentPolicy, dict(RECURRENT_PK, n_layers=2),
                      population_size=32)
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])

    def test_learned_carry_params_exist_and_are_read(self):
        mod = RecurrentPolicy(**dict(RECURRENT_PK, learned_carry=True))
        obs = jnp.zeros((1,))
        v = mod.init(jax.random.PRNGKey(0), obs, mod.carry_init())
        assert "carry0_0" in v["params"]
        p = dict(v["params"])
        p["carry0_0"] = jnp.full((8,), 0.5)
        np.testing.assert_array_equal(np.asarray(mod.carry_init(p)),
                                      np.full((8,), 0.5))
        # variables-dict form and the zero-arg shape donor both work
        np.testing.assert_array_equal(np.asarray(mod.carry_init({"params": p})),
                                      np.full((8,), 0.5))
        assert np.all(np.asarray(mod.carry_init()) == 0)

    @pytest.mark.slow
    def test_learned_carry_trains_and_moves(self):
        es = _make_es(RecurrentPolicy,
                      dict(RECURRENT_PK, learned_carry=True),
                      population_size=64)
        c0 = np.asarray(
            es._spec.unravel(es.state.params_flat)["carry0_0"]).copy()
        es.train(3, verbose=False)
        c1 = np.asarray(es._spec.unravel(es.state.params_flat)["carry0_0"])
        assert np.isfinite(es.history[-1]["reward_mean"])
        # the learned carry is a real parameter: the update moved it
        assert not np.allclose(c0, c1)

    @pytest.mark.slow
    def test_learned_carry_split_equals_fused(self):
        from estorch_tpu.utils.fault import rank_weights_with_failures

        pk = dict(RECURRENT_PK, learned_carry=True)
        es = _make_es(RecurrentPolicy, pk, population_size=32)
        ev = es.engine.evaluate(es.state)
        w = rank_weights_with_failures(np.asarray(ev.fitness))
        split_state, _ = es.engine.apply_weights(es.state, w)
        es2 = _make_es(RecurrentPolicy, pk, population_size=32)
        fused_state, _ = es2.engine.generation_step(es2.state)
        np.testing.assert_array_equal(np.asarray(split_state.params_flat),
                                      np.asarray(fused_state.params_flat))

    @pytest.mark.slow
    def test_learned_carry_low_rank_is_dense_leaf(self):
        es = _make_es(RecurrentPolicy,
                      dict(RECURRENT_PK, learned_carry=True),
                      low_rank=1, population_size=32)
        # identify carry0_0's leaf INDEX (shape alone would collide with
        # same-shaped biases) and assert that exact leaf gets dense noise
        tree = es._spec.unravel(es.state.params_flat)
        paths = jax.tree_util.tree_flatten_with_path(tree)[0]
        carry_idx = [i for i, (path, _) in enumerate(paths)
                     if any(getattr(k, "key", None) == "carry0_0"
                            for k in path)]
        assert len(carry_idx) == 1
        dense_idx = {i for i, _, _, _ in es.engine.lr_spec.dense_leaves}
        assert carry_idx[0] in dense_idx  # exact dense noise, never dropped
        es.train(1, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])

    @pytest.mark.slow
    def test_lstm_stacked_learned_bf16_trains(self):
        pk = dict(RECURRENT_PK, cell="lstm", n_layers=2, learned_carry=True)
        es = _make_es(RecurrentPolicy, pk, population_size=32,
                      compute_dtype="bfloat16")
        es.train(1, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])

    def test_pooled_rejects_learned_carry(self):
        from estorch_tpu import PooledAgent

        with pytest.raises(ValueError, match="learned_carry"):
            ES(
                policy=RecurrentPolicy,
                agent=PooledAgent,
                optimizer=optax.adam,
                population_size=8,
                sigma=0.1,
                policy_kwargs={"action_dim": 2, "hidden": (8,),
                               "gru_size": 8, "discrete": True,
                               "learned_carry": True},
                agent_kwargs={"env_name": "cartpole", "horizon": 8},
                optimizer_kwargs={"learning_rate": 1e-2},
                seed=0,
            )

    @pytest.mark.slow
    def test_learned_carry_composes_with_obs_norm(self):
        """obs_norm packs the rollout's params as (tree, obs_stats); the
        engine's carry_init wrapper must read the learned carry from the
        PARAMS half (parallel/engine.py rollout_carry_init)."""
        es = _make_es(RecurrentPolicy,
                      dict(RECURRENT_PK, learned_carry=True),
                      population_size=32, obs_norm=True)
        es.train(2, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])
