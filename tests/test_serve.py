"""Serving vertical (estorch_tpu/serve, docs/serving.md).

The headline contract under test is BIT-EXACTNESS end to end: an
exported bundle — loaded in a fresh process, served through the dynamic
micro-batcher over HTTP, coalesced with unrelated concurrent requests —
must answer with the SAME float32 bits the exporting run's
``ES.predict`` computes.  Plus the artifact hygiene around it
(atomic commit, corruption rejection), the batcher's bucket/backpressure
mechanics, and THE acceptance demo: a trained pendulum policy served to
concurrent clients at ≥3x the batch-size-1 throughput with a clean
SIGTERM drain.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import flax.linen as flax_nn
import jax
import numpy as np
import optax
import pytest

from estorch_tpu import ES, JaxAgent, MLPPolicy, RecurrentPolicy
from estorch_tpu.envs import RecallEnv
from estorch_tpu.envs.pendulum import Pendulum
from estorch_tpu.obs.spans import Telemetry
from estorch_tpu.serve import (BatcherClosed, BatcherSaturated, Bundle,
                               BundleError, DynamicBatcher, ServeClient,
                               ServeError, bucket_sizes, export_bundle,
                               load_bundle, validate_bundle)
from estorch_tpu.serve.batcher import verify_stable_buckets

SMALL_PK = {"action_dim": 1, "hidden": (24, 24), "discrete": False,
            "action_scale": 2.0}


def _make_small_es(**over):
    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=8,
        sigma=0.05,
        policy_kwargs=dict(SMALL_PK),
        agent_kwargs={"env": Pendulum(), "horizon": 20},
        optimizer_kwargs={"learning_rate": 1e-2},
        seed=0,
        table_size=1 << 14,
        obs_norm=True,
        device=jax.devices()[0],
    )
    kw.update(over)
    return ES(**kw)


@pytest.fixture(scope="module")
def small_es():
    es = _make_small_es()
    es.train(1, verbose=False)
    return es


@pytest.fixture(scope="module")
def small_bundle(small_es, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("bundles") / "pendulum")
    small_es.export_bundle(path, version="test-v1")
    return path


# =====================================================================
# serving-parity predict (serve/predictor.py wired into ES.predict)
# =====================================================================

class TestPredictParity:
    def test_jitted_predict_matches_eager_composition(self, small_es):
        """ES.predict now runs the shared jitted serving program.  For a
        plain policy that is bit-identical to the eager apply it replaced
        (the batch-1 GEMV family is jit/eager-stable); with obs_norm the
        jit FUSES normalize into the forward and may differ in the last
        ulp — numerically equivalent, and the serving stack inherits
        exactly the jitted value (the bit contract that matters, pinned
        by the bundle tests below)."""
        from estorch_tpu.parallel.engine import normalize_obs

        obs = np.random.default_rng(0).standard_normal(3).astype(np.float32)
        got = np.asarray(small_es.predict(obs))
        import jax.numpy as jnp

        norm = normalize_obs(jnp.asarray(obs), small_es.state.obs_stats,
                             small_es._obs_clip)
        want = np.asarray(small_es._policy_apply(small_es.policy, norm))
        np.testing.assert_allclose(got, want, rtol=1e-6)

        es = _make_small_es(obs_norm=False)  # untrained center is fine
        got = np.asarray(es.predict(obs))
        want = np.asarray(es._policy_apply(es.policy, jnp.asarray(obs)))
        assert got.tobytes() == want.tobytes()

    def test_predict_accepts_batched_obs(self, small_es):
        obs = np.random.default_rng(1).standard_normal((5, 3)).astype(
            np.float32)
        out = np.asarray(small_es.predict(obs))
        assert out.shape == (5, 1)


# =====================================================================
# bundle round trip (satellite: export → load → bit-equal predict)
# =====================================================================

class TestBundleRoundTrip:
    def test_manifest_is_self_describing(self, small_bundle):
        man = validate_bundle(small_bundle)
        assert man["version"] == "test-v1"
        assert man["module"]["import"].endswith(":MLPPolicy")
        assert man["obs_shape"] == [3]
        assert man["obs_norm"] is True
        assert man["source"]["algorithm"] == "ES"
        assert man["source"]["generation"] == 1
        # the regression-hunt facts ride along (obs/manifest.py)
        assert "jax" in man["runtime"]
        assert "git_sha" in man["runtime"]

    def test_predict_bit_equal_single_and_batch(self, small_es,
                                                small_bundle):
        b = load_bundle(small_bundle)
        rng = np.random.default_rng(2)
        one = rng.standard_normal(3).astype(np.float32)
        batch = rng.standard_normal((6, 3)).astype(np.float32)
        assert (np.asarray(b.predict(one)).tobytes()
                == np.asarray(small_es.predict(one)).tobytes())
        assert (np.asarray(b.predict(batch)).tobytes()
                == np.asarray(small_es.predict(batch)).tobytes())

    def test_batched_fn_matches_es_predict_at_same_shape(self, small_es,
                                                         small_bundle):
        """The link that anchors served bits to ES.predict: at one batch
        shape, the serving program (jit·vmap) and ES.predict's direct
        jitted apply agree bit-for-bit.  Combined with the batcher's
        bucket-vs-anchor verification, every served response chains back
        to an ES.predict value (docs/serving.md)."""
        b = load_bundle(small_bundle)
        fn = b.batched_predict_fn()
        batch = np.random.default_rng(9).standard_normal((8, 3)).astype(
            np.float32)
        assert (fn(batch).tobytes()
                == np.asarray(small_es.predict(batch)).tobytes())

    def test_use_best_snapshot_roundtrip(self, small_es, small_bundle,
                                         tmp_path):
        path = str(tmp_path / "best")
        small_es.export_bundle(path, use_best=True)
        b = load_bundle(path)
        obs = np.random.default_rng(3).standard_normal(3).astype(np.float32)
        assert (np.asarray(b.predict(obs)).tobytes()
                == np.asarray(small_es.predict(obs,
                                               use_best=True)).tobytes())

    @pytest.mark.slow  # fresh interpreter: ~15s of import/compile; the
    # non-slow serving demo exercises the same cross-process contract
    # end-to-end through the server
    def test_fresh_process_bit_equal(self, small_es, small_bundle,
                                     tmp_path):
        """THE bundle contract: a process that never saw the ES — only
        the artifact — reproduces es.predict bit for bit.  The fresh
        process pins the same host compute configuration (8 virtual CPU
        devices, matching conftest) because bit-parity is only promised
        within one configuration (docs/serving.md)."""
        rng = np.random.default_rng(4)
        obs = rng.standard_normal((8, 3)).astype(np.float32)
        np.save(tmp_path / "obs.npy", obs)
        script = (
            "import sys, numpy as np\n"
            "from estorch_tpu.utils import force_cpu_backend\n"
            "force_cpu_backend(8)\n"
            "from estorch_tpu.serve import load_bundle\n"
            "b = load_bundle(sys.argv[1])\n"
            "obs = np.load(sys.argv[2])\n"
            "batch = np.asarray(b.predict(obs))\n"
            "single = np.asarray(b.predict(obs[0]))\n"
            "print(batch.tobytes().hex())\n"
            "print(single.tobytes().hex())\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", script, small_bundle,
             str(tmp_path / "obs.npy")],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert r.returncode == 0, r.stderr[-2000:]
        batch_hex, single_hex = r.stdout.strip().splitlines()[-2:]
        assert batch_hex == np.asarray(small_es.predict(obs)).tobytes().hex()
        assert single_hex == np.asarray(
            small_es.predict(obs[0])).tobytes().hex()

    def test_recurrent_bundle_roundtrip(self, tmp_path):
        # no training needed: the round-trip contract is about the
        # artifact, and the freshly-initialized center is a real policy
        es = ES(RecurrentPolicy, JaxAgent, optax.adam, population_size=8,
                sigma=0.1, seed=0, table_size=1 << 14,
                policy_kwargs={"action_dim": 1, "hidden": (8,),
                               "gru_size": 8, "discrete": False},
                agent_kwargs={"env": RecallEnv(), "horizon": 8},
                optimizer_kwargs={"learning_rate": 5e-2},
                device=jax.devices()[0])
        path = str(tmp_path / "rec")
        es.export_bundle(path)
        b = load_bundle(path)
        assert b.recurrent
        obs = np.random.default_rng(5).standard_normal(1).astype(np.float32)
        o_es, h_es = es.predict(obs)
        o_b, h_b = b.predict(obs)
        assert np.asarray(o_es).tobytes() == np.asarray(o_b).tobytes()
        # threaded carry continues bit-equal
        o_es2, _ = es.predict(obs, carry=h_es)
        o_b2, _ = b.predict(obs, carry=h_b)
        assert np.asarray(o_es2).tobytes() == np.asarray(o_b2).tobytes()
        # sessionless coalescing of carries is refused, not fudged
        with pytest.raises(BundleError, match="recurrent"):
            b.batched_predict_fn()

    def test_host_backend_is_not_bundleable(self, tmp_path):
        import torch

        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.l = torch.nn.Linear(2, 1)

            def forward(self, x):
                return self.l(x)

        class A:
            def rollout(self, policy):
                self.last_episode_steps = 1
                return 0.0

        es = ES(P, A, torch.optim.Adam, population_size=4, sigma=0.1,
                seed=0, table_size=1 << 12)
        with pytest.raises(NotImplementedError, match="torch"):
            es.export_bundle(str(tmp_path / "nope"))


class TestBundleRejection:
    """Corrupt/partial artifacts must be rejected loudly (satellite)."""

    def _copy(self, src, dst):
        import shutil

        shutil.copytree(src, dst)
        return str(dst)

    def test_missing_manifest_means_uncommitted(self, small_bundle,
                                                tmp_path):
        p = self._copy(small_bundle, tmp_path / "b")
        os.remove(os.path.join(p, "MANIFEST.json"))
        with pytest.raises(BundleError, match="never\\s+committed"):
            load_bundle(p)

    def test_corrupt_payload_fails_checksum(self, small_bundle, tmp_path):
        p = self._copy(small_bundle, tmp_path / "b")
        arrays = os.path.join(p, "arrays.npz")
        data = bytearray(open(arrays, "rb").read())
        data[len(data) // 2] ^= 0xFF
        with open(arrays, "wb") as f:
            f.write(bytes(data))
        with pytest.raises(BundleError, match="checksum"):
            load_bundle(p)

    def test_unsupported_schema_rejected(self, small_bundle, tmp_path):
        p = self._copy(small_bundle, tmp_path / "b")
        mp = os.path.join(p, "MANIFEST.json")
        man = json.load(open(mp))
        man["schema"] = 99
        json.dump(man, open(mp, "w"))
        with pytest.raises(BundleError, match="schema"):
            load_bundle(p)

    def test_param_count_drift_rejected(self, small_bundle, tmp_path):
        p = self._copy(small_bundle, tmp_path / "b")
        mp = os.path.join(p, "MANIFEST.json")
        man = json.load(open(mp))
        man["param_dim"] = int(man["param_dim"]) + 1
        json.dump(man, open(mp, "w"))
        with pytest.raises(BundleError, match="param"):
            load_bundle(p)

    def test_unimportable_module_rejected(self, small_bundle, tmp_path):
        p = self._copy(small_bundle, tmp_path / "b")
        mp = os.path.join(p, "MANIFEST.json")
        man = json.load(open(mp))
        man["module"]["import"] = "estorch_tpu.nonexistent:Ghost"
        json.dump(man, open(mp, "w"))
        with pytest.raises(BundleError, match="importable|import"):
            load_bundle(p)

    def test_reexport_over_existing_bundle(self, small_es, tmp_path):
        path = str(tmp_path / "b")
        small_es.export_bundle(path, version="a")
        small_es.export_bundle(path, version="b")
        assert load_bundle(path).version == "b"


# =====================================================================
# dynamic batcher (satellite: bucketing, recompiles, shed) — jax-free
# =====================================================================

class TestBucketLadder:
    def test_ladder_shapes(self):
        assert bucket_sizes(1) == (1,)
        assert bucket_sizes(2) == (2,)
        assert bucket_sizes(32) == (2, 4, 8, 16, 32)

    def test_non_power_of_two_rejected(self):
        with pytest.raises(ValueError, match="power of two"):
            bucket_sizes(12)


class TestDynamicBatcher:
    def _batcher(self, fn=None, **kw):
        tel = Telemetry(enabled=True)
        shapes = []

        def batch_fn(arr):
            shapes.append(arr.shape)
            return (fn or (lambda a: a.sum(axis=1, keepdims=True)))(arr)

        kw.setdefault("max_batch", 8)
        kw.setdefault("max_wait_ms", 5.0)
        b = DynamicBatcher(batch_fn, (3,), telemetry=tel, **kw)
        shapes.clear()  # drop the construction-time verification shapes
        return b, shapes, tel

    def test_batches_pad_to_ladder_buckets(self):
        b, shapes, _ = self._batcher()
        outs = [b.submit(np.full(3, i, np.float32)) for i in range(5)]
        for o in outs:
            assert o.event.wait(10)
        b.close()
        assert shapes, "no batches dispatched"
        for s in shapes:
            assert s[0] in b.buckets, f"dispatched shape {s} off-ladder"
        # results map back to the right requests
        for i, o in enumerate(outs):
            assert o.result[0] == pytest.approx(3.0 * i)

    def test_recompiles_bounded_under_mixed_load(self):
        b, shapes, tel = self._batcher(max_batch=16, max_wait_ms=2.0)
        n_ladder = len(b.buckets) + len(b.buckets_excluded)

        def client(seed):
            rng = np.random.default_rng(seed)
            for _ in range(40):
                item = b.submit(rng.standard_normal(3).astype(np.float32))
                assert item.event.wait(10)
                if rng.random() < 0.3:
                    time.sleep(0.001)

        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        b.close()
        assert tel.counters.get("recompiles") <= n_ladder
        assert tel.counters.get("requests_total") == 240
        assert tel.counters.get("batched_requests_total") == 240

    def test_full_queue_sheds_with_backpressure(self):
        gate = threading.Event()

        def slow(arr):
            gate.wait(10)
            return arr

        tel = Telemetry(enabled=True)
        b = DynamicBatcher(slow, (3,), max_batch=2, max_wait_ms=1.0,
                           max_queue=4, telemetry=tel, verify=False)
        first = b.submit(np.zeros(3, np.float32))
        time.sleep(0.1)  # worker picks `first` up and blocks in slow()
        for _ in range(4):
            b.submit(np.zeros(3, np.float32))
        with pytest.raises(BatcherSaturated):
            b.submit(np.zeros(3, np.float32))
        assert tel.counters.get("shed_total") == 1
        gate.set()
        assert first.event.wait(10)
        b.close()

    def test_close_drains_queued_requests(self):
        def slowish(arr):
            time.sleep(0.02)
            return arr

        b = DynamicBatcher(slowish, (3,), max_batch=2, max_wait_ms=1.0,
                           verify=False)
        items = [b.submit(np.full(3, i, np.float32)) for i in range(10)]
        b.close(drain=True)
        for i, item in enumerate(items):
            assert item.event.is_set()
            assert item.error is None
            assert item.result[0] == pytest.approx(float(i))
        with pytest.raises(BatcherClosed):
            b.submit(np.zeros(3, np.float32))

    def test_batch_fn_error_propagates_to_waiters(self):
        def boom(arr):
            raise RuntimeError("model exploded")

        tel = Telemetry(enabled=True)
        b = DynamicBatcher(boom, (3,), max_batch=2, telemetry=tel,
                           verify=False)
        item = b.submit(np.zeros(3, np.float32))
        assert item.event.wait(10)
        assert isinstance(item.error, RuntimeError)
        assert tel.counters.get("batch_errors_total") == 1
        b.close()

    def test_obs_shape_mismatch_rejected(self):
        b, _, _ = self._batcher()
        with pytest.raises(ValueError, match="obs_shape"):
            b.submit(np.zeros(4, np.float32))
        b.close()


class TestBucketVerification:
    """The measured bit-determinism gate: XLA's cross-batch-shape row
    stability is checked per policy, never assumed (the B=2 lowering
    genuinely deviates by 1 ulp for some trained parameters)."""

    def test_unstable_bucket_excluded(self):
        def fn(arr):
            out = arr.sum(axis=1, keepdims=True)
            if arr.shape[0] == 2:  # model a shape-dependent lowering
                out = out + np.float32(1e-6)
            return out

        stable, excluded = verify_stable_buckets(fn, (3,), (2, 4, 8))
        assert excluded == (2,)
        assert stable == (4, 8)

    def test_batcher_routes_around_excluded_bucket(self):
        shapes = []

        def fn(arr):
            shapes.append(arr.shape[0])
            out = arr.sum(axis=1, keepdims=True)
            if arr.shape[0] == 2:
                out = out + np.float32(1e-6)
            return out

        b = DynamicBatcher(fn, (3,), max_batch=8, max_wait_ms=1.0)
        assert b.buckets_excluded == (2,)
        item = b.submit(np.ones(3, np.float32))
        assert item.event.wait(10)
        b.close()
        assert shapes[-1] == 4  # a lone request pads past the bad bucket

    def test_batcher_routes_around_excluded_interior_bucket(self):
        """An INTERIOR ladder shape failing verification must be padded
        past too — doubling from the smallest bucket would land exactly
        on the excluded (bit-unstable) shape."""
        shapes = []

        def fn(arr):
            shapes.append(arr.shape[0])
            out = arr.sum(axis=1, keepdims=True)
            if arr.shape[0] == 4:  # interior shape deviates
                out = out + np.float32(1e-6)
            return out

        b = DynamicBatcher(fn, (3,), max_batch=8, max_wait_ms=20.0)
        assert b.buckets_excluded == (4,)
        assert b.buckets == (2, 8)
        # the routing rule itself: sizes above the gap pad PAST it
        assert [b._bucket(n) for n in (1, 2, 3, 4, 5, 8)] == [
            2, 2, 8, 8, 8, 8]
        shapes.clear()
        items = [b.submit(np.ones(3, np.float32)) for _ in range(3)]
        for it in items:
            assert it.event.wait(10)
        b.close()
        assert 4 not in shapes  # the unstable shape is never dispatched

    def test_slot_dependent_anchor_is_fatal(self):
        def fn(arr):
            out = arr.sum(axis=1, keepdims=True)
            out[0] += np.float32(1e-6)  # slot 0 special-cased
            return out

        with pytest.raises(ValueError, match="slot-dependent"):
            verify_stable_buckets(fn, (3,), (2, 4))

    def test_stable_fn_keeps_whole_ladder(self):
        stable, excluded = verify_stable_buckets(
            lambda a: a.sum(axis=1, keepdims=True), (3,), (2, 4, 8))
        assert stable == (2, 4, 8)
        assert excluded == ()


# =====================================================================
# server endpoints (in-process PolicyServer)
# =====================================================================

@pytest.fixture(scope="module")
def live_server(small_bundle):
    from estorch_tpu.serve import PolicyServer

    srv = PolicyServer(small_bundle, port=0, max_batch=8, max_wait_ms=2.0,
                       telemetry=Telemetry(enabled=True))
    srv.start_background()
    yield srv
    srv.shutdown(drain=True)


def _anchor_ref(es, obs, anchor):
    """The bit-sound reference for a lone served request: the batcher
    pads into a VERIFIED bucket whose rows equal the anchor bucket's, and
    the anchor shape is where es.predict's direct program and the serving
    vmap agree (pinned by test_batched_fn_matches_es_predict_at_same_shape)
    — so reference = es.predict on an anchor-sized zero-padded batch."""
    pad = np.zeros((anchor,) + np.shape(obs), np.float32)
    pad[0] = obs
    return np.asarray(es.predict(pad))[0]


class TestServerEndpoints:
    def test_predict_health_stats(self, small_es, live_server):
        with ServeClient(f"{live_server.host}:{live_server.port}") as c:
            h = c.health()
            assert h["ok"] and h["version"] == "test-v1"
            obs = np.random.default_rng(6).standard_normal(3).astype(
                np.float32)
            action = np.asarray(c.predict(obs), np.float32)
            s = c.stats()
            ref = _anchor_ref(small_es, obs, max(s["buckets"]))
            assert action.tobytes() == ref.tobytes()
            assert s["requests_total"] >= 1
            assert s["recompiles"] <= len(s["buckets"]) + len(
                s["buckets_excluded"])

    def test_metrics_exposition_scrapeable(self, live_server):
        """GET /metrics speaks Prometheus text exposition: the serving
        counters as estorch_-prefixed samples, validated by the parser
        that did not write them (obs/export/prometheus.py)."""
        import urllib.request

        from estorch_tpu.obs.export.prometheus import (parse_exposition,
                                                       samples_by_name)

        with ServeClient(f"{live_server.host}:{live_server.port}") as c:
            obs = np.zeros(3, np.float32)
            c.predict(obs)  # at least one served request on the counters
        url = f"http://{live_server.host}:{live_server.port}/metrics"
        with urllib.request.urlopen(url, timeout=30) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        vals = samples_by_name(parse_exposition(body))
        assert vals["estorch_requests_total"] >= 1
        assert vals["estorch_up"] == 1  # serving and not draining
        assert vals["estorch_uptime_seconds"] >= 0
        assert "estorch_queue_depth" in vals
        assert "# TYPE estorch_requests_total counter" in body
        assert "# TYPE estorch_queue_depth gauge" in body

    def test_bad_requests_are_4xx(self, live_server):
        with ServeClient(f"{live_server.host}:{live_server.port}") as c:
            with pytest.raises(ServeError) as ei:
                c.predict([1.0, 2.0])  # wrong obs shape
            assert ei.value.status == 400
            with pytest.raises(ServeError) as ei:
                c._request("POST", "/predict", {"not_obs": 1})
            assert ei.value.status == 400
            with pytest.raises(ServeError) as ei:
                c._request("GET", "/nope")
            assert ei.value.status == 404

    def test_hot_reload_swaps_atomically(self, small_es, live_server,
                                         tmp_path):
        v2 = str(tmp_path / "v2")
        small_es.export_bundle(v2, version="test-v2")
        addr = f"{live_server.host}:{live_server.port}"
        with ServeClient(addr) as c:
            assert c.reload(v2)["version"] == "test-v2"
            assert c.health()["version"] == "test-v2"
            # a bad reload is a 409 and the old bundle keeps serving
            with pytest.raises(ServeError) as ei:
                c.reload(str(tmp_path / "missing"))
            assert ei.value.status == 409
            assert c.health()["version"] == "test-v2"
            obs = np.random.default_rng(7).standard_normal(3).astype(
                np.float32)
            got = np.asarray(c.predict(obs), np.float32)
            ref = _anchor_ref(small_es, obs, max(c.stats()["buckets"]))
            assert got.tobytes() == ref.tobytes()


# =====================================================================
# tail-latency truth: quantile honesty + trace ids (docs/observability.md
# "Tails & traces")
# =====================================================================

class TestQuantileHonesty:
    def test_loadgen_offline_vs_server_histogram_quantiles(
            self, small_bundle):
        """Quantile honesty: the loadgen's OFFLINE p50/p95/p99 (exact
        nearest-rank over every client-measured latency) and the
        server's histogram-derived quantiles for the same run must agree
        within the bucket ladder's documented error bound, plus a small
        absolute allowance for what the client clock sees and the
        batcher's cannot (HTTP parse + event-wakeup, loopback-scale)."""
        from estorch_tpu.serve import PolicyServer
        from estorch_tpu.serve.loadgen import _percentile, run_load

        srv = PolicyServer(small_bundle, port=0, max_batch=8,
                           max_wait_ms=2.0,
                           telemetry=Telemetry(enabled=True))
        srv.start_background()
        try:
            res = run_load(f"{srv.host}:{srv.port}", conns=8, total=400,
                           duration_s=60.0, obs=[0.0, 0.0, 0.0],
                           collect_latencies=True)
            assert res["requests"] == 400 and not res["errors"]
            offline = sorted(res["latencies_s"])
            hist = srv.obs.hists.get("serve/request_s")
            assert hist is not None and hist.count == 400
            bound = hist.quantile_error_bound()
            for q in (0.50, 0.95, 0.99):
                off = _percentile(offline, q)
                srv_q = hist.quantile(q)
                # client latency >= server-side request_s (wakeup +
                # HTTP legs ride only the client clock), so the server
                # quantile may sit below; it must never exceed the
                # offline one by more than the ladder bound + slack
                assert srv_q <= off * (1 + bound) + 0.002, (
                    f"p{q * 100:g}: hist {srv_q} vs offline {off}")
                assert srv_q >= off * (1 - bound) - 0.010, (
                    f"p{q * 100:g}: hist {srv_q} vs offline {off}")
            # lifecycle legs all populated on a real HTTP run
            names = srv.obs.hists.names()
            for name in ("serve/queue_wait_s", "serve/coalesce_wait_s",
                         "serve/compute_s", "serve/request_s",
                         "serve/write_s"):
                assert name in names, names
            # /stats surfaces histogram-derived request quantiles
            assert srv.stats()["request_ms"]["p50"] > 0
        finally:
            srv.shutdown(drain=True)

    def test_predict_response_carries_trace_id(self, live_server):
        import urllib.request

        body = json.dumps({"obs": [0.0, 0.0, 0.0]}).encode()
        req = urllib.request.Request(
            f"http://{live_server.host}:{live_server.port}/predict",
            data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            trace = r.headers.get("X-Trace-Id")
        assert trace and trace.startswith("r")
        # the same id is recorded in the batcher's dispatch event — the
        # causal link from an HTTP answer back to its coalesced batch
        evs = [e for e in live_server.obs.recorder.events()
               if e["name"] == "batch_dispatch"]
        assert any(trace in e.get("traces", []) for e in evs)


# =====================================================================
# supervised serving (resilience integration)
# =====================================================================

def _beat_then_wedge(root, marker):
    """Supervised child: first incarnation beats then wedges (watchdog
    food); later incarnations exit clean."""
    from estorch_tpu.obs.recorder import HEARTBEAT_ENV, Heartbeat

    hb = Heartbeat(os.environ[HEARTBEAT_ENV])
    if os.path.exists(marker):
        hb.beat("serving", 1)
        return
    with open(marker, "w") as f:
        f.write("seen")
    for _ in range(3):
        hb.beat("serving", 0)
        time.sleep(0.1)
    time.sleep(600)  # silent wedge: alive but beatless


class TestSupervisedServe:
    def test_generic_child_watchdog_restart(self, tmp_path):
        """The PR-3 watchdog babysits a NON-training child (the serving
        recipe): heartbeat staleness kills the wedged incarnation, the
        restart completes, provenance lands in the manifest."""
        from estorch_tpu.resilience import Supervisor

        marker = str(tmp_path / "marker")
        sup = Supervisor(
            ckpt_root=str(tmp_path / "root"),
            child_target=_beat_then_wedge,
            child_args=(marker,),
            stale_after_s=2.0,
            startup_grace_s=60.0,
            backoff_s=0.1,
            max_restarts=2,
            poll_s=0.2,
        )
        result = sup.run()
        assert result["ok"], result
        assert len(result["restarts"]) == 1
        assert "stale" in result["restarts"][0]["reason"]

    def test_exactly_one_child_mode_required(self, tmp_path):
        from estorch_tpu.resilience import Supervisor

        with pytest.raises(ValueError, match="exactly one"):
            Supervisor(ckpt_root=str(tmp_path))
        with pytest.raises(ValueError, match="exactly one"):
            Supervisor(es_factory=lambda: None, child_target=_beat_then_wedge,
                       ckpt_root=str(tmp_path))

    @pytest.mark.slow  # supervisor + spawned jax server child: ~15s; the
    # non-slow watchdog-restart test above covers the Supervisor's
    # generic-child mechanics
    def test_supervised_serve_end_to_end(self, small_bundle, tmp_path):
        """``serve --supervised``: the server answers under the watchdog,
        and SIGTERM to the SUPERVISOR forwards to the child, which drains
        — the supervisor reports clean completion (ok, exit 0)."""
        from estorch_tpu.serve.server import find_free_port

        port = find_free_port()
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.Popen(
            [sys.executable, "-m", "estorch_tpu.serve", "--bundle",
             small_bundle, "--supervised", "--supervise-root",
             str(tmp_path / "root"), "--port", str(port),
             "--cpu-devices", "8", "--max-batch", "8",
             "--beat-interval", "0.5", "--stale-after-s", "30"],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        try:
            deadline = time.time() + 120
            health = None
            while time.time() < deadline:
                try:
                    with ServeClient(f"127.0.0.1:{port}",
                                     timeout_s=2) as c:
                        health = c.health()
                    break
                except OSError:
                    time.sleep(0.5)
            assert health is not None and health["ok"], health
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        assert proc.returncode == 0, out[-1000:]
        last = json.loads(out.strip().splitlines()[-1])
        assert last == {"supervised": True, "ok": True, "restarts": 0,
                        "reason": None}


# =====================================================================
# THE acceptance demo (tier-1): trained pendulum policy, real server
# subprocesses, concurrent load, bit-exactness + >=3x + clean drain
# =====================================================================

DEMO_HIDDEN = 6144  # big enough that one request's GEMV is memory-bound:
# the batching win being measured is one weight-stream amortized over the
# whole bucket — the 2206.08888 batched-inference effect, not a cache toy


@pytest.fixture(scope="module")
def demo_bundle(tmp_path_factory):
    es = _make_small_es(
        policy_kwargs=dict(SMALL_PK, hidden=(DEMO_HIDDEN, DEMO_HIDDEN)),
        agent_kwargs={"env": Pendulum(), "horizon": 8},
        population_size=4,
        table_size=1 << 26,
        obs_norm=False,
    )
    es.train(1, verbose=False)
    path = str(tmp_path_factory.mktemp("demo") / "pendulum_big")
    es.export_bundle(path, version="demo")
    return es, path


def _spawn_server(bundle, max_batch, extra_env=None, max_wait_ms=4.0,
                  extra_args=()):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **(extra_env or {})}
    proc = subprocess.Popen(
        [sys.executable, "-m", "estorch_tpu.serve", "--bundle", bundle,
         "--port", "0", "--cpu-devices", "8",
         "--max-batch", str(max_batch), "--max-wait-ms", str(max_wait_ms),
         "--beat-interval", "0.5", *extra_args],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    ready = json.loads(proc.stdout.readline())
    return proc, ready


def _finish(proc, timeout=60):
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=timeout)
    return proc.returncode, json.loads(out.strip().splitlines()[-1])


class TestServingDemo:
    def test_serving_demo(self, demo_bundle):
        """Acceptance: (a) responses bit-equal to direct ES.predict,
        (b) dynamic batching >=3x the batch-size-1 throughput on this
        host, (c) recompiles <= n_buckets under mixed concurrent load,
        (d) SIGTERM drains in-flight requests cleanly — no shed, real
        answers, exit 0."""
        from estorch_tpu.serve.loadgen import run_load

        es, bundle = demo_bundle
        rng = np.random.default_rng(8)
        # exactly anchor-many obs: the reference es.predict batch IS the
        # anchor shape, where the direct program and the serving vmap are
        # asserted bit-equal in-process before anything goes on the wire
        check_obs = rng.standard_normal((64, 3)).astype(np.float32)
        ref = np.asarray(es.predict(check_obs))
        b = load_bundle(bundle)
        assert b.batched_predict_fn()(check_obs).tobytes() == ref.tobytes()

        # ---- dynamic-batching leg --------------------------------------
        proc, ready = _spawn_server(bundle, max_batch=64)
        addr = ready["url"]
        try:
            # (a) correctness under CONCURRENT load: 32 distinct obs ride
            # mixed buckets; every response must be bit-equal to the
            # exporting run's es.predict rows (same 8-virtual-device host
            # config on both sides)
            chk = run_load(addr, conns=6, total=len(check_obs),
                           duration_s=120.0,
                           obs_list=[o.tolist() for o in check_obs],
                           collect_responses=True)
            assert chk["errors"] == 0 and chk["shed"] == 0
            got = np.asarray([r["action"] for r in chk["responses"]],
                             np.float32)
            assert got.tobytes() == ref.tobytes(), (
                "served responses are not bit-equal to ES.predict")

            dyn = run_load(addr, conns=48, duration_s=2.5,
                           obs=[0.1, 0.2, 0.3])
            assert dyn["errors"] == 0

            with ServeClient(addr) as c:
                stats = c.stats()
            # (c) bucket ladder held: one compile per ladder shape, no
            # recompile churn under mixed batch sizes
            n_ladder = len(stats["buckets"]) + len(stats["buckets_excluded"])
            assert stats["recompiles"] <= n_ladder
            assert stats["shed_total"] == 0

            # (d) SIGTERM lands while 12 requests are in flight (the
            # batched forward takes tens of ms at this size, so firing
            # right after the clients guarantees work is mid-pipeline);
            # every one of them must get a REAL answer, nothing shed
            results: list = [None] * 12
            errors: list = []
            host_port = addr.split("://", 1)[1]
            # connections are ESTABLISHED (via a health round trip) before
            # the signal: in-flight means accepted work, not a racing
            # connect against the closing listener
            clients = [ServeClient(host_port, timeout_s=60)
                       for _ in range(12)]
            for c in clients:
                c.health()

            def client(i):
                try:
                    results[i] = clients[i].predict([0.1 * i, 0.2, 0.3])
                except Exception as e:  # asserted empty below
                    errors.append((i, repr(e)))
                finally:
                    clients[i].close()

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(12)]
            for t in threads:
                t.start()
            time.sleep(0.05)
            proc.send_signal(signal.SIGTERM)
            for t in threads:
                t.join(timeout=60)
            out, _ = proc.communicate(timeout=60)
            final = json.loads(out.strip().splitlines()[-1])
            assert not errors, errors
            assert all(r is not None for r in results)
            assert proc.returncode == 0
            assert final["clean"]
            assert final["counters"].get("shed_total", 0) == 0
            # drained responses are REAL answers: reference at the anchor
            # shape, zero-padded the same way the batcher pads
            pad = np.zeros((64, 3), np.float32)
            pad[:12] = np.asarray(
                [[0.1 * i, 0.2, 0.3] for i in range(12)], np.float32)
            drain_ref = np.asarray(es.predict(pad))[:12]
            assert np.asarray(results,
                              np.float32).tobytes() == drain_ref.tobytes()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

        # ---- batch-size-1 baseline leg ---------------------------------
        proc, ready = _spawn_server(bundle, max_batch=1)
        try:
            b1 = run_load(ready["url"], conns=8, duration_s=2.5,
                          obs=[0.1, 0.2, 0.3])
            assert b1["errors"] == 0
            code, final = _finish(proc)
            assert code == 0 and final["clean"]
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

        # (b) the batching win: one weight-stream amortized per bucket.
        # Steady-state headroom is ~4x on this 2-core host; a transient
        # external load spike during one 2.5s leg can crater either
        # number, so a sub-3x first reading gets ONE full re-measurement
        # (both legs, fresh servers) before the gate decides.
        def measure_legs():
            p_dyn, r_dyn = _spawn_server(bundle, max_batch=64)
            try:
                d = run_load(r_dyn["url"], conns=48, duration_s=2.5,
                             obs=[0.1, 0.2, 0.3])
                _finish(p_dyn)
            finally:
                if p_dyn.poll() is None:
                    p_dyn.kill()
                    p_dyn.wait(timeout=30)
            p_b1, r_b1 = _spawn_server(bundle, max_batch=1)
            try:
                s = run_load(r_b1["url"], conns=8, duration_s=2.5,
                             obs=[0.1, 0.2, 0.3])
                _finish(p_b1)
            finally:
                if p_b1.poll() is None:
                    p_b1.kill()
                    p_b1.wait(timeout=30)
            return d["throughput_rps"], s["throughput_rps"]

        dyn_rps, b1_rps = dyn["throughput_rps"], b1["throughput_rps"]
        ratio = dyn_rps / b1_rps
        if ratio < 3.0:
            dyn_rps, b1_rps = measure_legs()
            ratio = dyn_rps / b1_rps
        print(f"\nserving demo: dyn={dyn_rps} rps "
              f"(p50 {dyn['latency_ms']['p50']}ms) vs b1={b1_rps} rps "
              f"-> {ratio:.2f}x")
        assert ratio >= 3.0, (
            f"dynamic batching {dyn_rps} rps vs batch-1 {b1_rps} rps = "
            f"{ratio:.2f}x < 3x")


# =====================================================================
# warm-start bundles (serve/warm.py, docs/serving.md "Cold start &
# quantized serving")
# =====================================================================

@pytest.fixture(scope="module")
def warm_bundle_path(small_es, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("warm") / "pendulum_warm")
    small_es.export_bundle(path, version="warm-v1", warm=True,
                           warm_max_batch=4, serve_bf16=True)
    return path


class TestWarmBundle:
    def test_warm_block_packed_and_checksummed(self, warm_bundle_path):
        man = validate_bundle(warm_bundle_path)
        warm = man["warm"]
        assert warm["format"] == "xla_cache"
        assert warm["entries"], "warm export packed no cache entries"
        sha = man["sha256"]
        for fname in warm["entries"]:
            assert f"warm/{fname}" in sha
            assert os.path.exists(
                os.path.join(warm_bundle_path, "warm", fname))
        # ladder complete: warmed + verification-excluded covers exactly
        # the bucket ladder of the recorded max_batch
        covered = set(warm["buckets"]) | set(warm["buckets_excluded"])
        assert covered == set(bucket_sizes(warm["max_batch"]))
        assert warm["dtypes"] == ["f32", "bf16"]
        assert warm["jax_version"] == jax.__version__
        assert warm["platform"] == "cpu"

    def test_warm_corruption_rejected(self, warm_bundle_path, tmp_path):
        import shutil

        dst = str(tmp_path / "tampered")
        shutil.copytree(warm_bundle_path, dst)
        man = validate_bundle(warm_bundle_path)
        fname = sorted(man["warm"]["entries"])[0]
        victim = os.path.join(dst, "warm", fname)
        with open(victim, "r+b") as f:
            f.seek(0)
            b = f.read(1)
            f.seek(0)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(BundleError, match="checksum"):
            validate_bundle(dst)
        os.remove(victim)
        with pytest.raises(BundleError, match="missing"):
            validate_bundle(dst)

    def test_version_mismatch_is_finding_not_error(self, warm_bundle_path,
                                                   tmp_path):
        """Warmth built under another jax version must be IGNORED with a
        structured reason (load still succeeds, serving still works) —
        and the doctor's warm probe reports the same finding."""
        import shutil

        dst = str(tmp_path / "stale_warm")
        shutil.copytree(warm_bundle_path, dst)
        man_path = os.path.join(dst, "MANIFEST.json")
        with open(man_path) as f:
            man = json.load(f)
        man["warm"]["jax_version"] = "0.0.0"
        with open(man_path, "w") as f:
            json.dump(man, f)
        b = load_bundle(dst, install_warm=True)
        assert b.warm_status["installed"] is False
        assert "0.0.0" in b.warm_status["reason"]
        # still a perfectly servable bundle
        out = b.batched_predict_fn()(np.zeros((2, 3), np.float32))
        assert out.shape == (2, 1)
        from estorch_tpu.doctor import check_serve

        probe = check_serve(bundle=dst)["bundle"]["warm"]
        assert probe["present"] and probe["compatible"] is False
        assert "re-export" in probe["finding"]

    def test_cold_bundle_reports_no_warmth(self, small_bundle):
        b = load_bundle(small_bundle, install_warm=True)
        assert b.warm_status["installed"] is False
        assert "no warmth" in b.warm_status["reason"]
        from estorch_tpu.doctor import check_serve

        probe = check_serve(bundle=small_bundle)["bundle"]["warm"]
        assert probe == {"present": False}

    def test_reexport_without_warm_clears_stale_entries(self, small_es,
                                                        tmp_path):
        path = str(tmp_path / "re")
        small_es.export_bundle(path, warm=True, warm_max_batch=4)
        assert os.path.isdir(os.path.join(path, "warm"))
        small_es.export_bundle(path)  # cold re-export over the same dir
        man = validate_bundle(path)
        assert "warm" not in man
        assert not os.path.isdir(os.path.join(path, "warm"))

    def test_warm_roundtrip_fresh_process_zero_fresh_builds(
            self, small_es, warm_bundle_path):
        """THE warm-bundle acceptance: a fresh --cpu-devices-pinned
        process loads the warm bundle and serves its first request with
        ZERO fresh XLA builds (every program a persistent-cache hit, per
        the compile ledger's bundle_load accounting), answers bit-equal
        to the exporting run, and leaves the bundle's checksums intact.
        The --no-warm control leg on the SAME bundle pays the JIT storm,
        proving the A/B is real."""
        proc, ready = _spawn_server(warm_bundle_path, max_batch=4)
        try:
            cold = ready["cold_start"]
            assert cold["warm"]["installed"] is True
            assert cold["compiles_at_load"] == 0, (
                f"warm load paid {cold['compiles_at_load']} fresh builds")
            assert cold["warm_cache_hits"] > 0
            obs = np.random.default_rng(11).standard_normal(3).astype(
                np.float32)
            with ServeClient(ready["url"].split("://")[1]) as c:
                got = np.asarray(c.predict(obs), np.float32)
                stats = c.stats()
            ref = _anchor_ref(small_es, obs, max(stats["buckets"]))
            assert got.tobytes() == ref.tobytes()
            assert stats["cold_start"]["first_request_s"] is not None
            assert stats["cold_start"]["startup_s"] is not None
            code, final = _finish(proc)
            assert code == 0 and final["clean"]
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        # serving never wrote into the bundle: checksums still hold
        validate_bundle(warm_bundle_path)

        # control leg: same bundle, warmth ignored -> the JIT storm
        proc, ready = _spawn_server(warm_bundle_path, max_batch=4,
                                    extra_args=["--no-warm"])
        try:
            cold = ready["cold_start"]
            assert cold["warm"]["installed"] is False
            assert cold["compiles_at_load"] > 0
            assert cold["warm_cache_hits"] == 0
            code, final = _finish(proc)
            assert code == 0 and final["clean"]
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)


# =====================================================================
# quantized serving: divergence measurement + bucket exclusion
# (serve/batcher.py, jax-free) and the bf16 path (serve/predictor.py)
# =====================================================================

class TestQuantBatcher:
    obs_shape = (3,)

    @staticmethod
    def _f32(arr):
        return arr.sum(axis=1, keepdims=True).astype(np.float32)

    def test_drifting_bucket_excluded_f32_fallback_answers(self):
        """A quantized path that drifts at ONE bucket keeps serving:
        that bucket is excluded (measured, counted) and dispatches the
        exact f32 program at the same shape, while within-bound buckets
        ride the quantized fast path."""
        def quant(arr):
            out = self._f32(arr) + 0.01  # inside the bound
            if arr.shape[0] == 4:
                out = out + 1e3  # engineered drift at bucket 4
            return out

        tel = Telemetry(enabled=True)
        b = DynamicBatcher(self._f32, self.obs_shape, max_batch=8,
                           max_wait_ms=40.0, telemetry=tel,
                           quant_fn=quant, quant_bound=0.05)
        try:
            assert b.quant_buckets_excluded == (4,)
            assert set(b.quant_buckets) == {2, 8}
            assert b.quant_divergence[4] > 0.05
            assert int(tel.counters.get("quant_buckets_excluded")) == 1
            # one lone request pads to bucket 2 -> quantized value
            got = b.predict([1.0, 2.0, 3.0], timeout=10.0)
            assert got[0] == np.float32(6.0) + np.float32(0.01)
            # three coalesced requests pad to bucket 4 -> EXCLUDED from
            # the quant ladder -> exact f32 values
            items = [b.submit([float(i), 1.0, 1.0]) for i in range(3)]
            for i, it in enumerate(items):
                assert it.event.wait(10.0)
                assert it.result[0] == np.float32(i + 2.0)
            stats = b.stats()
            assert stats["quant"]["excluded"] == [4]
            assert stats["quant"]["batches_total"] >= 1
        finally:
            b.close()

    def test_anchor_drift_refused(self):
        with pytest.raises(ValueError, match="anchor"):
            DynamicBatcher(self._f32, self.obs_shape, max_batch=4,
                           max_wait_ms=1.0,
                           quant_fn=lambda a: self._f32(a) + 1e3,
                           quant_bound=0.05)

    def test_quant_needs_bound_and_verification(self):
        with pytest.raises(ValueError, match="quant_bound"):
            DynamicBatcher(self._f32, self.obs_shape, max_batch=4,
                           quant_fn=self._f32)
        with pytest.raises(ValueError, match="verification"):
            DynamicBatcher(self._f32, self.obs_shape, max_batch=4,
                           verify=False, quant_fn=self._f32,
                           quant_bound=0.05)

    def test_nonfinite_quant_output_is_infinite_divergence(self):
        from estorch_tpu.serve.batcher import measure_quant_divergence

        def quant(arr):
            out = self._f32(arr)
            out[0] = np.nan
            return out

        div = measure_quant_divergence(quant, self._f32, self.obs_shape,
                                       [2, 4])
        assert div[2] == float("inf") and div[4] == float("inf")

    def test_batch1_ladder_measures_divergence_too(self):
        """max_batch=1 (the GEMV baseline) still gets the accuracy
        contract: divergence measured at bucket 1, refused past bound."""
        b = DynamicBatcher(self._f32, self.obs_shape, max_batch=1,
                           max_wait_ms=1.0,
                           quant_fn=lambda a: self._f32(a) + 0.001,
                           quant_bound=0.05)
        try:
            assert b.quant_buckets == (1,)
            assert 1 in b.quant_divergence
        finally:
            b.close()
        with pytest.raises(ValueError, match="anchor"):
            DynamicBatcher(self._f32, self.obs_shape, max_batch=1,
                           max_wait_ms=1.0,
                           quant_fn=lambda a: self._f32(a) + 1e3,
                           quant_bound=0.05)


class DriftPolicy(flax_nn.Module):
    """bf16-hostile by construction: the +4096/-4096 round trip keeps
    the (tiny) signal in f32 but destroys it at bf16's 8 mantissa bits
    — the policy-exceeds-the-bound refusal case."""

    @flax_nn.compact
    def __call__(self, x):
        # weak-typed python literals follow the computation dtype: in
        # bf16 the +4096 absorbs the whole signal (8 mantissa bits), in
        # f32 it survives — a jnp.float32 constant would instead promote
        # the bf16 activations back to f32 and defeat the engineering
        h = flax_nn.Dense(1)(x) * 0.01
        return (h + 4096.0) - 4096.0


class TestBf16Serving:
    def test_bf16_refused_without_opt_in(self, small_bundle):
        b = load_bundle(small_bundle)
        with pytest.raises(BundleError, match="did not opt into"):
            b.batched_predict_fn(dtype="bf16")

    def test_bf16_server_serves_within_measured_bound(self, small_es,
                                                      warm_bundle_path):
        """An opted-in policy serves bf16 with per-bucket divergence
        MEASURED at load and every answer inside the documented bound of
        the f32 reference."""
        from estorch_tpu.serve import PolicyServer
        from estorch_tpu.serve.warm import BF16_DIVERGENCE_BOUND

        srv = PolicyServer(warm_bundle_path, port=0, max_batch=4,
                           max_wait_ms=2.0, dtype="bf16",
                           telemetry=Telemetry(enabled=True))
        srv.start_background()
        try:
            obs = np.random.default_rng(12).standard_normal(3).astype(
                np.float32)
            with ServeClient(f"{srv.host}:{srv.port}") as c:
                got = np.asarray(c.predict(obs), np.float32)
                stats = c.stats()
            quant = stats["quant"]
            assert quant["dtype"] == "bf16"
            assert quant["bound"] == BF16_DIVERGENCE_BOUND
            for b_, d in quant["divergence"].items():
                if int(b_) in quant["buckets"]:
                    assert d <= BF16_DIVERGENCE_BOUND
            assert stats["dtype"] == "bf16"
            ref = _anchor_ref(small_es, obs, max(stats["buckets"]))
            scale = max(abs(float(ref[0])), 1e-6)
            assert abs(float(got[0]) - float(ref[0])) <= (
                BF16_DIVERGENCE_BOUND * max(scale, 2.0))
        finally:
            srv.shutdown(drain=True)

    def test_drift_policy_refused_as_bundle_error(self, tmp_path):
        """A policy whose bf16 divergence exceeds the bound at the
        anchor is REFUSED (the server's 409 / CLI exit 2), never served
        quantized-but-wrong; the same bundle serves f32 fine."""
        es = _make_small_es(policy=DriftPolicy, policy_kwargs={},
                            obs_norm=False)
        path = str(tmp_path / "drift")
        es.export_bundle(path, serve_bf16=True)
        from estorch_tpu.serve import PolicyServer
        from estorch_tpu.serve.warm import build_serving_batcher

        with pytest.raises(BundleError, match="divergence bound"):
            build_serving_batcher(load_bundle(path), max_batch=4,
                                  dtype="bf16")
        # the exact path still answers: f32 serving of the same bundle
        srv = PolicyServer(path, port=0, max_batch=4, dtype="f32")
        srv.start_background()
        try:
            with ServeClient(f"{srv.host}:{srv.port}") as c:
                out = c.predict([0.1, 0.2, 0.3])
            assert np.isfinite(np.asarray(out, np.float32)).all()
        finally:
            srv.shutdown(drain=True)

    def test_warm_export_fails_loudly_on_drift_policy(self, tmp_path):
        """warm=True + serve_bf16=True REPLAYS the bf16 verification at
        export: a drifting policy fails the export with the diagnosis
        instead of shipping a bundle every server will 409."""
        es = _make_small_es(policy=DriftPolicy, policy_kwargs={},
                            obs_norm=False)
        with pytest.raises(BundleError, match="divergence bound"):
            es.export_bundle(str(tmp_path / "drift_warm"), warm=True,
                             warm_max_batch=4, serve_bf16=True)
