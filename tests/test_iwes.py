"""IW-ES: importance-weighted sample reuse (algo/iwes.py + engine programs).

Anchors: λ against a direct Gaussian-density-ratio oracle on materialized
member params; the combined update against a dense hand-built estimator;
the ESS guard's fallback to vanilla ES; end-to-end learnability.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from estorch_tpu import ES, IW_ES, JaxAgent, MLPPolicy
from estorch_tpu.envs import CartPole


def _make(cls=IW_ES, n_pop=16, seed=7, **kw):
    base = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=n_pop,
        sigma=0.1,
        seed=seed,
        policy_kwargs={"action_dim": 2, "hidden": (8,)},
        agent_kwargs={"env": CartPole(), "horizon": 50},
        optimizer_kwargs={"learning_rate": 1e-2},
        table_size=1 << 15,
    )
    base.update(kw)
    return cls(**base)


class TestRatios:
    @pytest.mark.slow
    def test_lambda_matches_density_ratio_oracle(self):
        """λ from engine noise_stats must equal the direct Gaussian density
        ratio computed from each materialized old member."""
        es = _make()
        es.train(1, verbose=False)
        prev_st = es.state  # snapshot a REAL state (the ring keeps only a
        es.train(1, verbose=False)  # minimal record; member_params needs it)
        st = es.state
        entry = (prev_st.params_flat, float(np.asarray(prev_st.sigma)),
                 es.engine.all_pair_offsets(prev_st), None)
        lam, d_vec, c, old_offsets = es._ratios(entry, st)

        dim = es._spec.dim
        s_old = float(np.asarray(prev_st.sigma))
        s_new = float(np.asarray(st.sigma))
        center_old = np.asarray(prev_st.params_flat)
        center_new = np.asarray(st.params_flat)
        want = np.zeros(es.population_size)
        for i in range(es.population_size):
            theta = np.asarray(es.engine.member_params(prev_st, i))
            e_old = (theta - center_old) / s_old
            e_new = (theta - center_new) / s_new
            log_ratio = dim * np.log(s_old / s_new) + 0.5 * (
                e_old @ e_old - e_new @ e_new
            )
            want[i] = log_ratio
        want = np.exp(want - want.max())  # _ratios shifts by max too
        np.testing.assert_allclose(lam, want, rtol=2e-3, atol=2e-4)

    def test_identity_move_gives_uniform_lambda(self):
        """θ_new == θ_old and equal σ → every λ identical → ESS == n."""
        es = _make()
        es.train(1, verbose=False)  # populate state only
        st = es.state
        entry = (st.params_flat, float(np.asarray(st.sigma)),
                 es.engine.all_pair_offsets(st), None)
        lam, d_vec, c, _ = es._ratios(entry, st)
        np.testing.assert_allclose(lam, lam[0])
        ess = lam.sum() ** 2 / (lam**2).sum()
        assert ess == pytest.approx(es.population_size)


class TestUpdate:
    @pytest.mark.slow
    def test_reuse_update_matches_dense_oracle(self):
        """engine.apply_weights_reuse == hand-built combined estimator on
        materialized noise, run through the same optax transform."""
        es = _make(n_pop=16)
        es.train(1, verbose=False)
        prev_st = es.state
        prev_fit = np.asarray(es.engine.evaluate(prev_st).fitness)
        es.train(1, verbose=False)
        st = es.state

        ev = es.engine.evaluate(st)
        fitness = np.asarray(ev.fitness)
        entry = (prev_st.params_flat, float(np.asarray(prev_st.sigma)),
                 es.engine.all_pair_offsets(prev_st), prev_fit)
        lam, d_vec, c, old_offsets = es._ratios(entry, st)
        new_st, gnorm = es._reuse_update(
            st, fitness, [(prev_fit, lam, d_vec, c, old_offsets)]
        )

        # ---- oracle ----
        from estorch_tpu.utils.fault import rank_weights_with_failures

        n = 16
        sigma_new = float(np.asarray(st.sigma))
        w_all = rank_weights_with_failures(np.concatenate([fitness, prev_fit]))
        w_fresh, w_old = w_all[:n], w_all[n:]
        lam_t = lam * n / lam.sum()

        center = np.asarray(st.params_flat)
        grad = np.zeros_like(center)
        okey = jax.random.fold_in(jax.random.fold_in(st.key, st.generation), 0)
        from estorch_tpu.ops.noise import sample_pair_offsets

        offs = np.asarray(
            sample_pair_offsets(okey, n // 2, es.table.size, es._spec.dim)
        )
        for i in range(n):
            eps = np.asarray(es.table.slice(int(offs[i // 2]), es._spec.dim))
            s = 1.0 if i % 2 == 0 else -1.0
            grad += w_fresh[i] * s * eps
        d_np = np.asarray(d_vec)
        for i in range(n):
            theta = np.asarray(es.engine.member_params(prev_st, i))
            eps_new = (theta - center) / sigma_new
            grad += w_old[i] * lam_t[i] * eps_new
        grad /= 2 * n * sigma_new

        opt = optax.adam(1e-2)
        updates, _ = opt.update(
            -jnp.asarray(grad), st.opt_state, st.params_flat
        )
        want = np.asarray(optax.apply_updates(st.params_flat, updates))
        np.testing.assert_allclose(
            np.asarray(new_st.params_flat), want, rtol=1e-4, atol=1e-5
        )

    def test_ess_guard_falls_back_to_vanilla(self):
        """A huge center move collapses λ → ESS guard skips reuse and the
        generation must be recorded as non-reused."""
        es = _make(optimizer_kwargs={"learning_rate": 5.0})  # violent moves
        es.train(3, verbose=False)
        assert not any(r["reused_prev"] for r in es.history[1:])
        # with a tame lr the same seed settles into reuse within a few gens
        es2 = _make()
        es2.train(6, verbose=False)
        assert any(r["reused_prev"] for r in es2.history)
        assert all(r["ess"] >= 0.0 for r in es2.history)

    def test_decomposed_forward_is_equivalent(self):
        """IW_ES advertises the decomposed forward (ctor accepts it, only
        streamed/noise_kernel are rejected); since the decomposition is an
        exact identity at f32, the whole reuse trajectory must match the
        standard forward bit-for-bit — offsets, fitness, ESS decisions,
        and the combined update."""
        es_std = _make()
        es_dec = _make(decomposed=True)
        es_std.train(5, verbose=False)
        es_dec.train(5, verbose=False)
        assert ([r["reused_prev"] for r in es_std.history]
                == [r["reused_prev"] for r in es_dec.history])
        np.testing.assert_allclose(
            np.asarray(es_std.state.params_flat),
            np.asarray(es_dec.state.params_flat),
            rtol=0, atol=1e-6,
        )

    @pytest.mark.slow
    def test_never_reusing_warns_once_with_heuristic(self):
        """20+ consecutive ESS rejections → one RuntimeWarning naming the
        lr ≲ σ/√dim fix; reuse-friendly runs stay silent."""
        import warnings

        es = _make(optimizer_kwargs={"learning_rate": 5.0})
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            es.train(IW_ES.DRY_WARN_AFTER + 3, verbose=False)
        msgs = [w for w in caught if issubclass(w.category, RuntimeWarning)
                and "ESS guard" in str(w.message)]
        assert len(msgs) == 1, [str(w.message) for w in caught]
        assert "sigma/sqrt(dim)" in str(msgs[0].message)
        assert not any(r["reused_prev"] for r in es.history)

        es2 = _make()  # tame lr: reuses, so no warning even over many gens
        with warnings.catch_warnings(record=True) as caught2:
            warnings.simplefilter("always")
            es2.train(IW_ES.DRY_WARN_AFTER + 3, verbose=False)
        assert not [w for w in caught2
                    if issubclass(w.category, RuntimeWarning)
                    and "ESS guard" in str(w.message)]
        assert any(r["reused_prev"] for r in es2.history)

    @pytest.mark.slow
    def test_multi_generation_window(self):
        """reuse_window=3: the ring fills, multiple generations are admitted
        once moves settle, and effective_samples scales with reused_gens."""
        es = _make(reuse_window=3)
        es.train(12, verbose=False)
        gens = [r["reused_gens"] for r in es.history]
        assert max(gens) >= 2, gens  # at least one update used 2+ old gens
        for r in es.history:
            assert r["effective_samples"] == 16 * (1 + r["reused_gens"])
        assert np.isfinite(es.history[-1]["reward_mean"])

    @pytest.mark.slow
    def test_window_mesh_invariance(self):
        from estorch_tpu.parallel.mesh import population_mesh

        es8 = _make(reuse_window=2)
        es1 = _make(reuse_window=2, mesh=population_mesh(jax.devices()[:1]))
        es8.train(4, verbose=False)
        es1.train(4, verbose=False)
        np.testing.assert_allclose(
            np.asarray(es8.state.params_flat),
            np.asarray(es1.state.params_flat),
            rtol=0, atol=1e-6,
        )

    def test_records_have_iw_fields(self):
        es = _make()
        es.train(2, verbose=False)
        r0, r1 = es.history
        assert r0["reused_prev"] is False  # nothing to reuse at gen 0
        assert r0["effective_samples"] == 16
        assert "ess" in r1

    def test_mesh_invariance(self):
        from estorch_tpu.parallel.mesh import population_mesh

        es8 = _make()
        es1 = _make(mesh=population_mesh(jax.devices()[:1]))
        es8.train(3, verbose=False)
        es1.train(3, verbose=False)
        np.testing.assert_allclose(
            np.asarray(es8.state.params_flat),
            np.asarray(es1.state.params_flat),
            rtol=0, atol=1e-6,
        )

    def test_unmirrored(self):
        es = _make(mirrored=False)
        es.train(3, verbose=False)
        assert np.isfinite(es.history[-1]["reward_mean"])

    def test_rejected_combinations(self):
        with pytest.raises(ValueError, match="low_rank"):
            _make(low_rank=1)
        import torch

        class P(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = torch.nn.Linear(2, 2)

            def forward(self, x):
                return self.lin(x)

        class A:
            def rollout(self, policy):
                return 0.0

        with pytest.raises(ValueError, match="device"):
            IW_ES(P, A, torch.optim.Adam, population_size=4)


class TestLearnability:
    def test_cartpole_improves(self):
        """Learnability and reuse are naturally antagonistic (fast learning
        = big center moves = collapsed λ, the guard correctly disables
        reuse) — so this asserts improvement only; reuse firing is pinned
        by test_ess_guard_falls_back_to_vanilla's small-step regime."""
        es = _make(n_pop=32, seed=0,
                   agent_kwargs={"env": CartPole(), "horizon": 200},
                   optimizer_kwargs={"learning_rate": 3e-2})
        es.train(12, verbose=False)
        first = es.history[0]["reward_mean"]
        best = max(r["reward_mean"] for r in es.history)
        assert best > first + 40.0, (first, best)
