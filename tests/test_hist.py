"""Tail-latency truth (estorch_tpu/obs/hist.py + the layers over it).

Anchors: the streaming histogram's exact-small-N/bucket quantile
contract and its documented error bound, merge/composition exactness
(the cross-restart story), the true-histogram Prometheus round trip,
the ``obs regress --tail`` gate flagging a median-invisible p99
regression NAMING the quantile and the endpoint/phase, and the causal
trace layer: async records carry dispatch→fold identity that ``obs
trace`` renders as Perfetto flow arrows — proven against a REAL
straggler-chaos ``train_async`` run, not just synthetic records.
"""

import json
import math
import os
import threading
import time

import numpy as np
import pytest
import torch

from estorch_tpu import ES
from estorch_tpu.obs.export.prometheus import (histogram_series,
                                               parse_exposition,
                                               render_exposition,
                                               samples_by_name,
                                               validate_histogram_series)
from estorch_tpu.obs.export.regress import (compare_phases, compare_tail,
                                            compare_tail_files,
                                            tail_selfcheck)
from estorch_tpu.obs.export.sidecar import (MetricsSidecar, compose_hists,
                                            publish_counters)
from estorch_tpu.obs.export.traceevent import export_trace, validate_trace
from estorch_tpu.obs.hist import (Histogram, Histograms, NullHistograms,
                                  merge_snapshots)
from estorch_tpu.obs.hist import selfcheck as hist_selfcheck
from estorch_tpu.obs.spans import Telemetry
from estorch_tpu.resilience.chaos import CHAOS_ENV, ChaosPlan, reset_cache


# =====================================================================
# the histogram itself
# =====================================================================

class TestHistogram:
    def test_exact_small_n_quantiles(self):
        h = Histogram()
        vals = [0.003, 0.001, 0.010, 0.002, 0.500]
        for v in vals:
            h.observe(v)
        s = sorted(vals)
        assert h.quantile(0.5) == s[math.ceil(0.5 * 5) - 1]
        assert h.quantile(0.99) == s[-1]
        assert h.count == 5
        assert h.sum == pytest.approx(sum(vals))

    def test_bucket_path_within_documented_bound(self):
        import random

        rng = random.Random(7)
        vals = [rng.expovariate(1 / 0.02) for _ in range(4000)]
        h = Histogram()
        for v in vals:
            h.observe(v)
        s = sorted(vals)
        bound = h.quantile_error_bound()
        for q in (0.5, 0.95, 0.99):
            exact = s[math.ceil(q * len(s)) - 1]
            assert abs(h.quantile(q) - exact) / exact <= bound

    def test_le_edge_lands_in_its_bucket(self):
        h = Histogram(lo=1e-3, decades=3, per_decade=1)
        # bounds: 1e-3, 1e-2, 1e-1, 1e0; v == bound(k) must land in
        # bucket k (le semantics), not k+1
        h.observe(1e-2)
        assert h._counts[1] == 1
        h.observe(1e-2 * 1.0001)
        assert h._counts[2] == 1

    def test_under_and_overflow(self):
        # exact_cap=0 forces the bucket path so the ladder's edge
        # behavior (not the exact list) is what's under test
        h = Histogram(lo=1e-3, decades=2, per_decade=2, exact_cap=0)
        h.observe(0.0)      # underflow
        h.observe(-1.0)     # clamped into underflow, still counted
        h.observe(5.0)      # past the top edge: +Inf bucket
        assert h._counts[0] == 2
        assert h._counts[-1] == 1
        assert h.count == 3
        # overflow quantile returns the top edge (documented underestimate)
        assert h.quantile(1.0) == pytest.approx(h.bound(h.n))
        # underflow quantile sits just below lo
        assert h.quantile(0.5) < h.lo

    def test_nonfinite_observations_dropped(self):
        h = Histogram()
        h.observe(float("nan"))
        h.observe(float("inf"))
        assert h.count == 0 and math.isnan(h.quantile(0.5))

    def test_weighted_observe(self):
        h = Histogram()
        h.observe(0.004, n=16)
        assert h.count == 16
        assert h.sum == pytest.approx(0.004 * 16)
        assert h.quantile(0.99) == 0.004

    def test_merge_equals_all_at_once_and_raises_on_mismatch(self):
        import random

        rng = random.Random(1)
        vals = [rng.uniform(1e-4, 1.0) for _ in range(900)]
        whole = Histogram()
        parts = [Histogram() for _ in range(3)]
        for i, v in enumerate(vals):
            whole.observe(v)
            parts[i % 3].observe(v)
        merged = parts[0].merge(parts[1]).merge(parts[2])
        assert merged._counts == whole._counts
        assert merged.count == whole.count
        assert merged.quantile(0.99) == whole.quantile(0.99)
        with pytest.raises(ValueError, match="ladder mismatch"):
            Histogram(lo=1e-3).merge(Histogram(lo=1e-5))

    def test_dict_round_trip_through_json(self):
        h = Histogram()
        for v in (0.001, 0.02, 0.3, 40.0):
            h.observe(v)
        back = Histogram.from_dict(json.loads(json.dumps(h.to_dict())))
        assert back._counts == h._counts
        assert back.count == h.count
        assert back.quantile(0.95) == h.quantile(0.95)

    def test_thread_safety_counts_exact(self):
        h = Histogram()

        def pump(seed):
            for i in range(1000):
                h.observe(1e-4 * (seed + 1) * (1 + i % 7))

        threads = [threading.Thread(target=pump, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count == 4000
        assert sum(h._counts) == 4000

    def test_selfcheck_clean(self):
        assert hist_selfcheck(render=render_exposition,
                              parse=parse_exposition) == []


class TestRegistryAndHub:
    def test_registry_observe_and_quantile(self):
        hs = Histograms()
        for v in (0.001, 0.002, 0.003):
            hs.observe("serve/request_s", v)
        assert hs.quantile("serve/request_s", 0.5) == 0.002
        assert hs.quantile("missing", 0.5) is None
        assert list(hs.snapshot()) == ["serve/request_s"]
        exp = hs.export()["serve/request_s"]
        assert exp["count"] == 3 and math.isinf(exp["buckets"][-1][0])

    def test_null_registry_and_disabled_hub_swallow(self):
        null = NullHistograms()
        null.observe("x", 1.0)
        assert null.snapshot() == {}
        tel = Telemetry(enabled=False)
        tel.observe("serve/request_s", 0.5)
        with tel.phase("eval"):
            pass
        assert tel.hists.snapshot() == {}

    def test_enabled_hub_histograms_every_phase(self):
        """The per-phase duration DISTRIBUTION rides the span machinery:
        every phase() observes into phase/<name> for free."""
        tel = Telemetry(enabled=True)
        for _ in range(3):
            with tel.phase("eval"):
                pass
            with tel.phase("update"):
                with tel.phase("obsnorm_merge"):
                    pass
        names = tel.hists.names()
        assert "phase/eval" in names
        assert "phase/update/obsnorm_merge" in names
        assert tel.hists.get("phase/eval").count == 3

    def test_trace_ctx_threads_ids_into_recorder(self):
        tel = Telemetry(enabled=True)
        with tel.trace_ctx("r42"):
            with tel.phase("eval"):
                pass
            tel.event("request_shed")
        evs = tel.recorder.events()
        assert any(e.get("trace") == "r42" and e["kind"] == "span"
                   for e in evs)
        assert any(e.get("trace") == "r42" and e["name"] == "request_shed"
                   for e in evs)
        # the id must not leak past the context
        with tel.phase("update"):
            pass
        assert "trace" not in tel.recorder.events()[-1]


# =====================================================================
# Prometheus histogram round trip + cross-restart composition
# =====================================================================

class TestExposition:
    def _hist(self, vals):
        h = Histogram()
        for v in vals:
            h.observe(v)
        return h

    def test_render_parse_validate_round_trip(self):
        h = self._hist([0.001, 0.004, 0.004, 2.0])
        body = render_exposition({"requests_total": 4}, None, up=True,
                                 histograms={"serve/request_s":
                                             h.to_export()})
        samples = parse_exposition(body)  # raises on malformed lines
        assert validate_histogram_series(samples) == []
        series = histogram_series(samples)["estorch_serve_request_s"]
        assert series["count"] == 4 and series["buckets"][-1][1] == 4
        assert series["sum"] == pytest.approx(2.009)
        # cumulative counts survive the zero-delta edge elision
        cums = [c for _, c in series["buckets"]]
        assert cums == sorted(cums)
        assert "# TYPE estorch_serve_request_s histogram" in body

    def test_validator_rejects_broken_series(self):
        h = self._hist([0.001])
        exp = h.to_export()
        exp["count"] = 5  # +Inf bucket no longer equals _count
        body = render_exposition({}, None, up=True,
                                 histograms={"lat": exp})
        problems = validate_histogram_series(parse_exposition(body))
        assert problems and "+Inf" in problems[0]

    def test_sidecar_composes_published_and_live(self, tmp_path):
        d = str(tmp_path)
        h_pub = self._hist([0.001, 0.002])
        h_live = self._hist([0.004])
        hb_ts = time.time()
        with open(os.path.join(d, "heartbeat.json"), "w") as f:
            json.dump({"ts": hb_ts, "pid": 1, "phase": "serving",
                       "generation": 0, "counters": {"env_steps": 1},
                       "hists": {"serve/request_s": h_live.to_dict()}}, f)
        publish_counters(d, {"env_steps": 2}, through_ts=hb_ts - 1.0,
                         hists={"serve/request_s": h_pub.to_dict()})
        sidecar = MetricsSidecar(d)
        try:
            body = sidecar.scrape()
        finally:
            sidecar.close()
        samples = parse_exposition(body)
        assert validate_histogram_series(samples) == []
        vals = samples_by_name(samples)
        # published (2 obs) + newer live beat (1 obs) = 3, monotone
        assert vals["estorch_serve_request_s_count"] == 3
        assert vals["estorch_env_steps"] == 3

    def test_stale_beat_not_double_counted(self, tmp_path):
        """A beat at/older than through_ts is the buried child's final
        beat, already folded into the published totals."""
        d = str(tmp_path)
        h = self._hist([0.001])
        hb_ts = time.time()
        with open(os.path.join(d, "heartbeat.json"), "w") as f:
            json.dump({"ts": hb_ts, "pid": 1, "phase": "eval",
                       "generation": 3,
                       "hists": {"lat": h.to_dict()}}, f)
        publish_counters(d, {}, through_ts=hb_ts,
                         hists={"lat": h.to_dict()})
        composed = compose_hists(
            {"through_ts": hb_ts, "hists": {"lat": h.to_dict()}},
            {"ts": hb_ts, "hists": {"lat": h.to_dict()}})
        assert composed["lat"]["count"] == 1

    def test_merge_snapshots_degrades_on_ladder_mismatch(self):
        big = self._hist([0.001, 0.002, 0.003]).to_dict()
        odd = Histogram(lo=1e-2)
        odd.observe(0.5)
        out = merge_snapshots({"lat": big}, {"lat": odd.to_dict()})
        assert out["lat"]["count"] == 3  # bigger side kept, no crash


# =====================================================================
# merge edge cases the fleet store leans on (obs/agg/store.py windows
# are merge_snapshots folds over scraped snapshots)
# =====================================================================

class TestMergeEdgeCases:
    def _build(self, values):
        h = Histogram()
        for v in values:
            h.observe(v)
        return h

    def test_merging_an_empty_snapshot_is_identity(self):
        """A freshly-restarted process's first scrape carries a zero
        histogram; folding it in must change nothing — counts, sum, OR
        quantiles."""
        full = self._build([0.01, 0.02, 0.03])
        before = full.to_dict()
        out = merge_snapshots({"lat": before},
                              {"lat": Histogram().to_dict()})
        back = Histogram.from_dict(out["lat"])
        assert back.count == 3 and back.sum == full.sum
        assert back.quantile(0.99) == full.quantile(0.99)
        # and the mirror: empty total absorbs the snapshot verbatim
        out2 = merge_snapshots(None, {"lat": before})
        assert Histogram.from_dict(out2["lat"]).count == 3
        # empty-vs-empty composes to an empty histogram, not a crash
        out3 = merge_snapshots({"lat": Histogram().to_dict()},
                               {"lat": Histogram().to_dict()})
        assert Histogram.from_dict(out3["lat"]).count == 0

    def test_exact_mode_merged_with_ladder_mode_across_restart(self):
        """Cross-restart composition where one incarnation died young
        (count <= exact_cap: raw samples still attached) and the other
        lived past the cap (ladder-only): the merge must drop to the
        ladder path with EXACT combined counts, and its quantiles must
        equal the all-at-once histogram's (which took the same
        ladder path)."""
        import random

        rng = random.Random(7)
        young = [rng.expovariate(1 / 0.01) for _ in range(50)]
        old = [rng.expovariate(1 / 0.01) for _ in range(2000)]
        h_young, h_old = self._build(young), self._build(old)
        assert h_young._exact is not None  # raw list survives
        assert h_old._exact is None  # past the cap
        composed = merge_snapshots({"lat": h_young.to_dict()},
                                   {"lat": h_old.to_dict()})
        back = Histogram.from_dict(composed["lat"])
        assert back._exact is None
        assert back.count == 2050
        both = self._build(young + old)
        assert back._counts == both._counts
        for q in (0.5, 0.95, 0.99):
            assert back.quantile(q) == both.quantile(q)
        # order must not matter (the store folds in scrape order, the
        # supervisor in death order)
        flipped = merge_snapshots({"lat": h_old.to_dict()},
                                  {"lat": self._build(young).to_dict()})
        assert Histogram.from_dict(flipped["lat"])._counts == back._counts

    def test_quantile_stability_after_many_segment_recomposition(self):
        """The store recomposes windows from MANY segments; 40 sequential
        JSON-round-tripped folds must reproduce the all-at-once
        histogram bit-for-bit (associativity is the contract) and stay
        inside the documented error bound of the offline exact
        quantiles."""
        import math
        import random

        rng = random.Random(11)
        values = [rng.expovariate(1 / 0.02) for _ in range(5000)]
        total = None
        for i in range(40):
            chunk = values[i::40]
            snap = {"lat": self._build(chunk).to_dict()}
            total = merge_snapshots(
                total, json.loads(json.dumps(snap, default=float)))
        back = Histogram.from_dict(total["lat"])
        whole = self._build(values)
        assert back._counts == whole._counts and back.count == 5000
        s = sorted(values)
        bound = whole.quantile_error_bound()
        for q in (0.5, 0.99):
            assert back.quantile(q) == whole.quantile(q)
            exact = s[max(1, math.ceil(q * len(s))) - 1]
            assert abs(back.quantile(q) - exact) <= exact * bound

    def test_snapshot_from_export_round_trip_and_foreign_ladder(self):
        """The collector only ever sees the text exposition; rebuilding
        the snapshot from cumulative (le, count) pairs must reproduce
        the ladder counts exactly, and a foreign ladder must yield None
        (degrade), never a resampled fake."""
        from estorch_tpu.obs.hist import snapshot_from_export

        h = self._build([0.001, 0.01, 0.01, 0.1, 5.0])
        snap = snapshot_from_export(h.to_export())
        back = Histogram.from_dict(snap)
        assert back._counts == h._counts
        assert back.count == h.count and back.sum == h.sum
        assert back.quantile(0.99) == \
            Histogram.from_dict(h.to_dict(compact=True)).quantile(0.99)
        foreign = {"buckets": [(0.00123, 2), (float("inf"), 2)],
                   "sum": 0.002, "count": 2}
        assert snapshot_from_export(foreign) is None


# =====================================================================
# the tail gate (obs regress --tail)
# =====================================================================

class TestTailGate:
    def _latency_rows(self, seed, n=1500, slow_every=0):
        import random

        rng = random.Random(seed)
        rows = []
        for i in range(n):
            v = 0.008 * (1.0 + rng.uniform(-0.03, 0.03))
            if slow_every and i % slow_every == 0:
                v *= 5.0
            rows.append({"endpoint": "/predict", "latency_s": v})
        return rows

    def test_median_clean_p99_regressed_flagged_with_names(self, tmp_path):
        """THE acceptance demo: a 5x slowdown on ~1% of requests passes
        every median verdict but is flagged at p99, naming the quantile
        and the endpoint."""
        base = tmp_path / "base.jsonl"
        cur = tmp_path / "cur.jsonl"
        # slow_every=80 → 1.25% of requests: nearest-rank p99 needs the
        # tail fraction to EXCEED 1% before the rank lands in it
        for path, rows in ((base, self._latency_rows(0)),
                           (cur, self._latency_rows(1, slow_every=80))):
            path.write_text("".join(json.dumps(r) + "\n" for r in rows))
        v = compare_tail_files(str(cur), str(base))
        assert v["verdict"] == "regress"
        assert v["regressed_groups"] == ["/predict"]
        assert v["quantile"] == "p99"
        g = v["groups"]["/predict"]
        assert g["median_verdict"] == "pass"
        assert g["slowdown_pct"] > 100

    def test_clean_rerun_passes(self):
        v = compare_tail(self._latency_rows(2), self._latency_rows(3))
        assert v["verdict"] == "pass"

    def test_phase_tail_named_while_median_gate_passes(self):
        import random

        def run(seed, slow_every=0):
            rng = random.Random(seed)
            rows = []
            for g in range(100):
                ev = 0.1 * (1 + rng.uniform(-0.02, 0.02))
                if slow_every and g % slow_every == 0:
                    ev *= 5
                rows.append({"generation": g, "wall_time_s": ev + 0.02,
                             "env_steps_per_sec": 1e3,
                             "phases": {"eval": ev, "update": 0.02}})
            return rows

        base, cur = run(4), run(5, slow_every=50)
        assert compare_phases(cur, base)["verdict"] == "pass"
        tail = compare_tail(cur, base)
        assert "eval" in tail["regressed_groups"]
        assert "update" not in tail["regressed_groups"]

    def test_no_shared_groups_is_an_error(self):
        with pytest.raises(ValueError, match="no shared tail groups"):
            compare_tail([{"latency_s": 0.1, "endpoint": "/a"}],
                         [{"latency_s": 0.1, "endpoint": "/b"}])

    def test_selfcheck_clean(self):
        assert tail_selfcheck() == []

    def test_cli_exit_codes(self, tmp_path, capsys):
        from estorch_tpu.obs.__main__ import main

        base = tmp_path / "base.jsonl"
        cur = tmp_path / "cur.jsonl"
        for path, rows in ((base, self._latency_rows(6)),
                           (cur, self._latency_rows(7, slow_every=80))):
            path.write_text("".join(json.dumps(r) + "\n" for r in rows))
        assert main(["regress", str(cur), "--baseline", str(base),
                     "--tail"]) == 1
        out = capsys.readouterr().out
        assert "p99" in out and "/predict" in out
        assert main(["regress", str(base), "--baseline", str(base),
                     "--tail"]) == 0
        # --tail cannot combine with --phases / --label
        assert main(["regress", str(cur), "--baseline", str(base),
                     "--tail", "--phases"]) == 3


# =====================================================================
# causal flow arrows (obs trace) — synthetic and REAL async runs
# =====================================================================

def _flow_events(trace, ph):
    return [e for e in trace["traceEvents"] if e["ph"] == ph]


class TestFlowArrows:
    def _record(self, g, async_block):
        return {"generation": g, "reward_max": 0.0, "reward_mean": 0.0,
                "best_reward": 0.0, "env_steps": 100,
                "env_steps_per_sec": 1e3, "wall_time_s": 0.1,
                "phases": {"eval": 0.08, "update": 0.02},
                "async": async_block}

    def test_dispatch_fold_discard_arrows(self):
        records = [
            self._record(0, {"consumed": 8, "fresh": 8, "folded": 0,
                             "stale_discarded": 0,
                             "dispatches": [0, 1],
                             "consumed_dispatches": [[0, 8]],
                             "discarded_dispatches": []}),
            self._record(1, {"consumed": 8, "fresh": 5, "folded": 3,
                             "stale_discarded": 2,
                             "dispatches": [2],
                             "consumed_dispatches": [[1, 3], [2, 5]],
                             "discarded_dispatches": [[0, 2]]}),
        ]
        trace = export_trace(records)
        assert validate_trace(trace) == []
        starts = _flow_events(trace, "s")
        finishes = _flow_events(trace, "f")
        assert {e["id"] for e in starts} == {0, 1, 2}
        # dispatch 0 is touched twice (fold in u0, discard in u1): the
        # LAST touch is the finish, the earlier one a step
        steps = _flow_events(trace, "t")
        assert any(e["id"] == 0 for e in steps)
        assert {e["id"] for e in finishes} == {0, 1, 2}
        names = [e["name"] for e in trace["traceEvents"]]
        assert any(n.startswith("fold d2") for n in names)
        assert any(n.startswith("discard d0") for n in names)

    def test_sync_records_grow_no_flow_lane(self):
        rec = self._record(0, None)
        del rec["async"]
        trace = export_trace([rec])
        assert validate_trace(trace) == []
        assert not _flow_events(trace, "s")
        assert all("async" not in e.get("args", {}).get("name", "")
                   for e in trace["traceEvents"] if e["ph"] == "M")


class _TinyPolicy(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.Tanh(), torch.nn.Linear(8, 2))

    def forward(self, x):
        return self.net(x)


class _QuadAgent:
    def rollout(self, policy):
        with torch.no_grad():
            v = torch.nn.utils.parameters_to_vector(policy.parameters())
            r = -float((v ** 2).sum())
        self.last_episode_steps = 1
        return r


class TestAsyncStragglerE2E:
    def test_straggler_run_traces_and_tails(self):
        """THE async acceptance demo: a straggler-chaos train_async run
        yields records whose causal identity renders as >=1 validated
        flow arrow linking a dispatch to the update that folded it, and
        the hub's lifecycle histograms carry the tail facts."""
        os.environ[CHAOS_ENV] = ChaosPlan(events=[
            {"kind": "straggler", "gen": 1, "member": 2, "sleep_s": 0.2},
        ]).to_json()
        reset_cache()
        try:
            es = ES(_TinyPolicy, _QuadAgent, torch.optim.Adam,
                    population_size=8, sigma=0.05, seed=0,
                    optimizer_kwargs={"lr": 0.05}, table_size=1 << 12,
                    telemetry=True)
            records = []
            es.train_async(4, n_proc=2, verbose=False,
                           log_fn=records.append)
        finally:
            os.environ.pop(CHAOS_ENV, None)
            reset_cache()
        assert len(records) == 4
        blocks = [r["async"] for r in records]
        # every update names the dispatches it consumed, and the union
        # of consumed+discarded covers what was dispatched
        assert all(b.get("consumed_dispatches") for b in blocks)
        dispatched = {d for b in blocks for d in b.get("dispatches", [])}
        consumed = {d for b in blocks
                    for d, _n in b.get("consumed_dispatches", [])}
        assert consumed & dispatched
        # the straggler forces at least one stale fold or discard
        assert (sum(b["folded"] for b in blocks) > 0
                or sum(b["stale_discarded"] for b in blocks) > 0)
        # queue-wait/staleness quantiles surfaced for obs summarize
        last = blocks[-1]
        assert last.get("queue_wait_s", {}).get("p99", 0) >= \
            last.get("queue_wait_s", {}).get("p50", 0)
        # hub lifecycle histograms populated
        names = es.obs.hists.names()
        for name in ("async/eval_s", "async/queue_wait_s",
                     "async/staleness", "async/fold_latency_s"):
            assert name in names, names
        # the straggler's 0.2s sleep lands in the eval_s tail
        assert es.obs.hists.get("async/eval_s").quantile(1.0) >= 0.2
        # trace export: validated, with >=1 complete dispatch→fold arrow
        # (via JSON, the CLI-equivalent path)
        records = json.loads(json.dumps(records, default=float))
        trace = export_trace(records)
        assert validate_trace(trace) == []
        assert _flow_events(trace, "s") and _flow_events(trace, "f")
        # the dispatch's trace id threads through the flight recorder:
        # dispatch event and its fold-side span family share "d<N>"
        traces = {e.get("trace") for e in es.obs.recorder.events()
                  if e.get("trace")}
        assert any(t.startswith("d") for t in traces)


# =====================================================================
# serve lifecycle histograms (batcher-level; HTTP honesty lives in
# tests/test_serve.py where a real bundle/server exists)
# =====================================================================

class TestServeLifecycleHists:
    def test_batcher_populates_lifecycle_histograms(self):
        from estorch_tpu.serve.batcher import DynamicBatcher

        tel = Telemetry(enabled=True)
        batcher = DynamicBatcher(
            lambda arr: arr * 2.0, (2,), max_batch=4, max_wait_ms=1.0,
            telemetry=tel, verify=True)
        try:
            for i in range(20):
                batcher.predict(np.full(2, i, np.float32),
                                trace=f"r{i}")
        finally:
            batcher.close()
        names = tel.hists.names()
        for name in ("serve/queue_wait_s", "serve/coalesce_wait_s",
                     "serve/compute_s", "serve/request_s"):
            assert name in names, names
        # request_s >= its parts, and counts line up with requests
        # (compute_s is n-weighted per coalesced request)
        assert tel.hists.get("serve/request_s").count == 20
        assert tel.hists.get("serve/compute_s").count == 20
        assert batcher.stats()["request_ms"]["p99"] >= \
            batcher.stats()["request_ms"]["p50"]
        # trace ids rode the recorder's batch_dispatch events
        evs = [e for e in tel.recorder.events()
               if e["name"] == "batch_dispatch"]
        assert evs and all(e.get("traces") for e in evs)
