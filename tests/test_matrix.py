"""Cross-backend × algorithm integration matrix.

Every algorithm on every backend, two generations each — the seams where
integration breaks hide (novelty-on-pooled, NSRA-on-host, gym-pool
variants). Asserts the contract every combination must honor: records
complete, fitness finite, state advances, novelty bookkeeping consistent.
"""

import numpy as np
import optax
import pytest
import torch

from estorch_tpu import ES, NS_ES, NSR_ES, NSRA_ES, JaxAgent, MLPPolicy, PooledAgent
from estorch_tpu.envs import CartPole

ALGOS = {
    "ES": (ES, {}),
    "NS_ES": (NS_ES, {"meta_population_size": 2, "k": 3}),
    "NSR_ES": (NSR_ES, {"meta_population_size": 2, "k": 3}),
    "NSRA_ES": (NSRA_ES, {"meta_population_size": 2, "k": 3, "weight": 0.7}),
}


class _TorchMLP(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.Tanh(), torch.nn.Linear(8, 2)
        )

    def forward(self, x):
        return self.net(x)


class _QuadAgent:
    def rollout(self, policy):
        with torch.no_grad():
            v = torch.nn.utils.parameters_to_vector(policy.parameters())
            r = -float(((v - 0.1) ** 2).sum())
        self.last_episode_steps = 1
        return r, v[:2].numpy()


BACKENDS = {
    "device": dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        policy_kwargs={"action_dim": 2, "hidden": (8,)},
        agent_kwargs={"env": CartPole(), "horizon": 30},
        optimizer_kwargs={"learning_rate": 1e-2},
    ),
    "pooled-native": dict(
        policy=MLPPolicy,
        agent=PooledAgent,
        optimizer=optax.adam,
        policy_kwargs={"action_dim": 2, "hidden": (8,)},
        agent_kwargs={"env_name": "cartpole", "horizon": 30},
        optimizer_kwargs={"learning_rate": 1e-2},
    ),
    "pooled-gym": dict(
        policy=MLPPolicy,
        agent=PooledAgent,
        optimizer=optax.adam,
        policy_kwargs={"action_dim": 2, "hidden": (8,)},
        agent_kwargs={"env_name": "gym:CartPole-v1", "horizon": 30},
        optimizer_kwargs={"learning_rate": 1e-2},
    ),
    "host": dict(
        policy=_TorchMLP,
        agent=_QuadAgent,
        optimizer=torch.optim.Adam,
        optimizer_kwargs={"lr": 1e-2},
    ),
}


@pytest.mark.parametrize("backend", sorted(BACKENDS))
@pytest.mark.parametrize("algo", sorted(ALGOS))
def test_algo_backend_combination(backend, algo):
    cls, extra = ALGOS[algo]
    kw = dict(BACKENDS[backend])
    kw.update(extra)
    es = cls(population_size=16, sigma=0.05, seed=0, table_size=1 << 14, **kw)
    es.train(2, verbose=False)

    assert len(es.history) == 2
    for rec in es.history:
        assert np.isfinite(rec["reward_mean"])
        assert np.isfinite(rec["grad_norm"])
    assert es.generation == 2
    if algo != "ES":
        # archive: meta seeds + one BC per generation; meta states intact
        assert len(es.archive) == 2 + 2
        assert len(es.meta_states) == 2
        assert "novelty_mean" in es.history[-1]
    if algo == "NSRA_ES":
        assert 0.0 <= es.history[-1]["nsra_weight"] <= 1.0
    if backend.startswith("pooled"):
        es.engine.pool.close()
        es.engine.center_pool.close()


@pytest.mark.parametrize("backend", sorted(BACKENDS))
def test_unmirrored_backends(backend):
    """mirrored=False (the reference's plain per-member sampling) must run
    on every backend — round-1 VERDICT next-round #7."""
    kw = dict(BACKENDS[backend])
    es = ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
            mirrored=False, **kw)
    es.train(2, verbose=False)
    assert len(es.history) == 2
    for rec in es.history:
        assert np.isfinite(rec["reward_mean"])
        assert np.isfinite(rec["grad_norm"])
    if backend.startswith("pooled"):
        es.engine.pool.close()
        es.engine.center_pool.close()


@pytest.mark.parametrize("backend", sorted(BACKENDS))
def test_sigma_decay_backends(backend):
    """sigma_decay anneals identically on every backend."""
    kw = dict(BACKENDS[backend])
    es = ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
            sigma_decay=0.5, sigma_min=0.02, **kw)
    es.train(2, verbose=False)
    assert es.history[0]["sigma"] == pytest.approx(0.05)
    assert es.history[1]["sigma"] == pytest.approx(0.025)
    sig = float(np.asarray(es.state.sigma))
    assert sig == pytest.approx(0.02)  # floored
    if backend.startswith("pooled"):
        es.engine.pool.close()
        es.engine.center_pool.close()


@pytest.mark.parametrize("backend", ["device", "pooled-native"])
def test_bf16_compute_dtype_backends(backend):
    """bf16 responsibility is split between engine.py (obs/output shim) and
    the param-cast at each builder (engine._member_cast / pooled
    materialize) — lock in that both halves stay wired on both backends."""
    kw = dict(BACKENDS[backend])
    es = ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
            compute_dtype="bfloat16", **kw)
    es.train(2, verbose=False)
    assert len(es.history) == 2
    for rec in es.history:
        assert np.isfinite(rec["reward_mean"])
    assert str(es.state.params_flat.dtype) == "float32"  # master stays f32
    if backend.startswith("pooled"):
        es.engine.pool.close()
        es.engine.center_pool.close()


def test_iwes_in_algo_matrix_on_device():
    """IW_ES honors the same record/state contract as the other algorithms
    on its (only) backend."""
    from estorch_tpu import IW_ES

    kw = dict(BACKENDS["device"])
    es = IW_ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14, **kw)
    es.train(2, verbose=False)
    assert len(es.history) == 2
    for rec in es.history:
        assert np.isfinite(rec["reward_mean"])
        assert np.isfinite(rec["grad_norm"])
        assert "reused_prev" in rec and "ess" in rec
    assert es.generation == 2


@pytest.mark.parametrize("mode", ["decomposed", "low_rank", "streamed"])
def test_engine_modes_run_all_algorithms(mode):
    """Every device forward mode composes with the novelty family (they all
    share _eval_local), not just vanilla ES."""
    over = {"decomposed": dict(decomposed=True),
            "low_rank": dict(low_rank=1),
            "streamed": dict(streamed=True)}[mode]
    from estorch_tpu import NSR_ES

    kw = dict(BACKENDS["device"])
    es = NSR_ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
                meta_population_size=2, k=3, **kw, **over)
    es.train(2, verbose=False)
    assert len(es.history) == 2
    assert np.isfinite(es.history[-1]["reward_mean"])


@pytest.mark.parametrize("mode", ["obs_norm", "recurrent"])
def test_round3_modes_run_novelty_family(mode):
    """obs_norm and recurrent policies compose with the novelty family's
    split path (stats refresh / carry threading live below _eval_local and
    apply_weights, which NS/NSR/NSRA share with vanilla ES)."""
    from estorch_tpu import NSR_ES, RecurrentPolicy

    kw = dict(BACKENDS["device"])
    over = {}
    if mode == "obs_norm":
        over["obs_norm"] = True
    else:
        kw["policy"] = RecurrentPolicy
        kw["policy_kwargs"] = {"action_dim": 2, "hidden": (8,),
                               "gru_size": 8}
    es = NSR_ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
                meta_population_size=2, k=3, **kw, **over)
    es.train(2, verbose=False)
    assert len(es.history) == 2
    assert np.isfinite(es.history[-1]["reward_mean"])
    if mode == "obs_norm":
        for st in es.meta_states:
            assert st.obs_stats is not None


def test_iwes_rejects_obs_norm():
    """Buffered generations' fitness was measured under older running
    stats — the density ratio's fixed-f(θ) assumption breaks, so the
    combination must fail loudly, not bias silently."""
    from estorch_tpu import IW_ES

    kw = dict(BACKENDS["device"])
    with pytest.raises(ValueError, match="obs_norm"):
        IW_ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
              obs_norm=True, **kw)


def test_iwes_recurrent_composes():
    """IW_ES's density-ratio reuse involves only params/noise/fitness —
    forward-shape agnostic, so the recurrent standard forward composes."""
    from estorch_tpu import IW_ES, RecurrentPolicy

    kw = dict(BACKENDS["device"])
    kw["policy"] = RecurrentPolicy
    kw["policy_kwargs"] = {"action_dim": 2, "hidden": (8,), "gru_size": 8}
    es = IW_ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
               **kw)
    es.train(2, verbose=False)
    assert np.isfinite(es.history[-1]["reward_mean"])
    assert "reused_prev" in es.history[-1]


def test_recurrent_lowrank_runs_novelty_family():
    """Round-5 composition: factored noise over the recurrent tree
    (per-episode materialization) lives below _eval_local/_local_grad,
    which the novelty family shares with vanilla ES."""
    from estorch_tpu import NSR_ES, RecurrentPolicy

    kw = dict(BACKENDS["device"])
    kw["policy"] = RecurrentPolicy
    kw["policy_kwargs"] = {"action_dim": 2, "hidden": (8,), "gru_size": 8}
    es = NSR_ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
                meta_population_size=2, k=3, low_rank=1, **kw)
    es.train(2, verbose=False)
    assert np.isfinite(es.history[-1]["reward_mean"])


def test_iwes_rejects_low_rank_as_ill_posed():
    """IW reuse under low_rank is not pending work — the drifted reused
    perturbation generally has no rank-r preimage, so no factor-space
    importance ratio exists; the combination must fail loudly."""
    from estorch_tpu import IW_ES

    kw = dict(BACKENDS["device"])
    with pytest.raises(ValueError, match="ill-posed"):
        IW_ES(population_size=16, sigma=0.05, seed=0, table_size=1 << 14,
              low_rank=1, **kw)
