"""Benchmark: device-native ES generation throughput on the flagship config.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: env-steps/sec/chip (BASELINE.json primary metric) for a full ES
generation — noise-table perturbation, vmapped policy rollouts, centered
ranks, psum'd rank-weighted update — on Pendulum (never terminates, so every
scanned step is a real env step; no done-mask inflation) with a 64x64 MLP,
population 4096, horizon 200: ~819k env steps per generation.

vs_baseline: ratio against a reference-style estorch loop measured live on
this host — per-member Python loop, torch CPU MLP forward per step,
gymnasium Pendulum env.step — the architecture SURVEY.md §3.2/§3.3 documents
(single process; the reference scales it by n_proc workers, so divide by
core count for a per-core figure if comparing to the 720-core runs).
"""

import json
import subprocess
import sys
import time

import numpy as np




def measure_tpu(population=4096, horizon=200, gens=5, force_cpu=False) -> tuple[float, str]:
    if force_cpu:
        from estorch_tpu.utils import force_cpu_backend

        force_cpu_backend(8)
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import Pendulum

    import jax

    on_tpu = not force_cpu and jax.devices()[0].platform == "tpu"
    es = ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=population,
        sigma=0.05,
        policy_kwargs={"action_dim": 1, "hidden": (64, 64), "discrete": False,
                       "action_scale": 2.0},
        agent_kwargs={"env": Pendulum(), "horizon": horizon},
        optimizer_kwargs={"learning_rate": 1e-2},
        eval_chunk=0,  # whole shard per vmap: +60% over chunked on CPU
        # bf16 policy compute on real TPU (MXU-native); CPU bf16 is emulated
        compute_dtype="bfloat16" if on_tpu else "float32",
    )
    es.train(1, verbose=False)  # warm-up generation (post-AOT sanity)
    t0 = time.perf_counter()
    es.train(gens, verbose=False)
    dt = time.perf_counter() - t0
    steps = sum(r["env_steps"] for r in es.history[-gens:])
    n_chips = es.mesh.devices.size
    platform = es.mesh.devices.flat[0].platform
    return steps / dt / n_chips, platform


def measure_reference_style_baseline(budget_s=6.0) -> float:
    """Single-process estorch-style loop: torch MLP + gymnasium Pendulum."""
    import gymnasium as gym
    import torch

    policy = torch.nn.Sequential(
        torch.nn.Linear(3, 64), torch.nn.Tanh(),
        torch.nn.Linear(64, 64), torch.nn.Tanh(),
        torch.nn.Linear(64, 1), torch.nn.Tanh(),
    )
    env = gym.make("Pendulum-v1")
    obs, _ = env.reset(seed=0)
    steps = 0
    t0 = time.perf_counter()
    with torch.no_grad():
        while time.perf_counter() - t0 < budget_s:
            for _ in range(200):
                a = policy(torch.from_numpy(np.asarray(obs, dtype=np.float32)))
                obs, r, term, trunc, _ = env.step(a.numpy() * 2.0)
                steps += 1
                if term or trunc:
                    obs, _ = env.reset()
    env.close()
    return steps / (time.perf_counter() - t0)


def _measure_tpu_subprocess(timeout_s: int = 480):
    """Run the TPU measurement in a child with a hard timeout — the tunnel
    can wedge at init OR mid-run, and bench must still emit its JSON line.
    Returns (rate, platform) or None; failure diagnostics go to OUR stderr
    (the JSON-line contract owns stdout only)."""
    try:
        r = subprocess.run(
            [sys.executable, __file__, "--stage-tpu"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        print(f"bench: TPU child timed out after {timeout_s}s (tunnel wedge?)",
              file=sys.stderr)
        return None
    if r.returncode != 0:
        print(f"bench: TPU child exited {r.returncode}; stderr tail:\n"
              f"{r.stderr[-2000:]}", file=sys.stderr)
        return None
    try:
        last = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")][-1]
        d = json.loads(last)
        return float(d["rate"]), str(d["platform"])
    except (IndexError, KeyError, ValueError):
        print(f"bench: TPU child output unparseable; stdout tail:\n"
              f"{r.stdout[-1000:]}\nstderr tail:\n{r.stderr[-1000:]}",
              file=sys.stderr)
        return None


def main():
    result = _measure_tpu_subprocess()
    if result is None:
        rate, platform = measure_tpu(force_cpu=True)
        fell_back = True
    else:
        rate, platform = result
        fell_back = False
    base_rate = measure_reference_style_baseline()
    unit = f"env-steps/s/chip (Pendulum MLP64x64 pop4096 h200, {platform}"
    unit += ", TPU-PATH-FAILED cpu fallback — see stderr)" if fell_back else ")"
    print(
        json.dumps(
            {
                "metric": "env_steps_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": unit,
                "vs_baseline": round(rate / base_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    if "--stage-tpu" in sys.argv:
        rate, platform = measure_tpu(force_cpu=False)
        print(json.dumps({"rate": rate, "platform": platform}))
    else:
        main()
